"""Benchmark: fused perception pipelines, frames/sec on one TPU chip.

Prints ONE JSON line (the driver's contract): the primary metric is the
YOLOv5n 512x512 fused end-to-end pipeline. Secondary metrics
(PointPillars 3D end-to-end) go to stderr and BENCH_LOCAL.json so
round-over-round history captures the whole surface without breaking
the one-line contract.

Methodology (BASELINE.md): the reference publishes no numbers; its
serving path is one blocking gRPC round-trip per frame to a remote
Triton GPU. The honest local anchor is real-time camera rate (30 fps) —
the rate the reference's ROS pipeline must sustain per stream
(sub_topic camera streams, SURVEY.md section 3.1). vs_baseline is
frames/sec/chip divided by that 30 fps anchor; BENCH history tracks
round-over-round gains.
"""

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

BATCH = 8
WARMUP = 5
ITERS = 100  # enough reps to smooth remote-chip tunnel jitter
CAMERA_FPS_BASELINE = 30.0
LIDAR_HZ_BASELINE = 10.0  # KITTI/nuScenes lidar scan rate


def bench_yolov5(dtype=None) -> dict:
    from triton_client_tpu.models.yolov5 import init_yolov5
    from triton_client_tpu.ops.detect_postprocess import extract_boxes
    from triton_client_tpu.ops.preprocess import normalize_image

    input_hw = (512, 512)
    model, variables = init_yolov5(
        jax.random.PRNGKey(0), num_classes=2, variant="n", input_hw=input_hw,
        dtype=dtype or jnp.float32,
    )

    @jax.jit
    def pipeline(variables, images):
        x = normalize_image(images, "yolo")
        pred = model.decode(model.apply(variables, x, train=False))
        return extract_boxes(pred, conf_thresh=0.3, iou_thresh=0.45)

    rng = np.random.default_rng(0)
    frames = jnp.asarray(
        rng.integers(0, 255, (BATCH, *input_hw, 3)).astype(np.float32)
    )

    for _ in range(WARMUP):
        out = pipeline(variables, frames)
    jax.block_until_ready(out)

    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = pipeline(variables, frames)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0

    fps = BATCH * ITERS / dt
    suffix = "_bf16" if dtype == jnp.bfloat16 else ""
    return {
        "metric": f"yolov5n_512{suffix}_e2e_frames_per_sec_per_chip",
        "value": round(fps, 2),
        "unit": "frames/sec",
        "vs_baseline": round(fps / CAMERA_FPS_BASELINE, 2),
    }


def _bench_3d_pipeline(pipeline, point_buckets, metric: str) -> dict:
    """Shared 3D-bench methodology (both lidar models): a ~KITTI-sized
    synthetic scan is padded and staged on device once, then the fused
    jit (voxel/scatter VFE -> CNN -> top-k decode -> rotated NMS) is
    timed back-to-back. Host-side bucketing/padding is ~0.4 ms/scan,
    measured separately; over the remote-chip tunnel used in CI,
    per-call host->device transfers would otherwise dominate and
    measure the tunnel, not the chip."""
    from triton_client_tpu.ops.voxelize import pad_points

    rng = np.random.default_rng(0)
    n_pts = 120_000  # ~KITTI velodyne scan
    pc_range = pipeline.model.cfg.voxel.point_cloud_range
    pts = np.empty((n_pts, 4), np.float32)
    pts[:, 0] = rng.uniform(pc_range[0], pc_range[3], n_pts)
    pts[:, 1] = rng.uniform(pc_range[1], pc_range[4], n_pts)
    pts[:, 2] = rng.uniform(pc_range[2], pc_range[5], n_pts)
    pts[:, 3] = rng.uniform(0, 1, n_pts)
    padded, m = pad_points(pts, max(point_buckets))
    pj, mj = jnp.asarray(padded), jnp.asarray(m)

    iters = max(10, ITERS // 3)
    for _ in range(WARMUP):
        out = pipeline._jit(pj, mj)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = pipeline._jit(pj, mj)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0

    fps = iters / dt
    return {
        "metric": metric,
        "value": round(fps, 2),
        "unit": "scans/sec",
        "vs_baseline": round(fps / LIDAR_HZ_BASELINE, 2),
    }


def bench_pointpillars() -> dict:
    """PointPillars end-to-end, KITTI grid (data/kitti_pointpillars.yaml)."""
    from triton_client_tpu.dataset_config import detect3d_from_yaml
    from triton_client_tpu.pipelines.detect3d import build_pointpillars_pipeline

    _, model_cfg, pipe_cfg = detect3d_from_yaml("data/kitti_pointpillars.yaml")
    pipeline, _, _ = build_pointpillars_pipeline(
        jax.random.PRNGKey(0), model_cfg=model_cfg, config=pipe_cfg
    )
    return _bench_3d_pipeline(
        pipeline,
        pipe_cfg.point_buckets,
        "pointpillars_kitti_e2e_scans_per_sec_per_chip",
    )


def bench_second() -> dict:
    """SECOND-IoU end-to-end (scatter mean VFE -> dense 3D middle
    encoder -> BEV backbone -> IoU-rectified decode -> rotated NMS)."""
    from triton_client_tpu.pipelines.detect3d import (
        Detect3DConfig,
        build_second_pipeline,
    )

    cfg = Detect3DConfig(model_name="second_iou")
    pipeline, _, _ = build_second_pipeline(jax.random.PRNGKey(0), config=cfg)
    return _bench_3d_pipeline(
        pipeline,
        cfg.point_buckets,
        "second_iou_kitti_e2e_scans_per_sec_per_chip",
    )


def main() -> None:
    primary = bench_yolov5()
    results = [primary]
    for label, secondary_fn in (
        ("yolov5n_bf16", lambda: bench_yolov5(dtype=jnp.bfloat16)),
        ("pointpillars", bench_pointpillars),
        ("second_iou", bench_second),
    ):
        try:
            results.append(secondary_fn())
        except Exception as e:  # secondary metrics must not break the contract
            print(f"{label} bench failed: {e}", file=sys.stderr)

    try:  # best-effort: the one-line stdout contract must survive
        with open("BENCH_LOCAL.json", "w") as f:
            json.dump(results, f, indent=2)
    except OSError as e:
        print(f"could not write BENCH_LOCAL.json: {e}", file=sys.stderr)
    for secondary in results[1:]:
        print(json.dumps(secondary), file=sys.stderr)
    print(json.dumps(primary))


if __name__ == "__main__":
    main()
