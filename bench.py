"""Benchmark: YOLOv5n fused pipeline frames/sec on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Methodology (BASELINE.md): the reference publishes no numbers; its
serving path is one blocking gRPC round-trip per frame to a remote
Triton GPU. The honest local anchor is real-time camera rate (30 fps) —
the rate the reference's ROS pipeline must sustain per stream
(sub_topic camera streams, SURVEY.md section 3.1). vs_baseline is
frames/sec/chip divided by that 30 fps anchor; BENCH history tracks
round-over-round gains.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

BATCH = 8
WARMUP = 3
ITERS = 30
CAMERA_FPS_BASELINE = 30.0


def main() -> None:
    from triton_client_tpu.models.yolov5 import init_yolov5
    from triton_client_tpu.ops.detect_postprocess import extract_boxes
    from triton_client_tpu.ops.preprocess import normalize_image

    input_hw = (512, 512)
    model, variables = init_yolov5(
        jax.random.PRNGKey(0), num_classes=2, variant="n", input_hw=input_hw
    )

    @jax.jit
    def pipeline(variables, images):
        x = normalize_image(images, "yolo")
        pred = model.decode(model.apply(variables, x, train=False))
        return extract_boxes(pred, conf_thresh=0.3, iou_thresh=0.45)

    rng = np.random.default_rng(0)
    frames = jnp.asarray(
        rng.integers(0, 255, (BATCH, *input_hw, 3)).astype(np.float32)
    )

    for _ in range(WARMUP):
        dets, valid = pipeline(variables, frames)
    jax.block_until_ready((dets, valid))

    t0 = time.perf_counter()
    for _ in range(ITERS):
        dets, valid = pipeline(variables, frames)
    jax.block_until_ready((dets, valid))
    dt = time.perf_counter() - t0

    fps = BATCH * ITERS / dt
    print(
        json.dumps(
            {
                "metric": "yolov5n_512_e2e_frames_per_sec_per_chip",
                "value": round(fps, 2),
                "unit": "frames/sec",
                "vs_baseline": round(fps / CAMERA_FPS_BASELINE, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
