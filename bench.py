"""Benchmark: fused perception pipelines on one TPU chip.

Prints ONE JSON line (the driver's contract): the primary metric is the
YOLOv5n 512x512 fused end-to-end pipeline. Secondary metrics (bf16,
batch-64, PointPillars, SECOND-IoU, CenterPoint 10-sweep) go to stderr
and BENCH_LOCAL.json.

Methodology (round 2 — trustworthy numbers over the remote-chip tunnel):

* Every timed call is CHAINED through a scalar token computed from the
  full output, so successive calls cannot overlap or be elided, and a
  float() readback forces completion. On this container's tunnel,
  ``jax.block_until_ready`` can acknowledge repeated identical
  dispatches early (phantom ~0.02 ms timings) — forced scalar readback
  is the only reliable fence.
* Throughput trials run the chained rep-loop INSIDE one jit
  (lax.fori_loop): the tunnel charges ~5 ms per DISPATCH (measured: a
  trivial scalar add costs the same as a full pipeline call when
  dispatched individually), so per-dispatch timing measures the tunnel,
  not the chip. One dispatch per trial + one readback amortizes that
  overhead to noise; per-request latency (which legitimately pays
  dispatch + RTT) is reported separately from single-dispatch calls.
* Configs are INTERLEAVED round-robin (A/B/A/B...) and the reported
  value is the median across trials, so slow tunnel phases hit all
  configs equally instead of biasing one.
* Per-request p50/p99 latency is measured separately with a readback
  per call (the BASELINE.json "p50 e2e latency" contract), alongside a
  tunnel round-trip probe so chip time vs tunnel time is explicit.
* MFU is derived from the compiled executable's own FLOP count
  (cost_analysis) against the v5e MXU peak. NOTE: jax's default matmul
  precision on TPU feeds the MXU bf16 inputs with f32 accumulation
  even for f32 arrays, so fp32 and bf16 model dtypes run the MXU at
  the same rate — the honest peak for both is the bf16 peak.

The reference publishes no numbers; its serving path is one blocking
gRPC round-trip per frame to a remote Triton GPU. vs_baseline remains
anchored to the real-time sensor rates its ROS pipelines must sustain
(30 fps camera / 10 Hz lidar, SURVEY.md section 3.1) — a deployment
headroom ratio, not a hardware comparison; p50/p99/MFU are the
hardware-meaningful numbers.

Round-4 budget discipline (VERDICT r3 #1): BENCH_r03.json timed out
(rc=124) with zero rows because all emission waited for the full run.
Now the run schedules itself against ``BENCH_BUDGET_S`` wall-clock
(default 960 s — the r3 driver clock ran out ~960 s in): configs build
and warm lazily in value order and are SKIPPED (stderr note) when
their estimated warmup no longer fits; trials stop early at
>= MIN_TRIALS rounds; every row prints the moment it exists; a SIGTERM
flushes whatever has >= 3 trial samples. The persistent compilation
cache (.jax_cache, utils/compilation_cache.py) turns the ~900 s fresh
warmup bill into seconds for every later run on the same rig.
"""

import json
import os
import signal
import statistics
import sys
import time

from triton_client_tpu.utils.compilation_cache import enable_persistent_cache

enable_persistent_cache()  # before any jax compile: 40-250 s/compile fresh

import jax
import jax.numpy as jnp
import numpy as np

BATCH = 8
TRIALS = 10          # interleaved rounds per config (r1-r4: 12 — the
                     # per-call medians moved <0.5% between 10 and 12
                     # rounds at r4 spreads of 0.005-0.03, and the two
                     # rounds buy ~25 s for the serving stage)
MIN_TRIALS = 6       # fewest rounds a budget squeeze may cut to
REPS = 25            # chained dispatches per trial
LAT_CALLS = 20       # single-call latency samples (readback per call)
# warmup-scheduler reserve for the serving stage (VERDICT r3 #2):
# every secondary admission, the trial loop's early stop, and the
# primary-extras gate all leave this much for the serving rows (r5:
# when only the b64 tails carried the reserve at admission, the delta
# rows and trial rounds ran right through it and serving starved).
# Admission stays value-ordered greedy: the b64 peak is considered
# before the delta rows and degrades to a shortened provisional block
# when the full protocol no longer fits (that block still costs its
# warmup, which can squeeze later admissions — the deliberate trade:
# the peak row outranks everything below it); a config shed OUTRIGHT
# never blocks later, cheaper rows. 280 (not 170): warm-cache warmups still run
# 20-115 s each through a slow tunnel phase, and with 170 the delta
# rows were admitted on optimistic estimates and left the serving gate
# ~40 s short twice in r5 — the reserve must absorb one mis-estimated
# warmup, not just the serving windows themselves.
SERVING_RESERVE_S = 280.0

# The serving stage's own envelope — the thing SERVING_RESERVE_S exists
# to protect. The start gate and the window sizing both derive from
# these (the gate used to hardcode 170, the OLD reserve value, and
# silently drifted when the reserve was retuned to 280):
SERVING_TAIL_S = 120.0      # merge-size precompiles + row-flush slack
SERVING_MIN_WINDOW_S = 15.0  # floor per transport window (~20 batches)
SERVING_MAX_WINDOW_S = 60.0
# cheapest viable stage: the tail plus one minimum window per transport
# row (5 rows: grpc/shm/uds/stream_b8 + the 3D row) — below this the
# window formula would bottom out under its own floor, so don't start
# at all
SERVING_FLOOR_S = SERVING_TAIL_S + 5 * SERVING_MIN_WINDOW_S
assert SERVING_FLOOR_S < SERVING_RESERVE_S

# Wall-clock budget (VERDICT r3 #1): BENCH_r03.json shows the driver's
# clock ran out with 902 s of warmups + 8 trial rounds + a setup phase
# (10 config builds + NMS gate) on the books — i.e. the external cap
# is at least ~1,050 s but its exact value is unknown. 1,020 stays
# BELOW that observed floor while still fitting the full warm-cache
# run with shortened serving windows; every headline row is out by
# ~T+700 regardless, and the SIGTERM flush covers a cap landing in
# the serving tail. Everything after setup is scheduled against it:
# warmups are ordered by value-per-second and skipped (with a stderr
# note) when they no longer fit, trials stop early at >= MIN_TRIALS,
# and rows are emitted the moment they exist.
BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "1020"))
T_START = time.perf_counter()


def _remaining() -> float:
    return BUDGET_S - (time.perf_counter() - T_START)


def _load_flops_sidecar() -> dict:
    try:
        with open("BENCH_FLOPS.json") as f:
            return dict(json.load(f))
    except Exception:
        return {}


# metric -> {"flops", "bytes"} per call, persisted across runs (see
# Config.warmup). Entries were plain flops floats before the roofline
# round; _sidecar_cost loads both forms.
_FLOPS_SIDEBAR = _load_flops_sidecar()


def _sidecar_cost(key: str) -> tuple[float, float]:
    """(flops, bytes) per call from a sidecar entry (0.0 = unknown)."""
    entry = _FLOPS_SIDEBAR.get(key)
    if isinstance(entry, dict):
        return (
            float(entry.get("flops", 0.0) or 0.0),
            float(entry.get("bytes", 0.0) or 0.0),
        )
    if entry:
        return float(entry), 0.0
    return 0.0, 0.0


def _save_flops_sidecar() -> None:
    try:
        with open("BENCH_FLOPS.json", "w") as f:
            json.dump(_FLOPS_SIDEBAR, f, indent=1, sort_keys=True)
    except OSError as e:
        print(f"could not write BENCH_FLOPS.json: {e}", file=sys.stderr)
CAMERA_FPS_BASELINE = 30.0
LIDAR_HZ_BASELINE = 10.0  # KITTI/nuScenes lidar scan rate
# Per-chip peaks live in obs/roofline.py — ONE table for bench MFU,
# served MFU, and the roofline ceiling (it keeps the per-policy MXU
# rationale: f32/bf16/int8w execute matmuls at the bf16 peak under
# jax's default precision, full int8 runs the int8 MAC path at 2x).
from triton_client_tpu.obs.roofline import (  # noqa: E402
    POLICY_PEAK_FLOPS,
    V5E_PEAK_FLOPS,
    classify as roofline_classify,
)


def _tunnel_rtt_ms() -> float:
    """Median host<->device round trip for a scalar readback: the
    per-call latency floor the tunnel imposes regardless of compute."""
    one = jnp.float32(1.0)
    f = jax.jit(lambda x: x + 1.0)
    float(f(one))  # compile
    samples = []
    for _ in range(20):
        t0 = time.perf_counter()
        float(f(one))
        samples.append((time.perf_counter() - t0) * 1e3)
    return statistics.median(samples)


class Config:
    """One benchmarked pipeline: ``one(tok) -> tok`` chains the full
    pipeline through a scalar token. Throughput runs ``reps`` chained
    iterations inside ONE jitted fori_loop dispatch; latency uses the
    single-step jit (a real per-request dispatch).

    ``reps`` scales with the pipeline so every trial's timed compute is
    ~1 s: with the default 25, a fast config's 0.2 s trial was the same
    order as the tunnel's dispatch jitter and the r2/r3 primary spread
    (0.17-0.27) was measuring the TUNNEL, not the chip — amortizing
    each dispatch over ~1 s of chip work pushes that noise down an
    order of magnitude."""

    def __init__(self, name, metric, one, unit_per_call, baseline_hz,
                 reps=REPS, precision="f32", fused_stages=()):
        self.name = name
        self.metric = metric
        self.one = one
        self.precision = precision  # serving policy the row ran under
        # which Pallas fusions the row's pipeline routed (ops/fused
        # resolution at build time; [] = pure XLA reference path) —
        # bench_diff readers need the column to know WHICH route a
        # round's number measured
        self.fused_stages = tuple(fused_stages)
        self.reps = reps
        self.step = jax.jit(one)          # single-dispatch form (latency)
        self.looped = jax.jit(
            lambda tok: jax.lax.fori_loop(0, reps, lambda i, t: one(t), tok)
        )
        self.unit_per_call = unit_per_call  # frames (batch) or scans per call
        self.baseline_hz = baseline_hz
        self.trial_ms = []                # per-call ms, one entry per trial
        self.flops_per_call = None
        self.bytes_per_call = None

    def warmup(self):
        tok = jnp.float32(0.0)
        float(self.looped(tok))
        float(self.step(jnp.float32(0.0)))
        # FLOP count: the sidecar (BENCH_FLOPS.json, keyed by metric)
        # spares the cost_analysis retrace+compile (~10-30 s/config of
        # pure warmup bill) on every run after the first; a config
        # whose flops change (model edit) just needs the sidecar entry
        # deleted — or delete the file to re-derive everything
        cached_flops, cached_bytes = _sidecar_cost(self.metric)
        if cached_flops and cached_bytes:
            self.flops_per_call = cached_flops
            self.bytes_per_call = cached_bytes
            return
        try:
            cost = self.step.lower(jnp.float32(0.0)).compile().cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            if cost and cost.get("flops"):
                self.flops_per_call = float(cost["flops"])
                self.bytes_per_call = float(
                    cost.get("bytes accessed", 0.0) or 0.0
                )
                _FLOPS_SIDEBAR[self.metric] = {
                    "flops": self.flops_per_call,
                    "bytes": self.bytes_per_call,
                }
                # persist per-config: a timeout mid-warmup (the exact
                # failure this cache targets) must not lose the
                # entries already derived
                _save_flops_sidecar()
        except Exception:
            pass  # cost analysis is best-effort over the tunnel
        if self.flops_per_call is None and cached_flops:
            # legacy flops-only sidecar entry and no fresh measurement:
            # MFU still computes, the roofline columns wait for bytes
            self.flops_per_call = cached_flops

    def run_trial(self):
        tok = jnp.float32(0.0)
        t0 = time.perf_counter()
        tok = self.looped(tok)  # self.reps chained calls, ONE dispatch
        float(tok)
        self.trial_ms.append((time.perf_counter() - t0) * 1e3 / self.reps)

    def latency_profile(self):
        """Per-request e2e latency: one forced readback per call."""
        samples = []
        tok = jnp.float32(0.0)
        for _ in range(LAT_CALLS):
            t0 = time.perf_counter()
            tok = self.step(tok)
            float(tok)
            samples.append((time.perf_counter() - t0) * 1e3)
        return samples

    def result(self, rtt_ms: float, with_latency: bool = True) -> dict:
        """``with_latency=False`` computes the row from trial samples
        alone (pure numpy, no device calls) — the form the SIGTERM
        flush uses, where a jax dispatch could deadlock."""
        per_call_ms = statistics.median(self.trial_ms)
        # trimmed spread (p90-p10)/median: tunnel stalls land in a
        # single trial and made the max-min spread useless for round-
        # over-round comparison (0.219 on the r2 primary from one
        # 847 ms outlier); the median value itself was already robust
        spread = (
            float(np.percentile(self.trial_ms, 90))
            - float(np.percentile(self.trial_ms, 10))
        ) / per_call_ms
        rate = self.unit_per_call / (per_call_ms / 1e3)
        lat = self.latency_profile() if with_latency else []
        out = {
            "metric": self.metric,
            "value": round(rate, 2),
            "unit": ("frames/sec" if self.unit_per_call > 1 else "scans/sec"),
            "vs_baseline": round(rate / self.baseline_hz, 2),
            "per_call_ms": round(per_call_ms, 4),
            "p50_e2e_ms": (
                round(float(np.percentile(lat, 50)), 3) if lat else None
            ),
            "p99_e2e_ms": (
                round(float(np.percentile(lat, 99)), 3) if lat else None
            ),
            "tunnel_rtt_ms": round(rtt_ms, 3),
            "trial_spread": round(spread, 3),
            "trials": len(self.trial_ms),
            "precision": self.precision,
            "fused_stages": list(self.fused_stages),
        }
        if self.flops_per_call:
            # MFU against the peak of the dtype the row actually ran
            # (POLICY_PEAK_FLOPS), not a blanket as-if-f32 denominator
            out["flops_per_call"] = self.flops_per_call
            out["mfu"] = round(
                self.flops_per_call
                / (per_call_ms / 1e3)
                / POLICY_PEAK_FLOPS.get(self.precision, V5E_PEAK_FLOPS),
                4,
            )
            if self.bytes_per_call:
                # roofline placement: measured intensity vs the machine
                # knee, the binding ceiling, and the attainable rate if
                # only that ceiling bound (obs/roofline.py)
                roof = roofline_classify(
                    self.flops_per_call, self.bytes_per_call,
                    self.precision, batch=int(self.unit_per_call),
                )
                out["bytes_per_call"] = self.bytes_per_call
                out["arithmetic_intensity"] = round(roof.intensity, 2)
                out["roofline_bound"] = roof.bound
                out["attainable_fps"] = round(roof.attainable_fps, 2)
                if roof.attainable_fps > 0:
                    out["roofline_attained_ratio"] = round(
                        rate / roof.attainable_fps, 6
                    )
        return out


def make_yolov5(dtype=None, batch=BATCH, mxu=False) -> Config:
    from triton_client_tpu.models.yolov5 import init_yolov5
    from triton_client_tpu.ops.detect_postprocess import extract_boxes
    from triton_client_tpu.ops.fused import fused_interpret, resolve_fused_stages
    from triton_client_tpu.ops.preprocess import normalize_image

    input_hw = (512, 512)
    model, variables = init_yolov5(
        jax.random.PRNGKey(0), num_classes=2, variant="n", input_hw=input_hw,
        dtype=dtype or jnp.float32,
        s2d=mxu, ch_floor=32 if mxu else 0,
    )
    rng = np.random.default_rng(0)
    frames = jnp.asarray(
        rng.integers(0, 255, (batch, *input_hw, 3)).astype(np.float32)
    )
    # same trace-time routing the served pipeline uses: fused decode+NMS
    # tail on a real TPU (ISSUE 16), reference chain elsewhere — the
    # row's fused_stages column records which route the number measured
    fused_stages = resolve_fused_stages("auto", ("decode_nms",))

    def step(tok):
        x = normalize_image(frames + tok * 0.0, "yolo")
        pred = model.decode(model.apply(variables, x, train=False))
        dets, valid = extract_boxes(
            pred, conf_thresh=0.3, iou_thresh=0.45,
            fused="decode_nms" in fused_stages,
            interpret=fused_interpret(),
        )
        # token depends on every output row -> readback fences the call
        return (jnp.sum(valid) + jnp.sum(dets) * 1e-12).astype(jnp.float32)

    suffix = (
        ("_mxu" if mxu else "")
        + ("_bf16" if dtype == jnp.bfloat16 else "")
        + (f"_b{batch}" if batch != BATCH else "")
    )
    return Config(
        f"yolov5n{suffix}",
        f"yolov5n_512{suffix}_e2e_frames_per_sec_per_chip",
        step, batch, CAMERA_FPS_BASELINE,
        # ~5-8 ms/call at b8: 120 chained reps ≈ 1 s of chip work per
        # dispatch; b64 runs ~18 ms/call so 50 reps lands in the same
        # regime
        reps=120 if batch == BATCH else 50,
        precision="bf16" if dtype == jnp.bfloat16 else "f32",
        fused_stages=fused_stages,
    )


def _structured_cloud(pc_range, n_target=120_000) -> np.ndarray:
    """Realistic-density synthetic scan (io/synthdata.py scene model):
    ground-plane clutter + surface-sampled objects with 1/r^2 return
    falloff. Real lidar concentrates returns near the sensor and on
    surfaces — uniform-random clouds have occupancy/collision patterns
    nothing like a scan, so 3D numbers are pinned on structured scenes
    (VERDICT r2 #6; the uniform config stays as a delta secondary)."""
    from triton_client_tpu.io.synthdata import synth_scene_frame

    rng = np.random.default_rng(0)
    pts, _ = synth_scene_frame(
        rng,
        pc_range=tuple(pc_range),
        n_objects=10,
        n_clutter=n_target - 4_000,
    )
    if len(pts) < n_target:
        # top up with extra ground clutter so structured-vs-uniform
        # configs compare the SAME point count, purely different
        # distributions
        extra = n_target - len(pts)
        x0, y0, _z0, x1, y1, _z1 = pc_range
        fill = np.stack(
            [
                rng.uniform(x0, x1, extra),
                rng.uniform(y0, y1, extra),
                rng.normal(-1.9, 0.05, extra),
                rng.uniform(0, 1, extra),
            ],
            axis=1,
        ).astype(np.float32)
        pts = np.concatenate([pts, fill])
    # shuffle before truncating: the object points are concatenated
    # last, and a tail cut must not preferentially delete objects
    return pts[rng.permutation(len(pts))[:n_target]]


def _make_3d(pipeline, point_budget, name, metric, cloud=None,
             structured=True, reps=REPS, fused_stages=()) -> Config:
    """Shared 3D config builder; ``cloud`` overrides the default
    synthetic KITTI-sized scan (CenterPoint passes its aggregated
    multi-sweep cloud) so the fencing-token step exists in ONE place."""
    from triton_client_tpu.ops.voxelize import pad_points

    if cloud is None and structured:
        cloud = _structured_cloud(pipeline.model.cfg.voxel.point_cloud_range)
    if cloud is None:
        rng = np.random.default_rng(0)
        n_pts = 120_000  # ~KITTI velodyne scan
        pc_range = pipeline.model.cfg.voxel.point_cloud_range
        cloud = np.empty((n_pts, 4), np.float32)
        cloud[:, 0] = rng.uniform(pc_range[0], pc_range[3], n_pts)
        cloud[:, 1] = rng.uniform(pc_range[1], pc_range[4], n_pts)
        cloud[:, 2] = rng.uniform(pc_range[2], pc_range[5], n_pts)
        cloud[:, 3] = rng.uniform(0, 1, n_pts)
    padded, m = pad_points(cloud, point_budget)
    pj, mj = jnp.asarray(padded), jnp.asarray(m)

    inner = pipeline._jit

    def step(tok):
        dets, valid = inner(pj + tok * 0.0, mj)
        return (jnp.sum(valid) + jnp.sum(dets) * 1e-12).astype(jnp.float32)

    return Config(name, metric, step, 1, LIDAR_HZ_BASELINE, reps=reps,
                  fused_stages=fused_stages)


def make_pointpillars(structured=True) -> Config:
    from triton_client_tpu.dataset_config import detect3d_from_yaml
    from triton_client_tpu.pipelines.detect3d import build_pointpillars_pipeline

    _, model_cfg, pipe_cfg = detect3d_from_yaml("data/kitti_pointpillars.yaml")
    pipeline, spec, _ = build_pointpillars_pipeline(
        jax.random.PRNGKey(0), model_cfg=model_cfg, config=pipe_cfg
    )
    suffix = "" if structured else "_uniform"
    return _make_3d(
        pipeline, max(pipe_cfg.point_buckets), f"pointpillars{suffix}",
        f"pointpillars_kitti{suffix}_e2e_scans_per_sec_per_chip",
        structured=structured,
        reps=75,  # ~11 ms/scan -> ~0.8 s per dispatch
        fused_stages=spec.extra.get("fused_stages", []),
    )


def make_centerpoint() -> Config:
    """CenterPoint-pillar, nuScenes 10-sweep config
    (data/nusc_centerpoint.yaml): a 5-feature aggregated cloud
    (x, y, z, i, Δt) through the velocity-head pipeline."""
    import dataclasses

    from triton_client_tpu.dataset_config import detect3d_from_yaml
    from triton_client_tpu.ops.sweeps import aggregate_sweeps
    from triton_client_tpu.ops.voxelize import pad_points
    from triton_client_tpu.pipelines.detect3d import build_centerpoint_pipeline

    _, model_cfg, pipe_cfg = detect3d_from_yaml("data/nusc_centerpoint.yaml")
    pipe_cfg = dataclasses.replace(pipe_cfg, point_buckets=(131072,))
    pipeline, spec, _ = build_centerpoint_pipeline(
        jax.random.PRNGKey(0), model_cfg=model_cfg, config=pipe_cfg
    )
    r = model_cfg.voxel.point_cloud_range
    sweeps, times = [], []
    for i in range(10):  # ~13k points/sweep -> ~131k aggregated
        # every sweep is a structured scene too (same rationale as
        # _structured_cloud; a static platform repeats the scene)
        sweeps.append(_structured_cloud(r, 13_000))
        times.append(-0.05 * i)
    cloud = aggregate_sweeps(sweeps, times=times)
    return _make_3d(
        pipeline, 131072, "centerpoint",
        "centerpoint_nusc_10sweep_e2e_scans_per_sec_per_chip",
        cloud=cloud,
        reps=75,  # ~11 ms/scan -> ~0.8 s per dispatch
        fused_stages=spec.extra.get("fused_stages", []),
    )


def make_second() -> Config:
    from triton_client_tpu.pipelines.detect3d import (
        Detect3DConfig,
        build_second_pipeline,
    )

    cfg = Detect3DConfig(model_name="second_iou")
    pipeline, spec, _ = build_second_pipeline(jax.random.PRNGKey(0), config=cfg)
    return _make_3d(
        pipeline, max(cfg.point_buckets), "second_iou",
        "second_iou_kitti_e2e_scans_per_sec_per_chip",
        reps=50,  # ~16 ms/scan -> ~0.8 s per dispatch
        fused_stages=spec.extra.get("fused_stages", []),
    )


def make_second_sparse() -> Config:
    """SECOND at the REFERENCE's 0.05 m spconv grid via the sparse
    submanifold encoder (ops/sparse_conv.py) — the grid the dense
    emulation cannot compile (5.4 GB volume, BASELINE.md sweep)."""
    from triton_client_tpu.dataset_config import detect3d_from_yaml
    from triton_client_tpu.pipelines.detect3d import build_second_pipeline

    _, model_cfg, pipe_cfg = detect3d_from_yaml(
        "data/kitti_second_sparse005.yaml"
    )
    pipeline, spec, _ = build_second_pipeline(
        jax.random.PRNGKey(0), model_cfg=model_cfg, config=pipe_cfg
    )
    return _make_3d(
        pipeline, max(pipe_cfg.point_buckets), "second_sparse005",
        "second_iou_sparse005_e2e_scans_per_sec_per_chip",
        fused_stages=spec.extra.get("fused_stages", []),
    )


def measure_serving(
    rtt_ms: float,
    duration_s: float = 60.0,
    clients: int = 16,
    max_batch: int = 8,
    max_merge: int = 16,
    input_hw: tuple = (512, 512),
    on_row=None,
    precision: str = "f32",
) -> list:
    """Serving-path benchmark (VERDICT r2 #3): N concurrent gRPC
    clients on localhost against the KServe server + micro-batcher —
    the Triton-equivalent surface whose metrics ARE the reference's
    perf story (README.md:88-95). Four transports, one row each:

      * grpc      — stock KServe raw tensors over loopback TCP (what a
        remote client pays);
      * shm       — the system shared-memory extension (the same-host
        auto-negotiated default): request tensors travel as region
        coordinates and the 786 KB frame payload is one memcpy instead
        of a protobuf serialize/copy/deserialize in each process;
      * uds       — shm tensors with the control plane on a unix
        socket instead of loopback TCP;
      * stream_b8 — uds+shm through ModelStreamInfer with 8-frame
        groups: one message carries 8 packed frames, so the
        per-message protocol cost is paid once per group.

    The gap between any row and the in-process primary is the serving
    overhead; the gaps BETWEEN the rows decompose it (codec vs TCP vs
    per-message cost). Each row reports served fps, ``host_gap_ratio``
    (served fps / device ceiling — the headline the tentpole moves),
    request p50/p99, and the batcher's merge-size histogram, alongside
    the two environment probes (upload_mbps, direct_batch_ms) that
    dominate this rig. A mode that completes zero requests degrades to
    a value-0 row with the error note — the decomposition fields stay
    meaningful.

    Round 4 (VERDICT r3 #2): the batcher forms device batches at slot
    time with ``max_merge`` > admission size, power-of-two bucket
    padding, and a merge hold for burst coalescing; with the
    device-host-device bounce fixed the path serves ~15 fps on this
    rig, so even the budget-floor 15 s window resolves ~20 device
    batches (a 60 s window ~80). Each transport's row is surfaced via
    ``on_row`` the moment its window closes."""
    import collections
    import threading

    from triton_client_tpu.channel.base import InferRequest
    from triton_client_tpu.channel.tpu_channel import TPUChannel
    from triton_client_tpu.pipelines.detect2d import build_yolov5_pipeline
    from triton_client_tpu.runtime.continuous import (
        ContinuousBatchingChannel,
    )
    from triton_client_tpu.runtime.repository import ModelRepository
    from triton_client_tpu.runtime.server import InferenceServer

    pipe, spec, _ = build_yolov5_pipeline(
        jax.random.PRNGKey(0), variant="n", num_classes=2, input_hw=input_hw,
        precision=precision,
    )
    repo = ModelRepository()
    # multi-device rig: serve the whole mesh through the sharded
    # channel (batches split over the data axis, params replicated) so
    # the row carries a real aggregate_frames_per_sec; single-device
    # keeps the historical eager TPUChannel path so served rows stay
    # comparable across rounds
    data_axis = len(jax.devices())
    if data_axis > 1:
        from triton_client_tpu.channel.sharded_channel import (
            ShardedTPUChannel,
        )
        from triton_client_tpu.parallel.mesh import MeshConfig

        repo.register(
            spec, pipe.infer_fn(), device_fn=pipe.device_fn(),
            precision=pipe.precision,
        )
        inner = ShardedTPUChannel(repo, MeshConfig(data=data_axis, model=1))
    else:
        repo.register(spec, pipe.infer_fn(), precision=pipe.precision)
        inner = TPUChannel(repo)

    occupancy: collections.Counter = collections.Counter()
    occ_lock = threading.Lock()
    inner_infer = inner.do_inference

    device_call_s = []  # per-device-call wall (stall forensics)
    window_t0 = [0.0]   # calls STARTED before the current window are
                        # not its forensics: a wire-mode stall that
                        # finishes inside the shm window must not be
                        # attributed to shm (run_pool's straggler join
                        # means in-window stalls do land before the
                        # row is built; only a stall outliving the
                        # join deadline escapes the row entirely)

    def tapped(req):
        # batch forensics are leading-dim semantics for every request
        # shape: the first input tensor's leading dim is the batch (a
        # 3D single-scan request's (N, pf) points then count the
        # cloud-size bucket, not a silent 1 — r5's hard "images"
        # lookup KeyError'd the whole 3D row; a flat b=1 fallback
        # would misattribute a future batched-points request)
        arr = req.inputs.get("images")
        if arr is None and req.inputs:
            arr = next(iter(req.inputs.values()))
        shape = np.shape(arr) if arr is not None else ()
        b = int(shape[0]) if shape else 1
        with occ_lock:
            occupancy[b] += 1
        t0 = time.perf_counter()
        try:
            return inner_infer(req)
        finally:
            with occ_lock:
                if t0 >= window_t0[0]:
                    device_call_s.append(time.perf_counter() - t0)

    inner.do_inference = tapped

    rng = np.random.default_rng(0)
    # uint8 wire frames: the pipeline normalizes on device, so shipping
    # raw bytes quarters the wire + host->device upload vs the
    # reference's float32 tensors (its clients convert BEFORE the wire,
    # utils/preprocess.py image_adjust) — on this rig upload bandwidth
    # IS the serving ceiling (see upload_mbps in the result)
    frame = rng.integers(0, 255, (1, *input_hw, 3)).astype(np.uint8)
    # pre-compile every batch size the bucket-padding dispatcher can
    # produce: log2(max_merge)+1 power-of-two sizes, not every integer
    # (over the tunnel each compile is tens of seconds and must not
    # land inside the timed window)
    k = 1
    while k <= max_merge:
        inner_infer(
            InferRequest(
                model_name=spec.name,
                inputs={"images": np.repeat(frame, k, axis=0)},
            )
        )
        k *= 2

    # reference device-path cost for the SAME work: one max_merge
    # batch through the pipeline from host memory (pays the upload the
    # in-process configs don't) — the gap between this and the served
    # rate is the wire/codec/host-CPU stack
    direct = np.repeat(frame, max_merge, axis=0)
    pipe.infer(direct)  # warm
    t0 = time.perf_counter()
    for _ in range(3):
        pipe.infer(direct)
    direct_batch_ms = (time.perf_counter() - t0) / 3 * 1e3

    # dtype-correct FLOP accounting for the served rows (round 10):
    # derive per-frame FLOPs once from the compiled executable (sidecar
    # cached, same methodology as the e2e configs) so served mfu stops
    # being as-if-f32
    flops_key = f"served_yolov5n_{input_hw[0]}_{precision}_b{max_merge}"
    flops_per_frame, bytes_per_frame = _sidecar_cost(flops_key)
    if not (flops_per_frame and bytes_per_frame):
        try:
            cost = (
                pipe._jit.lower(jnp.asarray(direct), tuple(input_hw))
                .compile()
                .cost_analysis()
            )
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            if cost and cost.get("flops"):
                flops_per_frame = float(cost["flops"]) / max_merge
                bytes_per_frame = (
                    float(cost.get("bytes accessed", 0.0) or 0.0) / max_merge
                )
                _FLOPS_SIDEBAR[flops_key] = {
                    "flops": flops_per_frame,
                    "bytes": bytes_per_frame,
                }
                _save_flops_sidecar()
        except Exception:
            pass  # best-effort over the tunnel

    # host->device upload bandwidth probe: the per-request transfer the
    # in-process configs never pay (device-resident inputs); over this
    # tunnel it is the serving bottleneck, on a real TPU-VM it is PCIe
    blob = np.zeros((8, *input_hw, 3), np.uint8)
    jnp.asarray(blob).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(3):
        jnp.asarray(blob).block_until_ready()
        blob[0, 0, 0, 0] += 1  # defeat any caching
    up_s = (time.perf_counter() - t0) / 3
    upload_mbps = blob.nbytes / 1e6 / up_s

    # per-request deadline sized from the measured device path: the
    # whole client pool behind one dispatch queue, with 20x headroom
    # for host CPU contention (the r3 driver rig hit 120 s deadlines
    # at p50 17 s) — deadlines firing inside the window turn the row
    # into an error count instead of a rate
    deadline_s = max(180.0, direct_batch_ms / 1e3 * clients * 20)

    # continuous scheduler (ISSUE 8): windowless EDF admission, dense
    # fallback padded to live-occupancy buckets — the merge-hold knob
    # the window batcher needed to fill merges is obsolete (arrivals
    # pool while device work is in flight)
    batching = ContinuousBatchingChannel(
        inner, max_batch=max_batch,
        max_merge=max_merge, pad_to_buckets=True,
    )
    server = InferenceServer(
        repo, batching, address="127.0.0.1:0", uds_address="auto",
        max_workers=clients + 8,
    )
    server.start()
    addr = f"127.0.0.1:{server.port}"
    replica_servers: list = []  # BENCH_REPLICAS extra front-door targets

    # per-transport serving rows (ISSUE 13): the host-gap story needs
    # one row per transport the host path offers, not just wire-vs-shm
    #   grpc      — loopback TCP, raw protobuf tensors (remote-client
    #               cost model)
    #   shm       — loopback TCP control + shared-memory tensors (the
    #               same-host default)
    #   uds       — unix socket control + shared-memory tensors
    #   stream_b8 — uds+shm with 8-frame stream groups: the per-message
    #               protocol cost paid once per 8 frames
    _TRANSPORT_MODES = {
        "grpc": dict(use_shm=False, uds=False, group=1),
        "shm": dict(use_shm=True, uds=False, group=1),
        "uds": dict(use_shm=True, uds=True, group=1),
        "stream_b8": dict(use_shm=True, uds=True, group=8),
    }

    def run_mode(transport: str) -> dict:
        from triton_client_tpu.utils.loadgen import run_pool

        mode = _TRANSPORT_MODES[transport]
        stats0 = {}

        def window_start():
            # timed window starts here: drop warm-phase accounting
            with occ_lock:
                occupancy.clear()
                device_call_s.clear()
                window_t0[0] = time.perf_counter()
            stats0.update(batching.stats())

        res = run_pool(
            server.uds_address if mode["uds"] else addr,
            spec.name,
            {"images": frame},
            clients=clients,
            duration_s=duration_s,
            deadline_s=deadline_s,
            use_shared_memory=mode["use_shm"],
            mode="stream" if mode["group"] > 1 else "unary",
            inflight=mode["group"],
            stream_group=mode["group"],
            on_window_start=window_start,
        )
        stats = batching.stats()
        if res.errors:
            print(
                f"serving bench ({transport}) client "
                f"errors: {res.errors[:3]}",
                file=sys.stderr,
            )

        total = res.served_frames
        latencies = res.latencies_ms
        d_frames = stats.get("merged_frames", 0) - stats0.get(
            "merged_frames", 0
        )
        d_merges = stats.get("merges", 0) - stats0.get("merges", 0)
        d_padded = stats.get("padded_frames", 0) - stats0.get(
            "padded_frames", 0
        )
        d_ragged_rows = stats.get("ragged_rows", 0) - stats0.get(
            "ragged_rows", 0
        )
        d_ragged_pad = stats.get("ragged_pad_rows", 0) - stats0.get(
            "ragged_pad_rows", 0
        )
        mean_batch = (d_frames / d_merges) if d_merges else 0.0
        # the wire row keeps its historical unsuffixed metric name so
        # bench_diff comparisons line up across rounds
        suffix = "" if transport == "grpc" else f"_{transport}"
        row = {
            "metric": f"yolov5n_512_served{suffix}_frames_per_sec",
            "transport": transport,
            "value": round(res.fps, 2),
            "unit": "frames/sec",
            "vs_baseline": round(res.fps / CAMERA_FPS_BASELINE, 2),
            # whole-server rate over every device the channel drives;
            # per-chip divides it back out for the BENCH_LOCAL-style
            # single-chip comparison
            "data_axis": data_axis,
            "aggregate_frames_per_sec": round(res.fps, 2),
            "frames_per_sec_per_chip": round(res.fps / data_axis, 2),
            "clients": clients,
            "served_frames": total,
            "request_p50_ms": (
                round(float(np.percentile(latencies, 50)), 2)
                if latencies else None
            ),
            "request_p99_ms": (
                round(float(np.percentile(latencies, 99)), 2)
                if latencies else None
            ),
            "request_p999_ms": (
                round(float(np.percentile(latencies, 99.9)), 2)
                if latencies else None
            ),
            # filled on the wire row by the open-loop SLO search below
            # (None = not searched: shm/3d rows, or budget ran out).
            # goodput = SLO-met completions/sec AT capacity and
            # shed_rate = deliberate RESOURCE_EXHAUSTED rejections /
            # scheduled — the capacity story reports what was served
            # within SLO, not just offered load survived
            "slo_capacity_qps": None,
            "goodput_qps": None,
            "shed_rate": None,
            "slo_ms": None,
            # fleet row (ISSUE 10): with BENCH_REPLICAS=N > 1 the wire
            # row also searches capacity through a FrontDoorRouter over
            # N endpoints (extra servers share this rig's device, so
            # the number measures the front door + failover machinery,
            # not N devices' worth of compute)
            "replicas": 1,
            "fleet_goodput_qps": None,
            "tunnel_rtt_ms": round(rtt_ms, 3),
            "upload_mbps": round(upload_mbps, 1),
            "direct_batch_ms": round(direct_batch_ms, 1),
            # what the device leg alone supports on THIS rig at the
            # same max_merge batch: every served batch pays one
            # un-amortized tunnel dispatch (~1 s; a co-located TPU-VM
            # pays ~ms) — served/ceiling is the serving stack's share,
            # ceiling is the environment's
            "device_ceiling_fps": round(
                max_merge / (direct_batch_ms / 1e3), 2
            ),
            # the host-gap headline: served rate as a fraction of what
            # the device leg alone supports on this rig — 1.0 means the
            # host transport costs nothing, the seed's shm row sat at
            # ~0.01 on BENCH_r05's rig
            "host_gap_ratio": round(
                res.fps / max(1e-9, max_merge / (direct_batch_ms / 1e3)),
                4,
            ),
            "client_errors": len(res.errors),
            "device_batches": d_merges,
            "mean_batch": round(float(mean_batch), 2),
            "padded_frames": stats.get("padded_frames", 0)
            - stats0.get("padded_frames", 0),
            # padding-tax headline for the window: pad rows (dense
            # bucket pad + ragged alignment slack) over all device rows
            "pad_fraction": round(
                (d_padded + d_ragged_pad)
                / max(1, d_frames + d_padded + d_ragged_rows + d_ragged_pad),
                4,
            ),
            "ragged_batches": stats.get("ragged_batches", 0)
            - stats0.get("ragged_batches", 0),
            "ragged_rows": d_ragged_rows,
            "ragged_pad_rows": d_ragged_pad,
            "batch_occupancy": {
                str(k): occupancy[k] for k in sorted(occupancy)
            },
            # stall forensics: the tunnel intermittently freezes a
            # device call for minutes (r3: 200-550 s warmups in bad
            # phases); a window with max >> median is environment-
            # stalled and its fps is not a framework number
            "max_device_call_s": (
                round(max(device_call_s), 2) if device_call_s else None
            ),
            "p50_device_call_s": (
                round(float(np.percentile(device_call_s, 50)), 2)
                if device_call_s else None
            ),
            "precision": precision,
            "fused_stages": spec.extra.get("fused_stages", []),
        }
        if flops_per_frame:
            row["flops_per_frame"] = flops_per_frame
            row["mfu"] = round(
                res.fps * flops_per_frame
                / POLICY_PEAK_FLOPS.get(precision, V5E_PEAK_FLOPS),
                4,
            )
            if bytes_per_frame:
                roof = roofline_classify(
                    flops_per_frame * max_merge,
                    bytes_per_frame * max_merge,
                    precision, batch=max_merge,
                )
                row["bytes_per_frame"] = bytes_per_frame
                row["arithmetic_intensity"] = round(roof.intensity, 2)
                row["roofline_bound"] = roof.bound
                row["attainable_fps"] = round(roof.attainable_fps, 2)
                if roof.attainable_fps > 0:
                    row["roofline_attained_ratio"] = round(
                        res.fps / roof.attainable_fps, 6
                    )
        if total == 0:
            row["degraded"] = (
                f"no request completed in the {duration_s:.0f}s window; "
                f"first error: {res.errors[:1]}"
            )
        return row

    rows = []
    try:
        for transport in ("grpc", "shm", "uds", "stream_b8"):
            if transport != "grpc" and _remaining() < 100.0:
                # the wire row is already captured; further transports
                # must not drag the run past the external cap
                print(
                    f"serving {transport} mode skipped: "
                    f"{_remaining():.0f}s left", file=sys.stderr,
                )
                break
            try:
                row = run_mode(transport)
                if (
                    transport == "grpc"
                    and row["request_p50_ms"]
                    and _remaining() > 240.0
                ):
                    # open-loop SLO capacity on the wire transport: the
                    # MLPerf server-scenario number (max offered qps at
                    # p99 <= SLO) next to the closed-loop fps. SLO =
                    # 3x a lightly-loaded OPEN-loop p50 — closed-loop
                    # p50 hides the batcher's merge hold (clients
                    # arrive together and fill batches; a lone Poisson
                    # arrival waits the hold out), so deriving from it
                    # reads capacity 0 on any held config; and a fixed
                    # wall SLO would read 0 through the tunnel RTT.
                    # Short probes + a hard straggler deadline keep the
                    # whole search bounded (~12 probes x ~15 s worst
                    # case) so it can never eat the rows that follow.
                    try:
                        from triton_client_tpu.utils.loadgen import (
                            run_open_loop,
                            slo_capacity_search,
                        )

                        calib = run_open_loop(
                            addr, [(spec.name, {"images": frame})],
                            rate_qps=4.0, duration_s=3.0,
                            deadline_s=60.0,
                        )
                        p50 = calib.percentile(50.0)
                        slo_ms = max(
                            10.0,
                            3.0 * (row["request_p50_ms"] or 0.0),
                            3.0 * (0.0 if p50 == float("inf") else p50),
                        )
                        cap = slo_capacity_search(
                            addr, [(spec.name, {"images": frame})],
                            slo_ms=slo_ms, duration_s=3.0,
                            qps_lo=0.5,
                            qps_hi=max(8.0, 4.0 * (row["value"] or 1.0)),
                            deadline_s=12.0,
                        )
                        row["slo_capacity_qps"] = cap["slo_capacity_qps"]
                        row["goodput_qps"] = cap.get("goodput_qps")
                        row["shed_rate"] = cap.get("shed_rate")
                        row["slo_ms"] = round(slo_ms, 2)
                        row["slo_p99_ms"] = cap["p99_ms"]
                        # fleet capacity through the front door: extra
                        # replica servers over the SAME repo + batcher
                        # (one host, shared device — the delta vs the
                        # single-endpoint number is the router's cost
                        # or win, not extra hardware)
                        n_replicas = int(
                            os.environ.get("BENCH_REPLICAS", "1")
                        )
                        if n_replicas > 1 and _remaining() > 180.0:
                            for _ in range(n_replicas - 1):
                                extra = InferenceServer(
                                    repo, batching,
                                    address="127.0.0.1:0",
                                    max_workers=clients + 8,
                                )
                                extra.start()
                                replica_servers.append(extra)
                            fleet = [addr] + [
                                f"127.0.0.1:{s.port}"
                                for s in replica_servers
                            ]
                            cap_fleet = slo_capacity_search(
                                fleet, [(spec.name, {"images": frame})],
                                slo_ms=slo_ms, duration_s=3.0,
                                qps_lo=0.5,
                                qps_hi=max(8.0, 4.0 * (row["value"] or 1.0)),
                                deadline_s=12.0,
                            )
                            row["replicas"] = n_replicas
                            row["fleet_goodput_qps"] = cap_fleet.get(
                                "goodput_qps"
                            )
                            row["fleet_slo_capacity_qps"] = cap_fleet[
                                "slo_capacity_qps"
                            ]
                    except Exception as e:
                        print(f"slo capacity search failed: {e}",
                              file=sys.stderr)
                rows.append(row)
                if on_row is not None:
                    on_row(row)  # emitted the moment it exists
            except Exception as e:
                print(
                    f"serving mode {transport} failed: {e}",
                    file=sys.stderr,
                )
        # 3D served row (VERDICT r4 Weak #2: serving evidence was
        # 2D-unary only): PointPillars through the SAME server +
        # batcher. 3D requests are single-scan (no leading batch dim —
        # the reference's 3D client contract), so they ride the
        # batcher's oversized-solo path; the row measures the serving
        # stack on the 3D pipeline, not merge behavior.
        if _remaining() > 110.0:
            try:
                row = _serve_3d_row(
                    repo, batching, server, rtt_ms,
                    duration_s=min(25.0, max(12.0, _remaining() - 90.0)),
                )
                rows.append(row)
                if on_row is not None:
                    on_row(row)
            except Exception as e:
                print(f"serving 3d mode failed: {e}", file=sys.stderr)
        else:
            print(
                f"serving 3d row skipped: {_remaining():.0f}s left",
                file=sys.stderr,
            )
    finally:
        for extra in replica_servers:
            try:
                extra.stop()
            except Exception:
                pass
        server.stop()
        batching.close()
    return rows


def _serve_3d_row(repo, batching, server, rtt_ms, duration_s: float) -> dict:
    """PointPillars served over the live KServe server: 8 closed-loop
    clients sending single scans (~20k-point uniform clouds, the
    pointpillars_uniform distribution)."""
    from triton_client_tpu.pipelines.detect3d import (
        build_pointpillars_pipeline,
    )
    from triton_client_tpu.utils.loadgen import run_pool

    pipe3, spec3, _ = build_pointpillars_pipeline(jax.random.PRNGKey(0))
    repo.register(spec3, pipe3.infer_fn())

    rng = np.random.default_rng(3)
    n_pts = 20000
    pts = np.stack(
        [
            rng.uniform(0.0, 69.12, n_pts),
            rng.uniform(-39.68, 39.68, n_pts),
            rng.uniform(-3.0, 1.0, n_pts),
            rng.uniform(0, 1, n_pts),
        ],
        axis=1,
    ).astype(np.float32)
    feed = {"points": pts, "num_points": np.asarray(n_pts, np.int32)}
    # warm the scan shape through the inner channel before the window,
    # then time one warm dispatch (the per-scan device-path cost)
    from triton_client_tpu.channel.base import InferRequest

    batching.do_inference(InferRequest(model_name=spec3.name, inputs=feed))
    t0 = time.perf_counter()
    batching.do_inference(InferRequest(model_name=spec3.name, inputs=feed))
    direct_ms = (time.perf_counter() - t0) * 1e3

    res = run_pool(
        f"127.0.0.1:{server.port}",
        spec3.name,
        feed,
        clients=8,
        duration_s=duration_s,
        deadline_s=240.0,
    )
    latencies = res.latencies_ms
    row = {
        "metric": "pointpillars_served_scans_per_sec",
        "value": round(res.fps, 2),
        "unit": "scans/sec",
        "vs_baseline": round(res.fps / LIDAR_HZ_BASELINE, 2),
        "clients": 8,
        "served_scans": res.served_frames,
        "request_p50_ms": (
            round(float(np.percentile(latencies, 50)), 2) if latencies else None
        ),
        "request_p99_ms": (
            round(float(np.percentile(latencies, 99)), 2) if latencies else None
        ),
        "request_p999_ms": (
            round(float(np.percentile(latencies, 99.9)), 2)
            if latencies else None
        ),
        "slo_capacity_qps": None,
        "goodput_qps": None,
        "shed_rate": None,
        "slo_ms": None,
        "tunnel_rtt_ms": round(rtt_ms, 3),
        "direct_scan_ms": round(direct_ms, 1),
        # single-scan dispatches: the ceiling is one scan per device
        # call on this rig (no batch amortization on the 3D wire)
        "device_ceiling_fps": round(1e3 / direct_ms, 2) if direct_ms else None,
        "client_errors": len(res.errors),
        "precision": "f32",
        "fused_stages": spec3.extra.get("fused_stages", []),
    }
    if res.served_frames == 0:
        row["degraded"] = f"no request completed; first error: {res.errors[:1]}"
    return row


def _serve_streaming_sessions_row(duration_s: float) -> dict:
    """ISSUE 15 streaming sessions at replay pace: 8 concurrent
    synthetic streams, each a scripted multi-object scene replayed at
    recorded fps through its own ``sequence_id`` against one in-process
    server with device-resident tracking. The row's ``value`` is the
    total sustained frames/sec across streams — gated by
    perf/bench_diff.py like every throughput row; the tracking-quality
    counters (id switches, fragmentation, aliases) ride along so a
    regression in EITHER pace or identity stability shows up in the
    diff. Echo detector on purpose: the row measures the session layer
    (slot pool + on-device tracker step + sequence plumbing), not
    detector math."""
    from triton_client_tpu.channel.tpu_channel import TPUChannel
    from triton_client_tpu.config import ModelSpec, TensorSpec
    from triton_client_tpu.ops.tracking import TrackerConfig
    from triton_client_tpu.runtime.repository import ModelRepository
    from triton_client_tpu.runtime.server import InferenceServer
    from triton_client_tpu.runtime.sessions import SessionManager
    from triton_client_tpu.utils.loadgen import run_streams, synthetic_stream

    n_streams, fps = 8, 10.0
    det_dim = 11
    spec = ModelSpec(
        name="stream_echo",
        version="1",
        platform="jax",
        inputs=(
            TensorSpec("detections", (-1, det_dim), "FP32"),
            TensorSpec("valid", (-1,), "BOOL"),
        ),
        outputs=(
            TensorSpec("detections", (-1, det_dim), "FP32"),
            TensorSpec("valid", (-1,), "BOOL"),
        ),
    )
    repo = ModelRepository()
    repo.register(
        spec,
        lambda inputs: {
            "detections": inputs["detections"],
            "valid": inputs["valid"],
        },
    )
    chan = TPUChannel(repo)
    manager = SessionManager(
        max_sessions=n_streams * 2, ttl_s=300.0,
        tracker=TrackerConfig(max_tracks=32),
    )
    chan.attach_sessions(manager)
    server = InferenceServer(
        repo, chan, address="127.0.0.1:0", uds_address="auto",
        max_workers=n_streams + 2,
    )
    server.start()
    try:
        # warm: compile the tracker step before the paced window
        run_streams(
            server.uds_address, spec.name, n_streams=1,
            source=lambda i: synthetic_stream(n_frames=3, fps=100.0),
            deadline_s=60.0, stream_id_prefix="warm",
        )
        n_frames = max(10, int(duration_s * fps))
        res = run_streams(
            server.uds_address, spec.name, n_streams=n_streams,
            source=lambda i: synthetic_stream(
                n_frames=n_frames, fps=fps, n_objects=4, seed=i
            ),
            deadline_s=duration_s + 120.0,
        )
        summary = res.summary()
        total_fps = sum(s.sustained_fps for s in res.streams)
        row = {
            "metric": "streaming_sessions",
            "value": round(total_fps, 2),
            "unit": "frames/sec",
            "streams": n_streams,
            "requested_fps_per_stream": fps,
            "min_sustained_fps": summary["min_sustained_fps"],
            "worst_inter_frame_p99_ms": summary["worst_inter_frame_p99_ms"],
            "goodput": summary["goodput"],
            "id_switches": summary["id_switches"],
            "fragmentation": summary["fragmentation"],
            "track_id_aliases": summary["track_id_aliases"],
            "session_frames": manager.stats()["frames_total"],
            "precision": "f32",
        }
        if res.frames_ok == 0:
            row["degraded"] = "no stream frame completed"
        return row
    finally:
        server.stop()


def _serve_quality_plane_row(duration_s: float) -> dict:
    """ISSUE 17 continuous quality plane: one in-process server with a
    detection echo model, its ``_int8`` twin armed as a canary, and the
    shadow sampler at the serve CLI's canary-default 25%. Two paced
    open-loop windows, sampling OFF then ON, same seed; the row's
    ``value`` is scored frames/sec off the mirror's own counter, and
    ``quality_overhead_headroom`` (p99 off / p99 on) is gated by
    perf/bench_diff.py: a >10% drop means the sampler started taxing
    the primary path. Echo detector on purpose — the row measures the
    route/observe/mirror machinery, not detector math."""
    from triton_client_tpu.channel.tpu_channel import TPUChannel
    from triton_client_tpu.config import ModelSpec, TensorSpec
    from triton_client_tpu.eval.quality_plane import QualityPlane
    from triton_client_tpu.runtime.repository import ModelRepository
    from triton_client_tpu.runtime.server import InferenceServer
    from triton_client_tpu.utils.loadgen import run_open_loop

    det = np.zeros((6, 6), np.float32)
    det[:, 0] = np.arange(6) * 30.0
    det[:, 1] = np.arange(6) * 20.0
    det[:, 2] = det[:, 0] + 24.0
    det[:, 3] = det[:, 1] + 16.0
    det[:, 4] = 0.9
    det[:, 5] = np.arange(6) % 3

    def _det_fn(inputs):
        return {
            "detections": det + np.float32(0.0) * inputs["x"][0, 0],
            "valid": np.ones((6,), bool),
        }

    repo = ModelRepository()
    for name in ("qp_det", "qp_det_int8"):
        repo.register(
            ModelSpec(
                name=name, version="1", platform="jax",
                inputs=(TensorSpec("x", (-1, 4), "FP32"),),
                outputs=(
                    TensorSpec("detections", (-1, 6), "FP32"),
                    TensorSpec("valid", (-1,), "BOOL"),
                ),
            ),
            _det_fn,
        )
    quality = QualityPlane(sample_rate=0.0, window_frames=16)
    quality.set_canary("qp_det", "qp_det_int8", 0.25)
    server = InferenceServer(
        repo, TPUChannel(repo), address="127.0.0.1:0",
        max_workers=8, quality=quality,
    )
    server.start()
    try:
        import dataclasses as _dc

        from triton_client_tpu.channel.base import InferRequest
        from triton_client_tpu.channel.grpc_channel import GRPCChannel

        window = max(2.0, duration_s / 2.0)
        scenarios = [("qp_det", {"x": np.ones((2, 4), np.float32)})]
        addr = f"127.0.0.1:{server.port}"
        rate = 120.0
        # compile BOTH registrations (the canary slice routes to the
        # variant mid-window otherwise) and the shadow dispatch path
        # before any timed window
        warm_chan = GRPCChannel(addr, timeout_s=30.0)
        try:
            for name in ("qp_det", "qp_det_int8"):
                for i in range(3):
                    warm_chan.do_inference(InferRequest(
                        name, scenarios[0][1], request_id=f"warm-{name}-{i}"
                    ))
        finally:
            warm_chan.close()
        # deterministic per-arrival identity: the hash-sampled canary
        # slice and shadow sample are then identical across runs
        factory = lambda req, i: _dc.replace(req, request_id=f"qp-{i}")
        off = run_open_loop(
            addr, scenarios, rate_qps=rate, duration_s=window, seed=11,
            deadline_s=30.0, request_factory=factory,
        )
        quality.set_sample_rate(0.25)
        t0 = time.perf_counter()
        on = run_open_loop(
            addr, scenarios, rate_qps=rate, duration_s=window, seed=11,
            deadline_s=30.0, request_factory=factory,
        )
        quality.drain(20.0)
        wall = time.perf_counter() - t0
        mirror = quality.snapshot()["mirror"]
        p99_off = off.percentile(99.0)
        p99_on = on.percentile(99.0)
        # the gated ratio uses p95: the same signal (sidecar tax on the
        # primary path) with far less single-sample jitter than p99
        p95_off = off.percentile(95.0)
        p95_on = on.percentile(95.0)
        row = {
            "metric": "quality_plane",
            "value": round(mirror["scored"] / max(wall, 1e-9), 2),
            "unit": "scored_frames/sec",
            "sample_rate": 0.25,
            "scored_frames": mirror["scored"],
            "mirror_dropped": mirror["dropped"],
            "shadow_lag_ms": round(mirror["mean_lag_s"] * 1e3, 3),
            "p99_off_ms": round(p99_off, 3),
            "p99_on_ms": round(p99_on, 3),
            "p99_delta_ms": round(p99_on - p99_off, 3),
            "p95_off_ms": round(p95_off, 3),
            "p95_on_ms": round(p95_on, 3),
            "shadow_overhead_ratio": round(p95_on / max(p95_off, 1e-9), 4),
            "quality_overhead_headroom": round(
                p95_off / max(p95_on, 1e-9), 4
            ),
            "canary": quality.canary.stats()["models"]
            .get("qp_det", {}).get("state", "none"),
            "precision": "f32",
        }
        if on.completed == 0 or off.completed == 0:
            row["degraded"] = (
                f"window incomplete; first error: {(off.errors or on.errors)[:1]}"
            )
        return row
    finally:
        server.stop()


def _serve_temporal_reuse_row(duration_s: float) -> dict:
    """ISSUE 19 temporal compute reuse: the same synthetic stream set
    replayed twice against an in-process server with device-resident
    tracking — reuse OFF (full detector every frame) then reuse ON
    (adaptive keyframe scheduling, static scene so K opens wide and
    coast dominates). The echo detector carries a fixed simulated
    device cost so the per-stream device-seconds ledger (the PR 11
    scoreboard) has something to save; the row's ``value`` is
    streams-per-chip at the replay fps with reuse on, and
    ``temporal_speedup`` (streams-per-chip on / off) is gated by
    perf/bench_diff.py. ID switches ride along so a cheaper schedule
    that costs identity stability shows up in the diff."""
    from triton_client_tpu.channel.tpu_channel import TPUChannel
    from triton_client_tpu.config import ModelSpec, TensorSpec
    from triton_client_tpu.ops.tracking import TrackerConfig
    from triton_client_tpu.runtime.repository import ModelRepository
    from triton_client_tpu.runtime.server import InferenceServer
    from triton_client_tpu.runtime.sessions import SessionManager
    from triton_client_tpu.runtime.temporal import (
        TemporalReuseConfig,
        TemporalReusePlane,
    )
    from triton_client_tpu.utils.loadgen import run_streams, synthetic_stream

    n_streams, fps, det_dim = 6, 10.0, 11
    detector_iters = 60  # 128x128 matmul chain: the simulated det cost
    n_frames = max(20, int(duration_s * 10))

    def _window(reuse: bool) -> dict:
        import jax.numpy as jnp

        spec = ModelSpec(
            name="tr_det",
            version="1",
            platform="jax",
            inputs=(
                TensorSpec("detections", (-1, det_dim), "FP32"),
                TensorSpec("valid", (-1,), "BOOL"),
            ),
            outputs=(
                TensorSpec("detections", (-1, det_dim), "FP32"),
                TensorSpec("valid", (-1,), "BOOL"),
            ),
        )
        repo = ModelRepository()

        def _det_fn(inputs):
            return {
                "detections": inputs["detections"],
                "valid": inputs["valid"],
            }

        # the simulated detector cost must be real async-dispatched
        # device work (a jitted device_fn): the ledger's scoreboard
        # window is launch -> execution-ready, so a host sleep would
        # run before dispatch and charge the stream tenant nothing
        eye = jnp.eye(128, dtype=jnp.float32)

        def _det_device_fn(inputs):
            det = inputs["detections"]
            v = jnp.broadcast_to(det.reshape(-1)[:1], (128, 128)) + eye
            for _ in range(detector_iters):
                v = v @ eye
            return {
                "detections": det + v[0, 0] * jnp.float32(1e-30),
                "valid": inputs["valid"],
            }

        repo.register(spec, _det_fn, device_fn=_det_device_fn)
        chan = TPUChannel(repo)
        manager = SessionManager(
            max_sessions=n_streams * 2, ttl_s=300.0,
            tracker=TrackerConfig(max_tracks=32),
        )
        chan.attach_sessions(manager)
        temporal = None
        if reuse:
            temporal = TemporalReusePlane(
                manager,
                config=TemporalReuseConfig(mode="auto", k_max=8),
                channel=chan,
            )
        # metrics on: the DeviceTimeLedger (the row's scoreboard) only
        # exists on the telemetry plane
        server = InferenceServer(
            repo, chan, address="127.0.0.1:0", uds_address="auto",
            max_workers=n_streams + 2, temporal=temporal,
            metrics_port="auto",
        )
        server.start()
        try:
            run_streams(  # compile tracker step + coast outside window
                server.uds_address, spec.name, n_streams=1,
                source=lambda i: synthetic_stream(
                    n_frames=6, fps=100.0, dynamics="static"
                ),
                deadline_s=60.0, stream_id_prefix="warm", realtime=False,
            )
            res = run_streams(
                server.uds_address, spec.name, n_streams=n_streams,
                source=lambda i: synthetic_stream(
                    n_frames=n_frames, fps=fps, n_objects=4, seed=i,
                    dynamics="static",
                ),
                deadline_s=duration_s + 120.0, realtime=False,
            )
            dev_s = 0.0
            if server.device_time is not None:
                dev_s = sum(
                    v
                    for k, v in server.device_time.device_seconds().items()
                    if "|stream:stream-" in k
                )
            summary = res.summary()
            frames = max(1, res.frames_ok)
            dev_per_frame = dev_s / frames
            # fixed-SLO capacity framing: one chip has 1 device-second
            # per wall second; a stream at `fps` consumes
            # dev_per_frame * fps of it
            spc = (
                1.0 / (dev_per_frame * fps) if dev_per_frame > 0 else 0.0
            )
            return {
                "streams_per_chip": spc,
                "device_seconds": dev_s,
                "frames_ok": res.frames_ok,
                "frames_coasted": summary["frames_coasted"],
                "id_switches": summary["id_switches"],
                "fragmentation": summary["fragmentation"],
                "coast_track_drops": summary["coast_track_drops"],
            }
        finally:
            server.stop()

    off = _window(reuse=False)
    on = _window(reuse=True)
    speedup = on["streams_per_chip"] / max(off["streams_per_chip"], 1e-9)
    row = {
        "metric": "temporal_reuse",
        "value": round(on["streams_per_chip"], 2),
        "unit": "streams/chip",
        "streams": n_streams,
        "replay_fps": fps,
        "detector_iters": detector_iters,
        "streams_per_chip_off": round(off["streams_per_chip"], 2),
        "streams_per_chip_on": round(on["streams_per_chip"], 2),
        "temporal_speedup": round(speedup, 3),
        "device_seconds_off": round(off["device_seconds"], 4),
        "device_seconds_on": round(on["device_seconds"], 4),
        "frames_coasted": on["frames_coasted"],
        "id_switches_off": off["id_switches"],
        "id_switches_on": on["id_switches"],
        "id_switch_delta": on["id_switches"] - off["id_switches"],
        "coast_track_drops": on["coast_track_drops"],
        "precision": "f32",
    }
    if on["frames_ok"] == 0 or off["frames_ok"] == 0:
        row["degraded"] = "a replay window completed no frames"
    return row


def _serve_multitenant_row(duration_s: float) -> dict:
    """ISSUE 9 multi-tenant lifecycle under pressure: five synthetic
    models (distinct multipliers, synthetic 100-byte HBM costs) over a
    budget that admits two, split across three tenants with 8/2/1
    shares. Three concurrent closed-loop pools (one per tenant) force
    paging and fair-share arbitration at once; the row reports
    promotion latency quantiles from the lifecycle histogram and
    per-tenant goodput from the scheduler's DRR accounting. Synthetic
    on purpose — the row measures the paging/fair-share machinery, not
    model math."""
    import threading as _threading

    from triton_client_tpu.channel.base import InferRequest
    from triton_client_tpu.channel.tpu_channel import TPUChannel
    from triton_client_tpu.config import ModelSpec, TensorSpec
    from triton_client_tpu.obs.histogram import quantile_from_snapshot
    from triton_client_tpu.runtime.continuous import (
        ContinuousBatchingChannel,
    )
    from triton_client_tpu.runtime.lifecycle import (
        ModelLifecycleManager,
        TenantPolicy,
        TenantTable,
    )
    from triton_client_tpu.runtime.repository import ModelRepository
    from triton_client_tpu.runtime.server import InferenceServer
    from triton_client_tpu.utils.loadgen import run_pool

    repo = ModelRepository()
    models = [("mt_a", 2.0), ("mt_b", 3.0), ("mt_c", 4.0),
              ("mt_d", 5.0), ("mt_e", 6.0)]
    for name, k in models:
        spec = ModelSpec(
            name=name, version="1", max_batch_size=8,
            inputs=(TensorSpec("x", (-1, 64), "FP32"),),
            outputs=(TensorSpec("y", (-1, 64), "FP32"),),
            extra={"param_bytes": 100},
        )
        repo.register(
            spec,
            lambda inputs, k=k: {
                "y": np.asarray(inputs["x"], np.float32) * k
            },
            device_fn=lambda inputs, k=k: {"y": inputs["x"] * k},
        )
    table = TenantTable([
        TenantPolicy(name="gold", share=8, models=("mt_a", "mt_b"),
                     pinned=("mt_a",)),
        TenantPolicy(name="silver", share=2, models=("mt_c",)),
        TenantPolicy(name="bronze", share=1, models=("mt_d", "mt_e")),
    ])
    base = TPUChannel(repo)
    lifecycle = ModelLifecycleManager(repo, budget_bytes=250, tenants=table)
    base.attach_lifecycle(lifecycle)
    batching = ContinuousBatchingChannel(base, max_batch=8)
    batching.attach_tenants(table)
    server = InferenceServer(
        repo, batching, address="127.0.0.1:0", metrics_port=0,
        lifecycle=lifecycle, tenants=table,
    )
    server.start()
    try:
        addr = f"127.0.0.1:{server.port}"
        feed = {"x": np.ones((2, 64), np.float32)}
        # one pool per tenant, concurrently: gold/silver/bronze each
        # hammer one of their models; bronze's model set also rotates
        # residency pressure through the 250-byte budget
        results = {}

        def pool(tenant, model):
            results[tenant] = run_pool(
                addr, model, feed, clients=4,
                duration_s=duration_s, deadline_s=60.0,
            )

        threads = [
            _threading.Thread(target=pool, args=(t, m), daemon=True)
            for t, m in (("gold", "mt_a"), ("silver", "mt_c"),
                         ("bronze", "mt_d"))
        ]
        for t in threads:
            t.start()
        # a low-rate scan over every model keeps cold ones promoting
        t_end = time.perf_counter() + duration_s
        scans = 0
        while time.perf_counter() < t_end:
            for name, _ in models:
                try:
                    batching.do_inference(
                        InferRequest(model_name=name, inputs=feed)
                    )
                    scans += 1
                except Exception:
                    pass
            time.sleep(0.05)
        for t in threads:
            t.join(timeout=duration_s + 60.0)
        lc = lifecycle.stats()
        promo = lc["promotion_latency"]
        served = batching.stats().get("tenant_served_frames", {})
        total_fps = sum(
            r.fps for r in results.values() if r is not None
        )
        row = {
            "metric": "multitenant_served_fps",
            "value": round(total_fps, 2),
            "unit": "frames/sec",
            "models_registered": len(models),
            "hbm_budget_bytes": lc["budget_bytes"],
            "hbm_resident_bytes": lc["resident_bytes"],
            "promotions": lc.get("promotions", 0),
            "evictions": lc.get("evictions", 0),
            "promotion_p50_ms": (
                round(quantile_from_snapshot(promo, 0.50) * 1e3, 3)
                if promo.get("count") else None
            ),
            "promotion_p99_ms": (
                round(quantile_from_snapshot(promo, 0.99) * 1e3, 3)
                if promo.get("count") else None
            ),
            "tenant_goodput_fps": {
                t: round(r.fps, 2) for t, r in results.items()
                if r is not None
            },
            "tenant_served_frames": {k: int(v) for k, v in served.items()},
            "tenant_shares": {"gold": 8, "silver": 2, "bronze": 1},
            "scan_requests": scans,
            "precision": "f32",
        }
        if not results:
            row["degraded"] = "no tenant pool completed"
        return row
    finally:
        server.stop()
        batching.close()


def validate_pallas_nms() -> dict:
    """Once per bench session: run the Pallas NMS kernel and the XLA
    loop on the LIVE backend on the same inputs and require identical
    selected-index sequences — a Mosaic lowering regression fails the
    bench run, not a customer (VERDICT r1: interpret-mode tests alone
    never exercised the real TPU lowering)."""
    from triton_client_tpu.ops.nms import _nms_xla
    from triton_client_tpu.ops.pallas_nms import nms_pallas

    if jax.default_backend() != "tpu":
        return {"pallas_nms_on_tpu": "skipped (backend=%s)" % jax.default_backend()}
    rng = np.random.default_rng(7)
    checked = 0
    for n in (128, 512, 1024):
        centers = rng.uniform(0, 512, (n, 2))
        wh = rng.uniform(8, 96, (n, 2))
        boxes = jnp.asarray(
            np.concatenate([centers - wh / 2, centers + wh / 2], axis=1),
            jnp.float32,
        )
        scores = jnp.asarray(rng.uniform(0.01, 1.0, n), jnp.float32)
        for thresh in (0.3, 0.45, 0.6):
            pi, pv = nms_pallas(
                boxes, scores, iou_thresh=thresh, max_det=128, interpret=False
            )
            xi, xv = _nms_xla(boxes, scores, thresh, max_det=128)
            pi, pv, xi, xv = (np.asarray(a) for a in (pi, pv, xi, xv))
            if not (np.array_equal(pv, xv) and np.array_equal(pi[pv], xi[xv])):
                raise AssertionError(
                    f"Pallas NMS diverges from XLA on TPU (n={n}, "
                    f"thresh={thresh}): pallas={pi[pv][:10]} xla={xi[xv][:10]}"
                )
            checked += 1
    return {"pallas_nms_on_tpu": f"identical to XLA loop ({checked} cases)"}


def warmup_with_retries(c, drop, attempts: int = 3, backoff_s: float = 5.0):
    """True if the config warmed; False if it was dropped. The
    tunnel's remote-compile intermittently closes the response body
    mid-read; a fresh attempt usually lands and a transient hiccup
    must not cost a secondary its row (TWO consecutive hiccups were
    observed dropping the b64 row — hence attempts=3)."""
    for attempt in range(attempts):
        try:
            c.warmup()
            return True
        except Exception as e:
            if attempt == attempts - 1:
                drop(c, "warmup", e)
                return False
            print(
                f"{c.name} warmup retry {attempt + 1} after: {e}",
                file=sys.stderr,
            )
            time.sleep(backoff_s)
    return False  # pragma: no cover


# r3-measured FRESH-compile warmup costs (BENCH_r03.json stderr) —
# used only to schedule warmups against the budget; observed actuals
# recalibrate them, so a cache-warm run (~20x cheaper) schedules
# everything and a fresh run sheds the expensive tail first.
WARMUP_EST_S = {
    "yolov5n": 90.0, "yolov5n_bf16": 69.0, "yolov5n_mxu": 79.0,
    "yolov5n_mxu_bf16": 82.0, "yolov5n_b64": 244.0,
    "yolov5n_b64_mxu_bf16": 250.0,
    "pointpillars": 50.0, "pointpillars_uniform": 48.0,
    "second_iou": 46.0, "second_sparse005": 154.0, "centerpoint": 44.0,
}

# shared with the SIGTERM flush: rows already emitted, live configs,
# measured rtt, accumulated results for BENCH_LOCAL.json
_STATE = {
    "configs": [], "provisional": [], "emitted": set(), "rtt": 0.0,
    "results": [], "nms_check": None,
}


def _emit_row(row: dict, primary: bool) -> None:
    """Print a metric row the moment it exists (VERDICT r3 #1a): the
    primary owns the one stdout line, secondaries stream to stderr —
    a driver timeout after this point cannot un-capture the row."""
    print(json.dumps(row), file=sys.stdout if primary else sys.stderr,
          flush=True)
    _STATE["emitted"].add(row["metric"])
    _STATE["results"].append(row)


def _write_local() -> None:
    try:  # best-effort: the stdout contract must survive
        with open("BENCH_LOCAL.json", "w") as f:
            json.dump(
                {"nms_check": _STATE["nms_check"],
                 "results": _STATE["results"]},
                f, indent=2,
            )
    except OSError as e:
        print(f"could not write BENCH_LOCAL.json: {e}", file=sys.stderr)


def _flush_rows_on_term(signum, frame):
    """Last-resort row insurance: if the driver's clock fires anyway,
    emit every config that has trial samples from pure numpy (no jax
    calls — a device dispatch inside a signal handler can deadlock
    against the interrupted main thread) and exit."""
    try:
        configs = _STATE["configs"]
        for c in configs + _STATE["provisional"]:
            if c.metric in _STATE["emitted"] or len(c.trial_ms) < 3:
                continue
            try:
                row = c.result(_STATE["rtt"], with_latency=False)
                row["provisional"] = "flushed on SIGTERM"
                _emit_row(row, primary=bool(configs) and c is configs[0])
            except Exception:
                pass
        _write_local()
    finally:
        os._exit(1)


def main() -> None:
    signal.signal(signal.SIGTERM, _flush_rows_on_term)
    nms_check = _STATE["nms_check"] = validate_pallas_nms()
    print(json.dumps(nms_check), file=sys.stderr)

    rtt = _STATE["rtt"] = _tunnel_rtt_ms()
    print(f"tunnel rtt {rtt:.2f} ms, budget {BUDGET_S:.0f}s",
          file=sys.stderr)

    # VALUE order (VERDICT r3 #1c, reworked r5): the primary is
    # mandatory; then the headline winner, the 3D family rows, the b64
    # peak claim (provisional-capable), the reference-grid sparse
    # SECOND, and only then the dtype/layout delta rows — a tight
    # budget sheds the A/Bs that BASELINE.md already records, not the
    # family rows or the claims the verdicts asked to see captured.
    factories = [
        ("yolov5n", make_yolov5),
        # fastest b8 config: the two levers stack (base 6.26 ms, mxu
        # 5.21, bf16 5.28, mxu+bf16 4.57 ms = -27%)
        ("yolov5n_mxu_bf16",
         lambda: make_yolov5(mxu=True, dtype=jnp.bfloat16)),
        ("pointpillars", make_pointpillars),
        ("centerpoint", make_centerpoint),
        ("second_iou", make_second),
        # the peak-per-chip claim (README): batch amortizes the small-
        # channel convs' fixed overhead. Ordered DIRECTLY after the
        # family rows (r5): in r4/r5 slow phases it sat behind four
        # delta rows whose warmups ate the budget, so the one row the
        # verdict asked to see driver-captured was always the one
        # shed. When the full protocol no longer fits it degrades to a
        # shortened provisional block instead of shedding silently.
        ("yolov5n_b64_mxu_bf16",
         lambda: make_yolov5(batch=64, mxu=True, dtype=jnp.bfloat16)),
        # the reference-grid sparse SECOND is a family row, not a
        # delta: it outranks the 2D dtype/layout A/Bs
        ("second_sparse005", make_second_sparse),
        # delta rows (dtype/layout/distribution A/Bs already recorded
        # in BASELINE.md): the right things to shed in a slow phase
        ("yolov5n_bf16", lambda: make_yolov5(dtype=jnp.bfloat16)),
        # MXU-shaped layout (s2d stem + 32ch floor): same detection
        # function, losslessly imported weights, measured +16% at b8
        ("yolov5n_mxu", lambda: make_yolov5(mxu=True)),
        # uniform-cloud delta config: same pipeline, r2's input
        # distribution — quantifies what structured scenes changed
        ("pointpillars_uniform",
         lambda: make_pointpillars(structured=False)),
        ("yolov5n_b64", lambda: make_yolov5(batch=64)),
    ]
    # configs whose row may be emitted from a shortened trial block
    # when the full protocol no longer fits the budget. ONLY the peak
    # claim: r5 observed the b64-fp32 delta row taking this path and
    # burning ~400 s (fresh compile through a slow phase) straight out
    # of the serving reserve — a delta row is shed outright, never
    # bought at the serving rows' expense
    PROVISIONAL_OK = {"yolov5n_b64_mxu_bf16"}

    configs = _STATE["configs"]

    def drop(c, stage, e):
        """A secondary failing mid-bench must never cost the primary
        its one-line stdout contract: log, remove, keep going. The
        primary config failing is fatal by design."""
        if configs and c is configs[0]:
            raise e
        print(f"{c.name} dropped ({stage}): {e}", file=sys.stderr)
        configs.remove(c)

    # Build + warm up lazily in value order, scheduling each secondary
    # against the remaining budget (VERDICT r3 #1b): a config we skip
    # costs a stderr line, never the captured rows. The estimate
    # recalibrates from observed actuals so a cache-warm run (compiles
    # ~20x cheaper) keeps everything.
    est_ratio = 1.0
    for label, factory in factories:
        planned = len(configs) + 1
        # what the rest of the run needs if this config joins: trials
        # (~1 s chip work each + tunnel jitter), latency profiles,
        # primary extras, result emission slack — plus the serving
        # stage's reserve for EVERY secondary (r5: when only the b64
        # tails carried the reserve, mid-value delta rows were
        # admitted right through the serving budget and the serving
        # stage starved at 34s left; no secondary may eat the reserve)
        need_after = TRIALS * planned * 1.4 + 3.0 * planned + 45.0 + 30.0
        if configs:
            need_after += SERVING_RESERVE_S
        est = WARMUP_EST_S.get(label, 90.0) * est_ratio
        if configs and _remaining() < est + need_after:
            # Provisional path: a config whose row matters more than
            # protocol uniformity (the b64 peak claims) runs a
            # SHORTENED block — warmup + 3 trials + immediate emission
            # — if at least that fits; the row is labeled provisional
            # so readers know it skipped the interleaved regime.
            # the serving rows outrank BOTH b64 tails: a provisional
            # block is admitted only when the serving reserve survives
            short_need = est + 3 * 1.6 + 8.0 + SERVING_RESERVE_S
            if label in PROVISIONAL_OK and _remaining() >= short_need:
                try:
                    c = factory()
                    # visible to the SIGTERM flush (it runs exactly in
                    # the budget-exhausted regime this block lives in)
                    # but NOT in configs — the main trial loop must not
                    # re-run a provisional config
                    _STATE["provisional"].append(c)
                    t0 = time.perf_counter()
                    c.warmup()
                    est_ratio = max(
                        0.05,
                        0.5 * est_ratio
                        + 0.5 * ((time.perf_counter() - t0)
                                 / WARMUP_EST_S.get(label, 90.0)),
                    )
                    for _ in range(3):
                        c.run_trial()
                    row = c.result(rtt, with_latency=False)
                    row["provisional"] = (
                        "shortened 3-trial block (budget); not "
                        "interleaved with the other configs"
                    )
                    _emit_row(row, primary=False)
                except Exception as e:
                    print(f"{label} provisional block failed: {e}",
                          file=sys.stderr)
                continue
            print(
                f"{label} warmup skipped: {_remaining():.0f}s left < "
                f"{est:.0f}s est warmup + {need_after:.0f}s to finish",
                file=sys.stderr,
            )
            continue
        try:
            c = factory()
        except Exception as e:
            if not configs:
                # the primary failing to BUILD is as fatal as its
                # warmup/trials failing: a secondary must never be
                # silently promoted to the stdout primary row
                raise
            print(f"{label} bench setup failed: {e}", file=sys.stderr)
            continue
        configs.append(c)
        t0 = time.perf_counter()
        if not warmup_with_retries(c, drop):
            continue
        took = time.perf_counter() - t0
        # EMA toward the observed fresh/warm ratio: a cache-warm run
        # (~20x under estimate) schedules everything, a contended slow
        # phase (over estimate) sheds the expensive tail sooner
        est_ratio = max(
            0.05,
            0.5 * est_ratio + 0.5 * (took / WARMUP_EST_S.get(label, 90.0)),
        )
        print(
            f"warmup {c.name}: {took:.1f}s "
            f"(flops/call={c.flops_per_call})",
            file=sys.stderr,
        )

    t0 = time.perf_counter()
    done_trials = 0
    for t in range(TRIALS):          # interleaved: A/B/C/D A/B/C/D ...
        for c in list(configs):
            try:
                c.run_trial()
            except Exception as e:
                drop(c, "trial", e)
        done_trials = t + 1
        print(
            f"trial {done_trials}/{TRIALS} done at "
            f"{time.perf_counter() - t0:.1f}s",
            file=sys.stderr,
        )
        if done_trials >= MIN_TRIALS and _remaining() < (
            3.0 * len(configs) + 30.0 + len(configs) * 1.4
            # the serving stage's reserve survives the trial loop too
            # (r5: admission guarded it but trials ran through it)
            + SERVING_RESERVE_S
        ):
            print(
                f"stopping trials at {done_trials}/{TRIALS}: "
                f"{_remaining():.0f}s left", file=sys.stderr,
            )
            break

    # emit secondaries IMMEDIATELY (VERDICT r3 #1a) — oldest protocol
    # first so a timeout mid-emission still keeps the earlier rows;
    # latency profiling (LAT_CALLS forced readbacks per config, 50 s+
    # across many configs in a slow phase) must not eat the serving
    # reserve — rows degrade to latency-free before serving starves
    for c in list(configs[1:]):
        try:
            _emit_row(
                c.result(
                    rtt,
                    with_latency=_remaining() > 20.0 + SERVING_RESERVE_S,
                ),
                primary=False,
            )
        except Exception as e:
            drop(c, "result", e)

    # the primary gets a second block of trials (2x total): its b8
    # config was the noisiest in r2 (trial_spread 0.219) and round-
    # over-round deltas hang off it. The extras stay in the interleaved
    # REGIME by alternating with a spacer config whose extra samples
    # are discarded — solo back-to-back dispatches would measure a
    # different tunnel phase than the protocol every other sample used.
    if configs and configs[0].trial_ms and _remaining() > (
        45.0 + SERVING_RESERVE_S
    ):
        spacer = configs[1] if len(configs) > 1 else None
        try:
            for t in range(TRIALS):
                if _remaining() < 15.0 + SERVING_RESERVE_S:
                    print(
                        f"primary extras stopped at {t}/{TRIALS}: "
                        f"{_remaining():.0f}s left", file=sys.stderr,
                    )
                    break
                configs[0].run_trial()
                if spacer is not None:
                    spacer.run_trial()
                    spacer.trial_ms.pop()
            else:
                print(f"primary extra trials done ({TRIALS})",
                      file=sys.stderr)
        except Exception as e:
            # the interleaved samples already satisfy the contract;
            # extras are a bonus and must not cost the stdout line
            print(f"primary extra trials aborted: {e}", file=sys.stderr)

    _emit_row(
        # the primary's 20 forced readbacks are budget spend too: in a
        # stalled phase they degrade to a latency-free row rather than
        # eat the serving reserve (the last unguarded stage, r5)
        configs[0].result(
            rtt, with_latency=_remaining() > 20.0 + SERVING_RESERVE_S
        ),
        primary=True,
    )
    _write_local()
    _save_flops_sidecar()

    # serving stage is strictly best-effort after the contract rows:
    # fresh it precompiles every merge size (minutes over the tunnel),
    # so it only starts with real budget left
    if _remaining() > SERVING_FLOOR_S:
        try:
            # window sized to the leftover budget (post-fix serving
            # runs ~15 fps, so even a minimum window resolves ~20
            # device batches); each transport's row is emitted the
            # moment its window closes, so a cap landing mid-stage
            # keeps the wire row
            measure_serving(
                rtt,
                duration_s=min(
                    SERVING_MAX_WINDOW_S,
                    max(
                        SERVING_MIN_WINDOW_S,
                        (_remaining() - SERVING_TAIL_S) / 5,
                    ),
                ),
                on_row=lambda row: (_emit_row(row, primary=False),
                                    _write_local()),
            )
            print("serving bench done", file=sys.stderr)
        except Exception as e:
            print(f"serving bench failed: {e}", file=sys.stderr)
        _write_local()
        # multi-tenant lifecycle row: synthetic and cheap (~10 s), but
        # only with budget left after the real serving windows
        if _remaining() > 40.0:
            try:
                row = _serve_multitenant_row(
                    duration_s=min(10.0, max(5.0, _remaining() - 30.0))
                )
                _emit_row(row, primary=False)
                _write_local()
            except Exception as e:
                print(f"multitenant bench failed: {e}", file=sys.stderr)
        else:
            print(
                f"multitenant row skipped: {_remaining():.0f}s left",
                file=sys.stderr,
            )
        # streaming-session replay row (ISSUE 15): synthetic and cheap
        # like the multitenant row — paced replay, so the window IS the
        # duration; last in the serving stage's value order
        if _remaining() > 40.0:
            try:
                row = _serve_streaming_sessions_row(
                    duration_s=min(8.0, max(4.0, _remaining() - 30.0))
                )
                _emit_row(row, primary=False)
                _write_local()
            except Exception as e:
                print(f"streaming sessions bench failed: {e}",
                      file=sys.stderr)
        else:
            print(
                f"streaming sessions row skipped: {_remaining():.0f}s "
                "left", file=sys.stderr,
            )
        # quality-plane sidecar row (ISSUE 17): synthetic and cheap —
        # two short paced windows (sampling off/on) on an echo detector
        if _remaining() > 40.0:
            try:
                row = _serve_quality_plane_row(
                    duration_s=min(8.0, max(4.0, _remaining() - 30.0))
                )
                _emit_row(row, primary=False)
                _write_local()
            except Exception as e:
                print(f"quality plane bench failed: {e}", file=sys.stderr)
        else:
            print(
                f"quality plane row skipped: {_remaining():.0f}s left",
                file=sys.stderr,
            )
        # temporal-reuse row (ISSUE 19): two synthetic replay windows
        # (reuse off/on) on an echo detector with a simulated device
        # cost — the streams-per-chip scoreboard off the ledger
        if _remaining() > 40.0:
            try:
                row = _serve_temporal_reuse_row(
                    duration_s=min(8.0, max(4.0, _remaining() - 30.0))
                )
                _emit_row(row, primary=False)
                _write_local()
            except Exception as e:
                print(f"temporal reuse bench failed: {e}", file=sys.stderr)
        else:
            print(
                f"temporal reuse row skipped: {_remaining():.0f}s left",
                file=sys.stderr,
            )
    else:
        print(
            f"serving stage skipped: {_remaining():.0f}s left of "
            f"{BUDGET_S:.0f}s budget", file=sys.stderr,
        )


if __name__ == "__main__":
    main()
