"""Function-style API: parity with the reference's pre-refactor v1 stack.

The reference keeps an older, non-OO copy of its client layer alive for
evaluate.py (utils/preprocess.py, utils/postprocess.py — SURVEY.md
section 2 #9): free functions for model parsing, image scaling modes,
filesystem batch generation, byte deserialization, and per-model box
extraction. This module is the same surface expressed over the new
framework's primitives, so scripts written against the v1 function
names port by changing an import. Numeric semantics:

- scaling modes NONE/INCEPTION/VGG/COCO match utils/preprocess.py:147-157
- deserialize_bytes_* replaces the per-scalar struct.unpack_from loop
  (utils/postprocess.py:12-34) with one numpy frombuffer — the loop was
  a documented hot spot (SURVEY.md section 2 #14)
- extract_boxes_yolov5 keeps the (n, 6) [x1,y1,x2,y2,conf,cls] contract
  of utils/postprocess.py:105-199
"""

from __future__ import annotations

import os
from typing import Iterator, Sequence

import numpy as np

from triton_client_tpu.config import ModelSpec, config_dtypes

_NP_DTYPES = {k: v for k, v in config_dtypes().items() if v is not None}


def model_dtype_to_np(model_dtype: str) -> np.dtype:
    """KServe/Triton dtype string -> numpy (utils/preprocess.py:17-40)."""
    if model_dtype not in _NP_DTYPES:
        raise ValueError(f"unsupported model dtype {model_dtype!r}")
    return np.dtype(_NP_DTYPES[model_dtype])


def load_class_names(namesfile: str) -> list[str]:
    """*.names file -> class list (utils/preprocess.py:42-49)."""
    with open(namesfile) as f:
        return [line.strip() for line in f if line.strip()]


def parse_model(spec: ModelSpec) -> tuple:
    """ModelSpec -> (input_name, output_names, c, h, w, format, dtype)
    — the v1 tuple contract (utils/preprocess.py:51-126). The format
    element is 'NHWC'/'NCHW' (inferred from the input layout/shape)
    instead of the protobuf enum."""
    if len(spec.inputs) != 1:
        raise ValueError(f"expecting 1 input, got {len(spec.inputs)}")
    inp = spec.inputs[0]
    if len(inp.shape) == 4:  # batch dim present
        shape = list(inp.shape[1:])
    elif len(inp.shape) == 3:
        shape = list(inp.shape)
    else:
        raise ValueError(
            f"expecting a 3-dim image input (+batch), got {inp.shape}"
        )
    layout = inp.layout or ("NCHW" if shape[0] in (1, 3) else "NHWC")
    if layout.endswith("NCHW") or layout == "CHW":
        c, h, w = shape
        fmt = "NCHW"
    else:
        h, w, c = shape
        fmt = "NHWC"
    return (
        inp.name,
        [o.name for o in spec.outputs],
        c,
        h,
        w,
        fmt,
        inp.dtype,
    )


def image_adjust(
    img,
    format: str = "NCHW",
    dtype: str = "FP32",
    c: int = 3,
    h: int = 512,
    w: int = 512,
    scaling: str = "NONE",
) -> np.ndarray:
    """Path or HWC uint8 array -> scaled (c, h, w) / (h, w, c) tensor.

    Scaling modes per utils/preprocess.py:147-157: INCEPTION
    ``x/127.5 - 1``; VGG ``x - (123,117,104)`` (128 for mono); COCO
    ``x/255``; anything else passes through.
    """
    if isinstance(img, (str, os.PathLike)):
        from triton_client_tpu.io.sources import _read_image_rgb

        img = _read_image_rgb(os.fspath(img))
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if c == 1 and arr.shape[2] == 3:
        # ITU-R 601 luma, same intent as PIL convert('L')
        arr = (arr @ np.array([0.299, 0.587, 0.114]))[..., None]
    if arr.shape[:2] != (h, w):
        try:
            import cv2

            arr = cv2.resize(
                arr.astype(np.uint8), (w, h), interpolation=cv2.INTER_LINEAR
            )
            if arr.ndim == 2:
                arr = arr[:, :, None]
        except ImportError:
            from triton_client_tpu.ops.preprocess import resize_bilinear

            arr = np.asarray(resize_bilinear(arr.astype(np.float32), (h, w)))
    # Scale in f32, cast to the model dtype last: casting first wraps
    # integer dtypes (VGG mean-subtract on uint8) and promotes the
    # division modes to float64 regardless of the requested dtype.
    if scaling == "INCEPTION":
        scaled = (arr.astype(np.float32) / 127.5) - 1
    elif scaling == "VGG":
        mean = (128,) if c == 1 else (123, 117, 104)
        scaled = arr.astype(np.float32) - np.asarray(mean, np.float32)
    elif scaling == "COCO":
        scaled = arr.astype(np.float32) / 255.0
    else:
        scaled = arr
    scaled = scaled.astype(model_dtype_to_np(dtype))
    if format == "NCHW":
        scaled = np.transpose(scaled, (2, 0, 1))
    return np.ascontiguousarray(scaled)


def request_generator(
    image_filename: str,
    batch_size: int = 1,
    *,
    c: int = 3,
    h: int = 512,
    w: int = 512,
    format: str = "NCHW",
    dtype: str = "FP32",
    scaling: str = "NONE",
    limit: int = 0,
) -> Iterator[tuple[np.ndarray, list[str]]]:
    """Directory (jpg/png) or single file -> (batched tensor, filenames)
    pairs — the filesystem batch path of utils/preprocess.py:185-263,
    minus the protobuf plumbing (the channel codec adds that when the
    batch is dispatched). The last batch repeats its final image to
    stay full-shape, matching the reference's wraparound behavior."""
    if os.path.isdir(image_filename):
        filenames = sorted(
            os.path.join(image_filename, f)
            for f in os.listdir(image_filename)
            if f.lower().endswith((".jpg", ".jpeg", ".png"))
        )
    elif os.path.isfile(image_filename):
        filenames = [image_filename]
    else:
        raise FileNotFoundError(image_filename)
    if limit:
        filenames = filenames[:limit]
    if not filenames:
        raise FileNotFoundError(f"no jpg/png under {image_filename}")

    batch, names = [], []
    for fn in filenames:
        batch.append(image_adjust(fn, format, dtype, c, h, w, scaling))
        names.append(fn)
        if len(batch) == batch_size:
            yield np.stack(batch), names
            batch, names = [], []
    if batch:
        while len(batch) < batch_size:  # pad final partial batch
            batch.append(batch[-1])
            names.append(names[-1])
        yield np.stack(batch), names


# --- wire codec (vectorized replacement for the v1 scalar loops) ---------


def deserialize_bytes_float(encoded: bytes | np.ndarray) -> np.ndarray:
    """raw little-endian FP32 bytes -> float32 array. One frombuffer vs
    the reference's per-scalar struct.unpack_from loop
    (utils/postprocess.py:12-22, clients/postprocess/base_postprocess.py:15-25)."""
    buf = encoded.tobytes() if isinstance(encoded, np.ndarray) else bytes(encoded)
    return np.frombuffer(buf, dtype="<f4").copy()


def deserialize_bytes_int(encoded: bytes | np.ndarray) -> np.ndarray:
    """raw little-endian INT64 bytes -> int64 array
    (utils/postprocess.py:24-34 semantics)."""
    buf = encoded.tobytes() if isinstance(encoded, np.ndarray) else bytes(encoded)
    return np.frombuffer(buf, dtype="<i8").copy()


# --- box math (numpy, v1 signatures: utils/postprocess.py:36-103) --------


def xywh2xyxy(x: np.ndarray) -> np.ndarray:
    y = np.array(x, dtype=np.float32, copy=True)
    y[..., 0] = x[..., 0] - x[..., 2] / 2
    y[..., 1] = x[..., 1] - x[..., 3] / 2
    y[..., 2] = x[..., 0] + x[..., 2] / 2
    y[..., 3] = x[..., 1] + x[..., 3] / 2
    return y


def box_iou(box1: np.ndarray, box2: np.ndarray) -> np.ndarray:
    """(N, 4) x (M, 4) xyxy -> (N, M) IoU (utils/postprocess.py:45-67)."""
    a1 = np.maximum(box1[:, None, :2], box2[None, :, :2])
    a2 = np.minimum(box1[:, None, 2:4], box2[None, :, 2:4])
    inter = np.prod(np.clip(a2 - a1, 0, None), axis=2)
    area1 = np.prod(box1[:, 2:4] - box1[:, :2], axis=1)
    area2 = np.prod(box2[:, 2:4] - box2[:, :2], axis=1)
    return inter / np.maximum(area1[:, None] + area2[None, :] - inter, 1e-9)


def nms_cpu(
    boxes: np.ndarray, confs: np.ndarray, nms_thresh: float = 0.5
) -> np.ndarray:
    """Greedy CPU NMS returning kept indices (utils/postprocess.py:69-103
    semantics — host-side fallback; the TPU path uses ops.nms)."""
    order = np.argsort(-np.asarray(confs))
    boxes = np.asarray(boxes, np.float32)
    keep = []
    alive = np.ones(len(order), bool)
    for oi, idx in enumerate(order):
        if not alive[oi]:
            continue
        keep.append(int(idx))
        rest = order[oi + 1 :]
        mask = alive[oi + 1 :]
        if not rest.size:
            break
        ious = box_iou(boxes[idx : idx + 1], boxes[rest]).reshape(-1)
        alive[oi + 1 :] = mask & (ious <= nms_thresh)
    return np.asarray(keep, np.int64)


# --- per-model extraction (v1 contracts) ---------------------------------


def extract_boxes_yolov5(
    prediction: np.ndarray,
    conf_thres: float = 0.6,
    iou_thres: float = 0.45,
    max_det: int = 300,
) -> list[np.ndarray]:
    """(B, N, 5+nc) raw YOLOv5 head -> per-image (n, 6)
    [x1,y1,x2,y2,conf,cls] float32 (utils/postprocess.py:105-199 /
    clients/postprocess/yolov5_postprocess.py:28-125). Runs the jitted
    fixed-shape TPU postprocess and strips padding on the way out."""
    from triton_client_tpu.ops.detect_postprocess import extract_boxes

    pred = np.asarray(prediction, np.float32)
    if pred.ndim == 2:
        pred = pred[None]
    dets, valid = extract_boxes(
        pred, conf_thresh=conf_thres, iou_thresh=iou_thres, max_det=max_det
    )
    dets, valid = np.asarray(dets), np.asarray(valid)
    return [dets[i][valid[i].astype(bool)] for i in range(dets.shape[0])]


def extract_boxes_triton(
    outputs: dict[str, np.ndarray] | Sequence[np.ndarray],
    conf_thresh: float = 0.4,
    nms_thresh: float = 0.6,
) -> list[list[list[float]]]:
    """YOLOv4 two-output contract: confs [B, num, nc] + boxes
    [B, num, 1, 4] -> per-image list of
    [x1, y1, x2, y2, conf, conf, cls] rows (conf duplicated — the v1
    wire quirk preserved; utils/postprocess.py:201-266 semantics).

    Per image: rows gate on the per-box max class confidence, then
    greedy NMS runs independently per argmax class; surviving rows are
    emitted class-by-class in ascending class order, score-descending
    within a class — the exact v1 ordering. Accepts the two arrays, a
    {'confs', 'boxes'} dict, or an InferResponse-style outputs dict
    keyed by the served names."""
    if isinstance(outputs, dict):
        confs = outputs.get("confs")
        boxes = outputs.get("boxes")
        if confs is None or boxes is None:
            # served-name fallback: pair by shape, not dict order. Only
            # an UNambiguous signature is accepted — boxes as the 4-D
            # [B, num, 1, 4] tensor, or exactly one of the pair with
            # trailing dim 4. A 4-class model whose boxes arrive
            # pre-squeezed to (B, num, 4) makes both arrays look alike;
            # raise rather than guess confs for boxes.
            vals = [np.asarray(v) for v in outputs.values()]
            if len(vals) != 2:
                raise ValueError(
                    "extract_boxes_triton needs exactly the confs + boxes "
                    f"outputs; got {len(vals)} arrays"
                )
            a, b = vals
            a_4d = a.ndim == 4 and a.shape[-1] == 4
            b_4d = b.ndim == 4 and b.shape[-1] == 4
            a_3d = a.ndim == 3 and a.shape[-1] == 4
            b_3d = b.ndim == 3 and b.shape[-1] == 4
            if a_4d != b_4d:
                boxes_first = a_4d
            elif a_3d != b_3d:
                boxes_first = a_3d
            else:
                raise ValueError(
                    "extract_boxes_triton: cannot tell confs from boxes by "
                    f"shape ({a.shape} vs {b.shape}); pass a dict keyed "
                    "'confs'/'boxes' or serve boxes as [B, num, 1, 4]"
                )
            confs, boxes = (b, a) if boxes_first else (a, b)
    else:
        confs, boxes = outputs[0], outputs[1]
    confs = np.asarray(confs, np.float32)
    boxes = np.asarray(boxes, np.float32)
    if boxes.ndim == 4:  # [B, num, 1, 4] -> [B, num, 4]
        boxes = boxes[:, :, 0]
    num_classes = confs.shape[2]

    max_conf = confs.max(axis=2)
    max_id = confs.argmax(axis=2)

    batch_boxes: list[list[list[float]]] = []
    for i in range(boxes.shape[0]):
        gate = max_conf[i] > conf_thresh
        g_boxes, g_conf, g_id = boxes[i][gate], max_conf[i][gate], max_id[i][gate]
        rows: list[list[float]] = []
        for j in range(num_classes):
            sel = g_id == j
            if not sel.any():
                continue
            c_boxes, c_conf = g_boxes[sel], g_conf[sel]
            keep = nms_cpu(c_boxes, c_conf, nms_thresh)
            for k in keep:
                rows.append(
                    [
                        float(c_boxes[k, 0]),
                        float(c_boxes[k, 1]),
                        float(c_boxes[k, 2]),
                        float(c_boxes[k, 3]),
                        float(c_conf[k]),
                        float(c_conf[k]),
                        float(j),
                    ]
                )
        batch_boxes.append(rows)
    return batch_boxes


def extract_boxes_detectron(
    outputs: dict[str, np.ndarray] | Sequence[np.ndarray],
    conf_thres: float = 0.6,
) -> np.ndarray:
    """Server-side-NMS family (FCOS/RetinaNet): boxes/scores/classes in,
    (n, 6) out with a confidence gate — no client NMS, matching
    clients/postprocess/detectron_postprocess.py:26-38. Accepts the
    3-output dict (pred_boxes/scores/pred_classes) or a sequence in
    that order; the 4th reference output (dims) is unused there too."""
    if isinstance(outputs, dict):
        boxes = np.asarray(outputs["pred_boxes"], np.float32)
        scores = np.asarray(outputs["scores"], np.float32)
        classes = np.asarray(outputs["pred_classes"], np.float32)
    else:
        boxes, scores, classes = (np.asarray(o, np.float32) for o in outputs[:3])
    boxes = boxes.reshape(-1, 4)
    scores = scores.reshape(-1)
    classes = classes.reshape(-1)
    keep = scores >= conf_thres
    return np.concatenate(
        [boxes[keep], scores[keep, None], classes[keep, None]], axis=1
    )


def plot_boxes(
    img: np.ndarray,
    boxes: np.ndarray,
    savename: str | None = None,
    class_names: Sequence[str] = (),
) -> np.ndarray:
    """Draw (n, 6) detections on an RGB image; optionally save
    (utils/postprocess.py:324-366 role, via the new draw module)."""
    from triton_client_tpu.io.draw import draw_boxes

    out = draw_boxes(img, np.asarray(boxes, np.float32), None, tuple(class_names))
    if savename:
        try:
            import cv2

            cv2.imwrite(savename, out[..., ::-1])
        except ImportError:
            from PIL import Image

            Image.fromarray(out).save(savename)
    return out
