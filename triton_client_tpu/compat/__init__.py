"""Function-style v1 compatibility API (the reference's legacy stack)."""

from triton_client_tpu.compat.functional import (  # noqa: F401
    box_iou,
    deserialize_bytes_float,
    deserialize_bytes_int,
    extract_boxes_detectron,
    extract_boxes_triton,
    extract_boxes_yolov5,
    image_adjust,
    load_class_names,
    model_dtype_to_np,
    nms_cpu,
    parse_model,
    plot_boxes,
    request_generator,
    xywh2xyxy,
)
