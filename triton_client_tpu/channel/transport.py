"""Endpoint transport negotiation for the host serving path.

BENCH_r05 put the problem in one row: yolov5n runs 1,685 fps/chip on
the device but 12.0 fps served over loopback gRPC — the host transport
is ~1% of the device ceiling, and the expensive part is not the
network, it is serializing a 786 KB frame into protobuf, copying it
through HTTP/2 framing, and deserializing it in the server process.
The fix (ROADMAP item 1) is to stop paying that tax whenever both ends
share a kernel: same-host endpoints ride POSIX shared memory, with the
gRPC message carrying only region coordinates.

This module is the one place that decides *which* transport an
endpoint gets, so `GRPCChannel`, the front-door router, the loadgen
dialer, and the `route` CLI all agree:

  endpoint                         class      shm eligible
  -------------------------------  ---------  ------------
  ``unix:/path`` / ``unix://...``  uds        yes
  ``localhost:8001``               local      yes
  ``127.0.0.1:8001`` (any 127.*)   local      yes
  ``[::1]:8001``                   local      yes
  anything else                    remote     no

Eligibility additionally requires a usable ``/dev/shm`` (absent in
some minimal containers); callers can always force the decision with
an explicit ``use_shared_memory=True/False``.
"""

from __future__ import annotations

import os

_SHM_DIR = "/dev/shm"

#: endpoint classes returned by :func:`classify`
UDS = "uds"
LOCAL = "local"
REMOTE = "remote"


def is_uds(endpoint: str) -> bool:
    """True for gRPC unix-socket targets (``unix:/path``,
    ``unix:///abs/path``, and the ``unix-abstract:`` namespace)."""
    return endpoint.startswith(("unix:", "unix-abstract:"))


def uds_path(endpoint: str) -> str:
    """Filesystem path of a ``unix:`` target (``unix:///a/b`` and
    ``unix:/a/b`` both mean ``/a/b``)."""
    if not is_uds(endpoint):
        raise ValueError(f"not a unix-socket endpoint: {endpoint!r}")
    rest = endpoint.split(":", 1)[1]
    if rest.startswith("//"):
        rest = rest[2:]
        # unix://authority/path — gRPC reserves the authority slot;
        # the common ``unix:///abs`` form has an empty authority
        if not rest.startswith("/"):
            rest = "/" + rest.split("/", 1)[1] if "/" in rest else rest
    return rest


def classify(endpoint: str) -> str:
    """``uds`` / ``local`` / ``remote`` for one gRPC target string."""
    if is_uds(endpoint):
        return UDS
    host = endpoint
    # dns:// and ipv4:/ipv6: scheme prefixes resolve to their target
    for scheme in ("dns:///", "ipv4:", "ipv6:"):
        if host.startswith(scheme):
            host = host[len(scheme):]
            break
    if host.startswith("["):  # [::1]:8001
        host = host[1:].split("]", 1)[0]
    else:
        host = host.rsplit(":", 1)[0]
    if host in ("localhost", "::1") or host.startswith("127."):
        return LOCAL
    return REMOTE


def shm_supported() -> bool:
    """Whether this host can back shm regions at all."""
    return os.path.isdir(_SHM_DIR) and os.access(_SHM_DIR, os.W_OK)


def shm_eligible(endpoint: str) -> bool:
    """Default-on decision for the shared-memory transport: both ends
    on this host (loopback TCP or a unix socket) and /dev/shm usable.
    This is the *auto* answer — an explicit ``use_shared_memory=``
    always wins."""
    return classify(endpoint) != REMOTE and shm_supported()


def negotiated(endpoint: str, use_shm: bool) -> str:
    """Human-readable transport label for one dialed endpoint, as the
    ``route`` CLI and bench rows print it: ``grpc`` (TCP wire),
    ``uds`` (unix socket wire), ``shm`` (loopback TCP + shm tensors),
    ``uds+shm`` (unix socket + shm tensors)."""
    kind = classify(endpoint)
    if kind == UDS:
        return "uds+shm" if use_shm else "uds"
    return "shm" if use_shm else "grpc"
