"""TPUChannel: the in-process dispatch channel.

This is the framework's answer to the reference's GRPCChannel
(communicator/channel/grpc_channel.py): instead of serializing ~3 MB of
image bytes into a protobuf and blocking on a remote GPU server
(SURVEY.md section 3.1), do_inference is a function call — inputs are
device_put onto the mesh with the batch axis sharded over `data`, the
jit-compiled model runs, and outputs come back as numpy only at the
driver boundary.

"register_channel" claims the device mesh (the analogue of dialing the
endpoint); "get_metadata" reads the local repository (the analogue of
the two startup RPCs, grpc_channel.py:39-54).
"""

from __future__ import annotations

import time

import jax
import numpy as np

from jax.sharding import NamedSharding, PartitionSpec

from triton_client_tpu.channel.base import (
    BaseChannel,
    InferFuture,
    InferRequest,
    InferResponse,
)
from triton_client_tpu.config import ModelSpec
from triton_client_tpu.parallel.mesh import MeshConfig, batch_sharding, make_mesh
from triton_client_tpu.runtime.repository import ModelRepository


class TPUChannel(BaseChannel):
    def __init__(
        self,
        repository: ModelRepository,
        mesh_config: MeshConfig | None = None,
        devices=None,
        validate: bool = True,
    ) -> None:
        self._repository = repository
        self._mesh_config = mesh_config
        self._devices = devices
        self._mesh = None
        self._validate = validate
        self.register_channel()

    # -- BaseChannel protocol -------------------------------------------------

    def register_channel(self) -> None:
        self._mesh = make_mesh(self._mesh_config, self._devices)

    def fetch_channel(self):
        return self._mesh

    def get_metadata(self, model_name: str, model_version: str = "") -> ModelSpec:
        return self._repository.metadata(model_name, model_version)

    def do_inference(self, request: InferRequest) -> InferResponse:
        model, outputs, t0 = self._dispatch(request)
        outputs = {k: np.asarray(v) for k, v in outputs.items()}
        return InferResponse(
            model_name=request.model_name,
            model_version=model.spec.version,
            outputs=outputs,
            request_id=request.request_id,
            latency_s=time.perf_counter() - t0,
        )

    def do_inference_async(self, request: InferRequest) -> InferFuture:
        """The in-process --async path: JAX dispatch is asynchronous, so
        _dispatch returns as soon as the computation is enqueued on the
        device; materializing numpy (the only blocking step) is deferred
        to result(). The driver can therefore preprocess frame N+1 while
        the chip runs frame N — no threads needed.

        Per the BaseChannel contract, dispatch-time errors (validation,
        unknown model, staging) are deferred to result() rather than
        raised here, so async callers have one error-surfacing point."""
        try:
            model, outputs, t0 = self._dispatch(request)
        except Exception as e:
            return InferFuture.failed(e)

        def resolve() -> InferResponse:
            host = {k: np.asarray(v) for k, v in outputs.items()}
            return InferResponse(
                model_name=request.model_name,
                model_version=model.spec.version,
                outputs=host,
                request_id=request.request_id,
                latency_s=time.perf_counter() - t0,
            )

        return InferFuture(resolve)

    def _dispatch(self, request: InferRequest):
        """Validate, stage inputs onto the mesh, enqueue the jitted
        infer_fn; returns (model, device outputs, start time) without
        forcing device->host transfer."""
        model = self._repository.get(request.model_name, request.model_version)
        if self._validate:
            for tensor_spec in model.spec.inputs:
                if tensor_spec.name not in request.inputs:
                    raise ValueError(
                        f"model '{model.spec.name}' requires input "
                        f"'{tensor_spec.name}'; request has "
                        f"{sorted(request.inputs)}"
                    )
                tensor_spec.validate(np.asarray(request.inputs[tensor_spec.name]))
        sharding = batch_sharding(self._mesh)
        device_inputs = {}
        for name, arr in request.inputs.items():
            # Shard batch-leading arrays over the data axis when the
            # batch divides; otherwise replicate (single-frame path).
            arr = np.asarray(arr)
            # Dtype policy (round 4 — this line was the serving-path
            # bottleneck): a stray float64/int64 must still be cast so
            # it can't trigger one retrace per dtype, but casting a
            # NARROWER wire dtype up to the spec on the HOST inflates
            # the host->device transfer (uint8 camera frames -> FP32 is
            # 4x the bytes; on the r4 rig that one cast tripled serving
            # batch latency). Narrow inputs upload as-is — every
            # in-tree pipeline widens on device, where the cast fuses
            # into the program for free. This is a REGISTRATION
            # CONTRACT (see runtime/repository.py RegisteredModel):
            # pipelines must widen internally and each distinct narrow
            # dtype traces its own executable.
            try:
                want = model.spec.input_by_name(name).np_dtype()
                if arr.dtype != want and (
                    np.dtype(want).itemsize <= arr.dtype.itemsize
                ):
                    arr = arr.astype(want)
            except (KeyError, ValueError, TypeError):
                pass  # undeclared/BF16 inputs pass through as-is
            use = (
                sharding
                if arr.ndim > 0 and arr.shape[0] % self._mesh.shape["data"] == 0
                else NamedSharding(self._mesh, PartitionSpec())
            )
            device_inputs[name] = jax.device_put(arr, use)
        t0 = time.perf_counter()
        return model, model.infer_fn(device_inputs), t0
