"""TPUChannel: the in-process dispatch channel.

This is the framework's answer to the reference's GRPCChannel
(communicator/channel/grpc_channel.py): instead of serializing ~3 MB of
image bytes into a protobuf and blocking on a remote GPU server
(SURVEY.md section 3.1), do_inference is a function call — inputs are
device_put onto the mesh with the batch axis sharded over `data`, the
jit-compiled model runs, and outputs come back as numpy only at the
driver boundary.

"register_channel" claims the device mesh (the analogue of dialing the
endpoint); "get_metadata" reads the local repository (the analogue of
the two startup RPCs, grpc_channel.py:39-54).

The overlapped stage/launch/lazy-readback protocol introduced in
round 6 now lives in :mod:`triton_client_tpu.channel.staged`
(``StagedChannel``), shared with the mesh-sharded serving channel
(round 9). This subclass keeps the single-executable placement policy:

  * dtype policy (round 4): narrow inputs upload as-is (pipelines widen
    on device), wider stray dtypes cast down to the wire contract;
  * per-array sharding heuristic: shard batch-leading arrays over the
    ``data`` axis when the batch divides, otherwise replicate;
  * launcher: cached ``jax.jit(fn, donate_argnums=(0,))`` whose first
    arg carries the spec-marked ``donatable`` inputs, so consecutive
    batches reuse the same HBM input buffers.
"""

from __future__ import annotations

import jax
import numpy as np

from jax.sharding import NamedSharding, PartitionSpec

from triton_client_tpu.channel.staged import (  # noqa: F401 — re-exported
    StagedChannel,
    StagedRequest,
    _Inflight,
    cast_wire_input,
)
from triton_client_tpu.config import config_dtypes
from triton_client_tpu.obs.roofline import name_launcher
from triton_client_tpu.parallel.mesh import batch_sharding


class TPUChannel(StagedChannel):
    """Single-executable serving channel (see module docstring)."""

    def _place_inputs(self, model, request):
        sharding = batch_sharding(self._mesh)
        device_inputs = {}
        for name, arr in request.inputs.items():
            # Shard batch-leading arrays over the data axis when the
            # batch divides; otherwise replicate (single-frame path).
            # round-4 dtype policy (see staged.cast_wire_input: never
            # widen on the host, cast stray wider dtypes down)
            arr = cast_wire_input(model, name, np.asarray(arr))
            use = (
                sharding
                if arr.ndim > 0
                and arr.shape[0] % self._mesh.shape["data"] == 0
                else NamedSharding(self._mesh, PartitionSpec())
            )
            device_inputs[name] = jax.device_put(arr, use)
        return device_inputs, None

    def _make_launcher(self, model):
        """Cached ``jax.jit(fn, donate_argnums=(0,))`` whose first arg
        carries the spec-marked donatable inputs — consecutive batches
        then reuse the same HBM input buffers."""
        donate_names = (
            frozenset(model.spec.donatable_inputs()) if self._donate else frozenset()
        )
        device_fn = self._device_body(model)
        # the launcher carries the model's name so its HLO module is
        # jit_mdl_<name>_<version> — profiler op events then attribute
        # back to the model by module name (obs/opstats.py)
        launcher = jax.jit(
            name_launcher(
                lambda donated, kept: device_fn({**donated, **kept}), model
            ),
            donate_argnums=(0,),
        )
        out_dtype = {
            t.name: config_dtypes().get(t.dtype) for t in model.spec.outputs
        }
        return launcher, donate_names, out_dtype
