"""Staged dispatch protocol: the stage/launch/resolve engine.

PR 1 split the in-process serving hot path into three overlapping
phases (stage the H2D copy, launch the jitted compute, resolve the
readback lazily) inside ``TPUChannel``. The mesh-sharded channel
(``channel/sharded_channel.py``) needs the SAME engine — staging slots,
trace spans, donation-aware launch cache, deferred error surfacing —
over a different placement policy (pad + shard the batch over the
``data`` axis instead of device_put per array). This module is that
engine factored out once, so the protocol cannot drift between the
single-device and mesh paths:

  * **stage**   — validate, acquire a staging slot, then hand the
    request to :meth:`StagedChannel._place_inputs` (the subclass
    placement policy). Slot admission is per CHANNEL — i.e. per mesh,
    not per device: at ``pipeline_depth`` (default 2) batch N+1's
    host->device copy runs while batch N executes across the whole
    mesh; ``pipeline_depth=1`` is the strictly serial legacy path.
  * **launch**  — enqueue the jitted compute through the launcher the
    subclass builds in :meth:`StagedChannel._make_launcher` (cached per
    model identity; donation split handled here). Outputs stay
    device-resident.
  * **resolve** — lazy. ``launch`` returns an ``InferFuture``; the
    device->host copy happens in :meth:`StagedChannel._host_outputs`
    only when the driver resolves it, and resolution retires the
    staging slot.

``do_inference`` is stage→launch→result; ``do_inference_async`` defers
the readback (and any dispatch-time error) to ``result()``.
"""

from __future__ import annotations

import collections
import logging
import threading
import time

import jax
import numpy as np

from triton_client_tpu.channel.base import (
    BaseChannel,
    InferFuture,
    InferRequest,
    InferResponse,
)
from triton_client_tpu.config import ModelSpec
from triton_client_tpu.parallel.mesh import MeshConfig, make_mesh
from triton_client_tpu.runtime import faults
from triton_client_tpu.runtime.admission import (
    CircuitBreaker,
    CircuitOpenError,
    DeadlineExpiredError,
)
from triton_client_tpu.parallel.ragged_kernels import (
    RaggedLayout,
    ShardedRaggedLayout,
)
from triton_client_tpu.runtime.repository import ModelRepository

log = logging.getLogger(__name__)

#: Reserved device-input key carrying the packed batch's row->segment
#: table (parallel/ragged_kernels.py). Never a wire tensor name.
SEGMENT_IDS_KEY = "__segment_ids__"


def _batch_rows(device_inputs: dict) -> int:
    """Frames in one dense launch: the largest leading dim among the
    staged arrays (pure shape metadata — no host sync)."""
    rows = 1
    for v in device_inputs.values():
        if getattr(v, "ndim", 0) >= 1:
            rows = max(rows, int(v.shape[0]))
    return rows


def cast_wire_input(model, name: str, arr: np.ndarray) -> np.ndarray:
    """The round-4 host-side dtype policy, shared by every placement
    policy so single-device and sharded channels cannot drift: a stray
    WIDER dtype (float64/int64) casts down to the wire contract so it
    can't trigger one retrace per dtype, but a NARROWER input uploads
    as-is — casting uint8 camera frames up to FP32 on the host is 4x
    the host->device bytes, and every in-tree pipeline widens on device
    where the cast fuses for free (the registration contract in
    runtime/repository.py).

    Round 10 extends the same never-widen rule to precision policies
    (runtime/precision.py): a model registered at bf16/int8 narrows its
    float wire inputs FURTHER here (f32 frames stage as bf16 words or
    calibrated int8 codes — half/quarter the H2D bytes), with keep-list
    inputs and integer frames untouched."""
    try:
        want = model.spec.input_by_name(name).np_dtype()
        if arr.dtype != want and np.dtype(want).itemsize <= arr.dtype.itemsize:
            arr = arr.astype(want)
    except (KeyError, ValueError, TypeError):
        pass  # undeclared/BF16 inputs pass through as-is
    policy = getattr(model, "precision", None)
    if policy is not None:
        arr = policy.wire_cast(name, arr)
    return arr


class StagedRequest:
    """A request whose inputs live on the mesh, awaiting launch.

    Produced by ``StagedChannel.stage``; consumed exactly once by
    ``StagedChannel.launch`` (the staging slot it occupies frees when
    the launched batch finishes executing, or immediately on launch
    failure). ``meta`` carries subclass placement state (the sharded
    channel records the real row count so resolve can slice the pad
    rows back off)."""

    __slots__ = (
        "model", "device_inputs", "request", "t_stage", "meta",
        "lifecycle_key",
    )

    def __init__(self, model, device_inputs, request, t_stage, meta=None) -> None:
        self.model = model
        self.device_inputs = device_inputs
        self.request = request
        self.t_stage = t_stage
        self.meta = meta
        # (name, version) in-flight reference on the lifecycle manager
        # (None when no manager is attached); dropped exactly once when
        # the request resolves or fails, so eviction can never reclaim
        # a model whose batch is still staged/executing
        self.lifecycle_key = None


class _Inflight:
    """One launched, not-yet-retired batch (a staging slot occupant)."""

    __slots__ = ("outputs", "retired")

    def __init__(self, outputs) -> None:
        self.outputs = outputs
        self.retired = False

    def wait_device(self) -> None:
        # Execution-complete, NOT readback: arrays stay on device.
        jax.block_until_ready(self.outputs)


class StagedChannel(BaseChannel):
    """Shared stage/launch/resolve machinery over a device mesh.

    Subclasses implement the placement policy:

      * :meth:`_place_inputs` — request host arrays -> device arrays on
        the mesh (plus opaque ``meta`` threaded to the readback);
      * :meth:`_make_launcher` — the cached jit wrapper over a model's
        ``device_fn`` (donation split, shardings);
      * :meth:`_host_outputs`  — device outputs -> host numpy at the
        wire dtypes (the designed readback sync point).
    """

    def __init__(
        self,
        repository: ModelRepository,
        mesh_config: MeshConfig | None = None,
        devices=None,
        validate: bool = True,
        pipeline_depth: int = 2,
        donate: bool = True,
        shed_expired: bool = False,
        breaker_threshold: int = 5,
        breaker_reset_s: float = 10.0,
    ) -> None:
        """``pipeline_depth``: launched-but-unretired batches allowed
        before ``stage`` blocks on the oldest batch's execution; 1 is
        the strictly serial legacy path. ``donate``: honor spec
        ``donatable`` marks (buffer reuse needs a ``device_fn``; on
        backends without donation support jax falls back to a copy).

        ``shed_expired``: enforce the deadline plane at launch — a
        request whose deadline already passed is FAILED with
        ``DeadlineExpiredError`` instead of executed (PR 6 only counted
        such launches; with shedding on, ``deadline_expired_launches``
        stays 0 while ``shed`` grows). Off by default so an SLO-less
        deployment keeps PR 6's count-only behavior.

        ``breaker_threshold``/``breaker_reset_s``: the per-model
        circuit breaker around launch+readback — ``threshold``
        consecutive failures open the circuit (fail-fast
        ``CircuitOpenError``, launch cache invalidated so recovery
        rebuilds the jitted launcher), a timed probe after ``reset_s``
        half-opens it, one success closes it. ``breaker_threshold=0``
        disables the breaker."""
        self._repository = repository
        self._mesh_config = mesh_config
        self._devices = devices
        self._mesh = None
        self._validate = validate
        self._pipeline_depth = max(1, int(pipeline_depth))
        self._donate = bool(donate)
        # staging slots: launched batches not yet retired (execution
        # still pending or readback not requested yet). Slots are per
        # channel — one admission window over the whole mesh.
        self._slot_cv = threading.Condition()
        self._inflight: collections.deque[_Inflight] = collections.deque()
        self._slots_active = 0
        self._slot_occupancy: collections.Counter = collections.Counter()
        self._stats = {
            "staged": 0,
            "launched": 0,
            "donated_launches": 0,
            "stage_slot_waits": 0,
            # launches whose request deadline (obs.slo deadline plane)
            # had already passed at enqueue time: sustained growth means
            # the queue ahead of the device eats the whole SLO budget —
            # the capacity-search saturation signal, visible live
            "deadline_expired_launches": 0,
            # launch/readback failures observed by the circuit breaker
            "launch_failures": 0,
        }
        self._shed_expired = bool(shed_expired)
        self._breaker = (
            CircuitBreaker(
                threshold=breaker_threshold, reset_s=breaker_reset_s
            )
            if breaker_threshold > 0
            else None
        )
        # per "model|priority|stage" shed counts, merged into the
        # collector's tpu_serving_shed_total family at scrape time
        self._shed: collections.Counter = collections.Counter()
        # (name, version) -> (model identity, launcher, donate_names,
        # output wire dtypes); rebuilt when the repository reloads the
        # model (identity mismatch)
        self._launch_cache: dict = {}
        # models whose measured flops/bytes (obs/roofline.py) were
        # already recorded into spec.extra — one attempt per model
        # identity, success or not, so a cost-model failure cannot
        # re-trace the launcher on every launch
        self._cost_measured: set = set()
        # optional ModelLifecycleManager (runtime/lifecycle.py): when
        # attached, stage() blocks until the model is WARM and holds an
        # in-flight reference through resolve
        self._lifecycle = None
        # optional DeviceTimeLedger (obs/device_time.py): when attached,
        # every launch's device-execute window accrues into per-
        # model×tenant device-seconds + live MFU
        self._device_time = None
        # optional SessionManager (runtime/sessions.py): when attached,
        # launches carrying a sequence_id run the device-resident
        # tracking step on their outputs before the response forms
        self._sessions = None
        # unregister must drop the cached launcher too — the cached
        # closure pins replicated params in HBM and would otherwise
        # leak until a same-named model happens to fail the identity
        # check; same invalidation path the circuit breaker uses
        subscribe = getattr(repository, "add_unregister_listener", None)
        if subscribe is not None:
            subscribe(self._on_unregister)
        self.register_channel()

    # -- BaseChannel protocol -------------------------------------------------

    def register_channel(self) -> None:
        self._mesh = make_mesh(self._mesh_config, self._devices)

    def fetch_channel(self):
        return self._mesh

    def get_metadata(self, model_name: str, model_version: str = "") -> ModelSpec:
        return self._repository.metadata(model_name, model_version)

    def do_inference(self, request: InferRequest) -> InferResponse:
        return self.launch(self.stage(request)).result()

    def do_inference_async(self, request: InferRequest) -> InferFuture:
        """The in-process --async path: JAX dispatch is asynchronous, so
        launch returns as soon as the computation is enqueued on the
        device; materializing numpy (the only blocking step) is deferred
        to result(). The driver can therefore preprocess frame N+1 while
        the chip runs frame N — no threads needed.

        Per the BaseChannel contract, dispatch-time errors (validation,
        unknown model, staging) are deferred to result() rather than
        raised here, so async callers have one error-surfacing point."""
        try:
            staged = self.stage(request)
        except Exception as e:
            return InferFuture.failed(e)
        return self.launch(staged)

    # -- subclass placement hooks ---------------------------------------------

    def _place_inputs(self, model, request: InferRequest):
        """Place the request's host arrays onto the mesh.

        Returns ``(device_inputs, meta)``. Runs INSIDE the staging slot
        (a raised error releases the slot); must not block on device
        execution."""
        raise NotImplementedError

    def _make_launcher(self, model):
        """Build ``(launcher | None, donate_names, out_dtypes)`` for a
        model. ``launcher(donated, kept)`` runs the jitted device_fn
        with ``donated`` in a ``donate_argnums`` position; None falls
        back to the host-boundary ``infer_fn``. Called once per model
        identity (cached by :meth:`_launcher`)."""
        raise NotImplementedError

    def _place_ragged(self, model, request: InferRequest):
        """Place a PACKED ragged request (``request.ragged`` is a
        :class:`RaggedLayout`): packed inputs and the segment-id table
        upload with default placement (the ragged body's segment math
        is global — XLA partitions it), per-segment inputs ride along
        unchanged. Subclasses with explicit shardings override."""
        layout = request.ragged
        device_inputs = {
            name: jax.device_put(cast_wire_input(model, name, np.asarray(arr)))
            for name, arr in request.inputs.items()
        }
        device_inputs[SEGMENT_IDS_KEY] = jax.device_put(layout.segment_ids)
        return device_inputs, layout

    def _make_ragged_launcher(self, model, num_segments: int):
        """Build ``(launcher, out_dtypes)`` for a model's segment-aware
        body at a STATIC bucketed segment capacity. No donation: packed
        shapes recur less often than dense buckets and a donated packed
        buffer would alias the replicated-row pad region."""
        from triton_client_tpu.config import config_dtypes

        ragged_fn = model.ragged_fn

        # named distinctly from the dense `launcher`: this jit does NOT
        # donate, and tpulint's donor index pools jit-bound names
        # module-wide
        def ragged_launcher(device_inputs):
            inputs = dict(device_inputs)
            ids = inputs.pop(SEGMENT_IDS_KEY)
            return ragged_fn(inputs, ids, num_segments)

        # stamped with the model's launcher name (runtime only — the
        # local binding above keeps lint's donor index unambiguous) so
        # profiler op events attribute by HLO module (obs/opstats.py)
        from triton_client_tpu.obs.roofline import name_launcher

        ragged_launcher = jax.jit(name_launcher(ragged_launcher, model))

        out_dtype = {
            t.name: config_dtypes().get(t.dtype) for t in model.spec.outputs
        }
        return ragged_launcher, out_dtype

    def _ragged_launcher(self, model, num_segments: int):
        """The ragged analogue of :meth:`_launcher`: cached per
        ``(model identity, segment bucket)`` — the segment capacity is
        static in the traced program, so the executable set stays
        log-bounded in segments (and jit's own shape cache bounds it in
        packed rows)."""
        key = (model.spec.name, model.spec.version, "ragged", num_segments)
        with self._slot_cv:
            cached = self._launch_cache.get(key)
            if cached is not None and cached[0] is model:
                return cached[1], cached[2]
        launcher, out_dtype = self._make_ragged_launcher(model, num_segments)
        with self._slot_cv:
            self._launch_cache[key] = (model, launcher, out_dtype)
        return launcher, out_dtype

    def _device_body(self, model):
        """The traced body both launcher implementations jit: the
        model's ``device_fn``, wrapped with the registered precision
        policy's wire ingest when the policy quantized activations —
        int8 wire inputs then dequantize INSIDE the launched program
        (runtime/precision.py), so the cached launcher stages in the
        wire dtype and runs the body at the policy dtype."""
        device_fn = model.device_fn
        policy = getattr(model, "precision", None)
        if (
            device_fn is None
            or policy is None
            or not getattr(policy, "wire_ingest_needed", False)
        ):
            return device_fn
        return lambda inputs, *rest: device_fn(policy.ingest(inputs), *rest)

    def _host_outputs(self, outputs, out_dtype, meta) -> dict:
        """Device outputs -> host numpy dict at the wire dtypes. The
        designed deferred-readback sync point (tpulint TPL301 baseline);
        subclasses slice off pad rows here before the copy."""
        if isinstance(meta, RaggedLayout):
            # drop the dead segment slots (lazy slice — the host copy
            # below pays only for real segments)
            outputs = {
                k: v[: meta.n_segments]
                if getattr(v, "ndim", 0) >= 1
                and v.shape[0] == meta.seg_bucket
                else v
                for k, v in outputs.items()
            }
        host = {}
        for k, v in outputs.items():
            # wire-contract dtypes at the host boundary: device traces
            # run with x64 disabled, so e.g. a scored head's INT64
            # classes come back int32 from device_fn — the cast keeps
            # launch paths identical
            dt = out_dtype.get(k) if out_dtype else None
            host[k] = np.asarray(v, dtype=dt) if dt else np.asarray(v)
        return host

    # -- pipeline knobs -------------------------------------------------------

    @property
    def pipeline_depth(self) -> int:
        return self._pipeline_depth

    @pipeline_depth.setter
    def pipeline_depth(self, depth: int) -> None:
        with self._slot_cv:
            self._pipeline_depth = max(1, int(depth))
            self._slot_cv.notify_all()

    @property
    def batch_multiple(self) -> int:
        """Preferred divisor for device batch sizes. 1 for per-device
        channels; the data-axis width for mesh-sharded channels (the
        batcher sizes merge groups and pad buckets off this)."""
        return 1

    def stats(self) -> dict:
        """Staging-slot counters (the channel-level analogue of
        BatchingChannel.stats): ``slot_occupancy`` maps concurrent
        in-flight batches at launch -> launches observed at that depth."""
        with self._slot_cv:
            out = dict(self._stats)
            out["slot_occupancy"] = dict(sorted(self._slot_occupancy.items()))
            out["inflight"] = len(self._inflight)
            out["slots_active"] = self._slots_active
            out["pipeline_depth"] = self._pipeline_depth
            out["shed"] = dict(self._shed)
        if self._breaker is not None:
            out["breaker"] = self._breaker.states()
        if self._mesh is not None:
            out["mesh_devices"] = int(self._mesh.devices.size)
            out["data_axis_size"] = int(self._mesh.shape["data"])
        return out

    # -- stage ----------------------------------------------------------------

    def stage(self, request: InferRequest) -> StagedRequest:
        """Validate the request and place its arrays onto the mesh.

        Blocks while ``pipeline_depth`` launched batches are still
        executing, so the H2D copy of the next batch overlaps (at most)
        depth in-flight computations — double-buffered at the default
        depth of 2. Must be paired with ``launch``."""
        tr = request.trace
        t_s0 = time.perf_counter() if tr is not None else 0.0
        model = self._repository.get(request.model_name, request.model_version)
        ragged = request.ragged is not None
        if self._validate and not ragged:
            # ragged requests carry PACKED shapes (rows concatenated
            # across members, padded to the layout bucket) that the
            # per-tensor wire spec cannot describe; the continuous
            # batcher validated each member at admission
            for tensor_spec in model.spec.inputs:
                if tensor_spec.name not in request.inputs:
                    raise ValueError(
                        f"model '{model.spec.name}' requires input "
                        f"'{tensor_spec.name}'; request has "
                        f"{sorted(request.inputs)}"
                    )
                tensor_spec.validate(np.asarray(request.inputs[tensor_spec.name]))
        lifecycle_key = None
        if self._lifecycle is not None:
            # block until the model is WARM (a cold model promotes on
            # demand here — first request pays the page-in, peers queue
            # behind it with a deadline-aware bound) and take the
            # in-flight reference that shields it from eviction
            t_p0 = time.perf_counter()
            try:
                lifecycle_key = self._lifecycle.acquire(
                    model.spec.name,
                    model.spec.version,
                    deadline_s=request.deadline_s,
                )
            except Exception:
                self._count_shed(model.spec.name, request.priority, "lifecycle")
                raise
            if tr is not None:
                tr.add("lifecycle", t_p0, time.perf_counter())
        if tr is not None:
            t_w0 = time.perf_counter()
            self._acquire_slot()
            tr.add("slot_wait", t_w0, time.perf_counter())
        else:
            self._acquire_slot()
        try:
            if ragged:
                device_inputs, meta = self._place_ragged(model, request)
            else:
                device_inputs, meta = self._place_inputs(model, request)
        except Exception:
            self._release_slot()
            if lifecycle_key is not None:
                self._lifecycle.release(*lifecycle_key)
            raise
        with self._slot_cv:
            self._stats["staged"] += 1
        t_staged = time.perf_counter()
        if tr is not None:
            # the whole stage phase: validate + slot admission + H2D
            tr.add("stage", t_s0, t_staged)
        staged = StagedRequest(model, device_inputs, request, t_staged, meta)
        staged.lifecycle_key = lifecycle_key
        return staged

    def _acquire_slot(self) -> None:
        waited = False
        while True:
            rec = None
            with self._slot_cv:
                if self._slots_active < self._pipeline_depth:
                    self._slots_active += 1
                    if waited:
                        self._stats["stage_slot_waits"] += 1
                    return
                waited = True
                if self._inflight:
                    rec = self._inflight.popleft()
                else:
                    # every slot is held by a peer between stage and
                    # launch; timed wait covers a missed notify
                    self._slot_cv.wait(timeout=0.05)
                    continue
            # block on EXECUTION completion outside the lock (readback
            # stays lazy; a concurrent resolve() of the same record is
            # fine — _retire is idempotent)
            rec.wait_device()
            self._retire(rec)

    def _release_slot(self) -> None:
        with self._slot_cv:
            self._slots_active -= 1
            self._slot_cv.notify_all()

    def _retire(self, rec: _Inflight) -> None:
        with self._slot_cv:
            if rec.retired:
                return
            rec.retired = True
            try:
                self._inflight.remove(rec)
            except ValueError:
                pass  # already popped by a staging thread
            self._slots_active -= 1
            self._slot_cv.notify_all()

    # -- launch ---------------------------------------------------------------

    def launch(self, staged: StagedRequest) -> InferFuture:
        """Enqueue the jitted compute for a staged request; returns a
        lazy InferFuture holding device arrays. The device->host copy
        happens at result(); the staging slot frees when the batch
        finishes executing (whichever of a later ``stage`` or this
        future's resolution observes it first)."""
        model, request = staged.model, staged.request
        name = model.spec.name
        tr = request.trace
        t0 = time.perf_counter()
        deadline = request.deadline_s
        if self._shed_expired and deadline is not None and t0 > deadline:
            # shedding enforced: a request whose deadline already
            # passed NEVER executes — fail its future in microseconds
            # instead of burning a device slot on work nobody can use
            self._release_slot()
            self._release_lifecycle(staged)
            self._count_shed(name, request.priority, "launch")
            return InferFuture.failed(
                DeadlineExpiredError(
                    f"model '{name}': deadline expired "
                    f"{(t0 - deadline) * 1e3:.1f}ms before launch"
                )
            )
        if self._breaker is not None and not self._breaker.allow(name, t0):
            self._release_slot()
            self._release_lifecycle(staged)
            self._count_shed(name, request.priority, "breaker")
            return InferFuture.failed(
                CircuitOpenError(
                    f"model '{name}': circuit breaker open "
                    "(recent consecutive launch failures)"
                )
            )
        try:
            faults.probe("slow_launch", name)
            faults.probe("launch", name)
            if request.ragged is not None:
                # packed-ragged launch: one jitted segment-aware body at
                # a static segment bucket; no donation split (see
                # _make_ragged_launcher), hence the distinct name — the
                # dense branch's `launcher` is a donating callable
                ragged_launcher, out_dtype = self._ragged_launcher(
                    model, request.ragged.launch_segments
                )
                donate_names = frozenset()
                self._ensure_launch_cost(
                    model, ragged_launcher, (staged.device_inputs,),
                    batch_rows=request.ragged.n_segments,
                )
                with jax.profiler.TraceAnnotation(
                    f"launch:{name}:{model.spec.version}"
                ):
                    outputs = ragged_launcher(staged.device_inputs)
            else:
                launcher, donate_names, out_dtype = self._launcher(model)
                if launcher is not None:
                    donated = {
                        k: v
                        for k, v in staged.device_inputs.items()
                        if k in donate_names
                    }
                    kept = {
                        k: v
                        for k, v in staged.device_inputs.items()
                        if k not in donate_names
                    }
                    self._ensure_launch_cost(
                        model, launcher, (donated, kept),
                        batch_rows=_batch_rows(staged.device_inputs),
                    )
                    # named region around the dispatch: a profiler
                    # capture (/profile, the continuous sampler) then
                    # maps device ops back to this model even when the
                    # HLO module name is unavailable (obs/opstats.py)
                    with jax.profiler.TraceAnnotation(
                        f"launch:{name}:{model.spec.version}"
                    ):
                        outputs = launcher(donated, kept)
                else:
                    with jax.profiler.TraceAnnotation(
                        f"launch:{name}:{model.spec.version}"
                    ):
                        outputs = model.infer_fn(staged.device_inputs)
        except Exception as e:
            # fan the error to THIS request's future only; the slot
            # frees, the channel and its caches stay serviceable for
            # every other request (the breaker decides if the model
            # itself needs a timeout)
            self._release_slot()
            self._release_lifecycle(staged)
            self._record_launch_failure(name)
            return InferFuture.failed(e)
        sessions = self._sessions
        session_id = request.sequence_id if sessions is not None else ""
        if session_id:
            # append the stream's device-resident tracking step to this
            # launch: async jit dispatch over arrays already in HBM —
            # the track tensors join the outputs, the state pytree
            # stays on device inside the session slot. The slot ref
            # advance() takes is dropped in resolve's finally.
            try:
                outputs = sessions.advance(request, outputs)
            except Exception as e:
                self._release_slot()
                self._release_lifecycle(staged)
                self._count_shed(name, request.priority, "session")
                return InferFuture.failed(e)
        rec = _Inflight(outputs)
        t_launched = time.perf_counter()
        if tr is not None:
            tr.add("launch", t0, t_launched)
        with self._slot_cv:
            self._inflight.append(rec)
            self._stats["launched"] += 1
            if donate_names:
                self._stats["donated_launches"] += 1
            if deadline is not None and t_launched > deadline:
                self._stats["deadline_expired_launches"] += 1
            self._slot_occupancy[len(self._inflight)] += 1

        ledger = self._device_time

        def resolve() -> InferResponse:
            try:
                if tr is not None or ledger is not None:
                    # device window: enqueue -> execution complete.
                    # block_until_ready is what np.asarray would wait on
                    # anyway; forcing it here splits execute from the
                    # device->host copy in the request timeline. The
                    # ledger accrues the SAME window the trace spans, so
                    # its totals reconcile with the device_execute
                    # histogram by construction.
                    jax.block_until_ready(outputs)
                    t_ready = time.perf_counter()
                    if tr is not None:
                        tr.add("device_execute", t_launched, t_ready)
                    if ledger is not None:
                        # session frames accrue under a per-stream
                        # tenant, so the ledger's tenant axis answers
                        # "device seconds per live stream" directly
                        ledger.record(
                            name, t_ready - t_launched, model.spec.extra,
                            tenant=f"stream:{session_id}"
                            if session_id
                            else None,
                        )
                faults.probe("readback", name)
                host = self._host_outputs(outputs, out_dtype, staged.meta)
                if tr is not None:
                    tr.add("readback", t_ready, time.perf_counter())
            except Exception:
                # readback failure belongs to THIS batch's futures only
                # (the batcher fans it to the members); the breaker
                # aggregates consecutive failures into a model timeout
                self._record_launch_failure(name)
                raise
            finally:
                self._retire(rec)
                self._release_lifecycle(staged)
                if session_id:
                    sessions.release(session_id)
            if self._breaker is not None:
                self._breaker.record_success(name)
            return InferResponse(
                model_name=request.model_name,
                model_version=model.spec.version,
                outputs=host,
                request_id=request.request_id,
                latency_s=time.perf_counter() - t0,
            )

        return InferFuture(resolve)

    def _launcher(self, model):
        """(jitted device_fn launcher | None, donate names, out dtypes),
        cached per model identity. Host-only models (no device_fn) keep
        the legacy infer_fn call, which may block on its own internal
        readback."""
        if model.device_fn is None:
            return None, (), None
        key = (model.spec.name, model.spec.version)
        with self._slot_cv:
            cached = self._launch_cache.get(key)
            if cached is not None and cached[0] is model:
                return cached[1], cached[2], cached[3]
        launcher, donate_names, out_dtype = self._make_launcher(model)
        with self._slot_cv:
            self._launch_cache[key] = (model, launcher, donate_names, out_dtype)
        return launcher, donate_names, out_dtype

    def _ensure_launch_cost(
        self, model, launcher, args, batch_rows: int = 1
    ) -> None:
        """Record XLA's measured flops/bytes for one launcher call into
        ``model.spec.extra`` (obs/roofline.py) — once per model
        identity, on the first launch, where the example args finally
        exist. Tracing-only (no backend compile) and immediately before
        the first call's full compile, so the marginal cost is
        milliseconds on a path about to pay seconds. Never fails the
        launch: the roofline is observability, not serving."""
        key = (model.spec.name, model.spec.version)
        with self._slot_cv:
            if key in self._cost_measured:
                return
            self._cost_measured.add(key)
        try:
            from triton_client_tpu.obs.roofline import record_launch_cost

            record_launch_cost(model, launcher, *args, batch_rows=batch_rows)
        except Exception:  # cost model unavailable on this backend
            log.debug(
                "measured-cost capture failed for %s:%s",
                *key, exc_info=True,
            )

    # -- model lifecycle (runtime/lifecycle.py) -------------------------------

    def attach_lifecycle(self, manager) -> None:
        """Attach a ModelLifecycleManager: stage() then blocks until the
        model is WARM (promoting it on demand) and brackets each request
        with acquire/release so eviction never reclaims a model with
        in-flight work. The manager's page-in hook builds this channel's
        cached launcher; its page-out hook drops it (freeing the
        replicated params the launcher closure pins in HBM)."""
        self._lifecycle = manager
        manager.set_hooks(warmer=self._warm_model, evictor=self._evict_model)

    @property
    def lifecycle(self):
        return self._lifecycle

    # -- device-time attribution (obs/device_time.py) -------------------------

    def attach_device_time(self, ledger) -> None:
        """Attach a DeviceTimeLedger: every subsequent launch records
        its device-execute window (t_launched -> block_until_ready)
        into the ledger from the resolve path."""
        self._device_time = ledger

    @property
    def device_time(self):
        return self._device_time

    # -- streaming sessions (runtime/sessions.py) -----------------------------

    def attach_sessions(self, manager) -> None:
        """Attach a SessionManager: launches whose request carries a
        ``sequence_id`` advance that stream's device-resident tracker
        on the launch outputs (state never leaves HBM between frames)
        and hold the session slot's refcount until resolve."""
        self._sessions = manager

    @property
    def sessions(self):
        return self._sessions

    def _warm_model(self, name: str, version: str) -> None:
        """Lifecycle page-in hook: build + cache the jitted launcher (the
        sharded subclass replicates the param tree here — the actual HBM
        page-in) so the promoting request pays compile+placement once and
        everything queued behind it launches hot."""
        model = self._repository.get(name, version)
        if model.device_fn is not None:
            self._launcher(model)

    def _evict_model(self, name: str, version: str) -> None:
        """Lifecycle page-out hook: drop the cached launcher so XLA frees
        the replicated params its closure holds."""
        self._invalidate_model(name, version)

    def _on_unregister(self, name: str, version: str) -> None:
        # repository listener (registered in __init__): an unregistered
        # model must not keep serving from — or pinning HBM through —
        # a stale cached launcher
        self._invalidate_model(name, version)

    def _invalidate_model(self, name: str, version: str) -> None:
        """Drop every cached launcher for one (name, version): the dense
        entry plus all ragged segment buckets."""
        with self._slot_cv:
            for key in [
                k
                for k in self._launch_cache
                if k[0] == name and k[1] == version
            ]:
                del self._launch_cache[key]

    def _release_lifecycle(self, staged: StagedRequest) -> None:
        """Drop the in-flight lifecycle reference exactly once (every
        launch failure path and resolve's finally funnel here)."""
        key, staged.lifecycle_key = staged.lifecycle_key, None
        if key is not None and self._lifecycle is not None:
            self._lifecycle.release(*key)

    # -- failure isolation ----------------------------------------------------

    def _count_shed(self, model: str, priority: int, stage: str) -> None:
        with self._slot_cv:
            self._shed[f"{model}|{int(priority)}|{stage}"] += 1

    def _record_launch_failure(self, model: str) -> None:
        """One launch/readback failure for ``model``: feed the breaker;
        when this failure OPENS the circuit, drop the cached launcher so
        recovery (the half-open probe) rebuilds the jit wrapper from the
        repository's current model instead of reusing state that may
        have been poisoned by the failure."""
        with self._slot_cv:
            self._stats["launch_failures"] += 1
        if self._breaker is None:
            return
        if self._breaker.record_failure(model):
            self._invalidate_launcher(model)

    def _invalidate_launcher(self, model: str) -> None:
        with self._slot_cv:
            for key in [k for k in self._launch_cache if k[0] == model]:
                del self._launch_cache[key]

    @property
    def breaker(self):
        """The per-model circuit breaker (None when disabled) — the
        collector reads states() off it via stats()["breaker"]."""
        return self._breaker
