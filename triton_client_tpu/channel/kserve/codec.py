"""numpy <-> KServe v2 raw tensor codec, zero-copy where possible.

The reference deserializes ``raw_output_contents`` with a per-scalar
``struct.unpack_from`` python loop (clients/postprocess/
base_postprocess.py:15-37) — O(N) interpreter round-trips per tensor.
Here both directions are single buffer views: ``np.frombuffer`` on
receive (no copy; the protobuf bytes own the memory) and
``ndarray.tobytes()`` / memoryview on send.

Datatype strings follow the KServe v2 table; BF16 travels as uint16
words (the standard Triton convention) and is viewed back at the jax
boundary.
"""

from __future__ import annotations

import ml_dtypes  # ships with jax
import numpy as np

from triton_client_tpu.channel.kserve import pb
from triton_client_tpu.config import config_dtypes
from triton_client_tpu.runtime import faults

# KServe v2 datatype string <-> numpy dtype (little-endian wire order,
# matching the reference's struct '<' formats, base_postprocess.py:20).
# Derived from the single table in config._DTYPES; BF16 is the one
# special case (no stock-numpy dtype) and maps to ml_dtypes.bfloat16.
_BF16 = np.dtype(ml_dtypes.bfloat16)
_TO_NP: dict[str, np.dtype] = {
    k: (_BF16 if v is None else np.dtype(v)) for k, v in config_dtypes().items()
}
_FROM_NP = {v: k for k, v in _TO_NP.items()}

_CONFIG_DTYPE = {
    "BOOL": pb.TYPE_BOOL,
    "UINT8": pb.TYPE_UINT8,
    "UINT16": pb.TYPE_UINT16,
    "UINT32": pb.TYPE_UINT32,
    "UINT64": pb.TYPE_UINT64,
    "INT8": pb.TYPE_INT8,
    "INT16": pb.TYPE_INT16,
    "INT32": pb.TYPE_INT32,
    "INT64": pb.TYPE_INT64,
    "FP16": pb.TYPE_FP16,
    "FP32": pb.TYPE_FP32,
    "FP64": pb.TYPE_FP64,
    "BF16": pb.TYPE_BF16,
}


def datatype_of(arr: np.ndarray) -> str:
    dtype = arr.dtype.newbyteorder("=")
    if dtype not in _FROM_NP:
        raise ValueError(f"unsupported wire dtype {arr.dtype}")
    return _FROM_NP[dtype]


def config_datatype(datatype: str) -> int:
    return _CONFIG_DTYPE.get(datatype, pb.TYPE_INVALID)


def serialize_tensor(arr: np.ndarray) -> bytes:
    """Array -> little-endian raw bytes (C order). A no-copy memoryview
    when the array is already contiguous little-endian."""
    arr = np.ascontiguousarray(arr)
    if arr.dtype.byteorder == ">":
        arr = arr.astype(arr.dtype.newbyteorder("<"))
    return arr.tobytes()


def deserialize_tensor(raw: bytes, datatype: str, shape) -> np.ndarray:
    """Raw bytes -> array view over the buffer (zero copy)."""
    if datatype not in _TO_NP:
        raise ValueError(f"unsupported wire datatype '{datatype}'")
    arr = np.frombuffer(raw, dtype=_TO_NP[datatype])
    return arr.reshape(tuple(int(d) for d in shape))


def set_request_params(msg, params: dict | None) -> None:
    """Write request/response-level ``parameters`` (str -> str/int/bool)
    onto a ModelInfer message: the side-channel trace context
    (``traceparent``), priorities, and span summaries travel here."""
    if not params:
        return
    for key, value in params.items():
        if isinstance(value, bool):
            msg.parameters[key].bool_param = value
        elif isinstance(value, int):
            msg.parameters[key].int64_param = value
        else:
            msg.parameters[key].string_param = str(value)


def get_string_param(msg, key: str) -> str | None:
    """Presence-checked read of a string parameter (bracket access on
    a protobuf map INSERTS a default entry — never subscript blind)."""
    p = msg.parameters
    if key not in p:
        return None
    return p[key].string_param or None


def get_int_param(msg, key: str, default: int = 0) -> int:
    """Presence-checked read of an int64 parameter."""
    p = msg.parameters
    if key not in p:
        return default
    return int(p[key].int64_param)


def get_bool_param(msg, key: str, default: bool = False) -> bool:
    """Presence-checked read of a bool parameter."""
    p = msg.parameters
    if key not in p:
        return default
    return bool(p[key].bool_param)


# streaming-session sequence parameters (runtime/sessions.py): frames
# of one stream share a sequence_id; sequence_start/sequence_end
# bracket the stream's life. Triton's sequence-batcher extension uses
# the same three names, so sequence-aware Triton clients speak this
# without translation.
SEQUENCE_ID_PARAM = "sequence_id"
SEQUENCE_START_PARAM = "sequence_start"
SEQUENCE_END_PARAM = "sequence_end"


# multi-frame streaming protocol (round 13): one ModelStreamInfer
# message carries a packed group of G equal-shape frames concatenated
# along the leading axis; the server fans them into the batcher as
# individual requests and streams one response per frame, so a tunnel
# RTT is paid once per group instead of once per frame.
STREAM_GROUP_PARAM = "stream_group"
STREAM_GROUP_IDS_PARAM = "stream_group_ids"


def build_infer_request(
    model_name: str,
    inputs: dict[str, np.ndarray],
    model_version: str = "",
    request_id: str = "",
    parameters: dict | None = None,
    input_parameters: dict[str, dict] | None = None,
) -> pb.ModelInferRequest:
    """``input_parameters`` maps input name -> per-tensor parameters
    (e.g. ``content_encoding`` for wire-compressed payloads,
    runtime/wire_encoding.py)."""
    req = pb.ModelInferRequest(
        model_name=model_name, model_version=model_version, id=request_id
    )
    set_request_params(req, parameters)
    # Sorted for a deterministic input<->raw_input_contents pairing
    # (the wire pairs them by position).
    for name in sorted(inputs):
        arr = np.asarray(inputs[name])
        t = req.inputs.add(
            name=name, datatype=datatype_of(arr), shape=arr.shape
        )
        if input_parameters and name in input_parameters:
            set_request_params(t, input_parameters[name])
        req.raw_input_contents.append(serialize_tensor(arr))
    return req


def build_infer_request_shm(
    model_name: str,
    inputs: dict[str, np.ndarray],
    shm_inputs: dict[str, tuple[str, int, int]],
    model_version: str = "",
    request_id: str = "",
    parameters: dict | None = None,
    input_parameters: dict[str, dict] | None = None,
) -> pb.ModelInferRequest:
    """Like build_infer_request, but inputs named in ``shm_inputs``
    (name -> (region, offset, byte_size)) travel as metadata + shared-
    memory parameters with no raw content; the caller has already
    written their bytes into the region."""
    req = pb.ModelInferRequest(
        model_name=model_name, model_version=model_version, id=request_id
    )
    set_request_params(req, parameters)
    for name in sorted(inputs):
        arr = np.asarray(inputs[name])
        t = req.inputs.add(
            name=name, datatype=datatype_of(arr), shape=arr.shape
        )
        if input_parameters and name in input_parameters:
            set_request_params(t, input_parameters[name])
        target = shm_inputs.get(name)
        if target is None:
            req.raw_input_contents.append(serialize_tensor(arr))
        else:
            set_shm_params(t, *target)
    return req


def add_requested_output(
    req: pb.ModelInferRequest,
    name: str,
    region: str,
    offset: int,
    byte_size: int,
) -> None:
    """Request that the server place one response tensor into a
    client-owned shm window (Triton requested-output semantics): the
    server writes readback bytes straight into the client's mapped
    segment and the response carries only coordinates."""
    t = req.outputs.add(name=name)
    set_shm_params(t, region, offset, byte_size)


def shm_params(tensor) -> tuple[str, int, int] | None:
    """(region, offset, byte_size) when a tensor's parameters request
    shared-memory transport (Triton system-shared-memory extension);
    None for plain wire tensors."""
    p = tensor.parameters
    if "shared_memory_region" not in p:
        return None
    # presence-check before EVERY subscript: bracket access on a
    # protobuf map inserts a default entry, silently mutating the
    # message being parsed — surprising for any later re-serialization
    # or logging of the request/response
    region = p["shared_memory_region"].string_param
    byte_size = (
        int(p["shared_memory_byte_size"].int64_param)
        if "shared_memory_byte_size" in p
        else 0
    )
    offset = (
        int(p["shared_memory_offset"].int64_param)
        if "shared_memory_offset" in p
        else 0
    )
    if not region or byte_size <= 0 or offset < 0:
        raise ValueError(
            "shared-memory tensor parameters need a region name, a "
            "positive byte_size, and a non-negative offset "
            f"(got {region!r}, {byte_size}, {offset})"
        )
    return region, offset, byte_size


def set_shm_params(tensor, region: str, offset: int, byte_size: int) -> None:
    tensor.parameters["shared_memory_region"].string_param = region
    tensor.parameters["shared_memory_byte_size"].int64_param = byte_size
    if offset:
        tensor.parameters["shared_memory_offset"].int64_param = offset


def parse_infer_request(
    req: pb.ModelInferRequest, shm=None
) -> dict[str, np.ndarray]:
    """Wire -> arrays. Inputs carrying shared-memory parameters are
    read from ``shm`` (a SystemSharedMemoryRegistry) and consume NO
    raw_input_contents slot — the wire pairs raw buffers positionally
    with the non-shm inputs only (Triton semantics)."""
    faults.probe("codec_decode", req.model_name)
    wire_inputs = [t for t in req.inputs if shm_params(t) is None]
    if len(req.raw_input_contents) != len(wire_inputs):
        raise ValueError(
            f"{len(wire_inputs)} wire input tensors but "
            f"{len(req.raw_input_contents)} raw buffers"
        )
    raws = iter(req.raw_input_contents)
    out = {}
    for t in req.inputs:
        region = shm_params(t)
        if region is None:
            out[t.name] = deserialize_tensor(next(raws), t.datatype, t.shape)
            continue
        if shm is None:
            raise ValueError(
                f"input {t.name!r} requests shared-memory transport but "
                "this server has no shared-memory registry"
            )
        name, offset, byte_size = region
        out[t.name] = deserialize_tensor(
            shm.read(name, offset, byte_size), t.datatype, t.shape
        )
    return out


def build_infer_response(
    model_name: str,
    outputs: dict[str, np.ndarray],
    model_version: str = "",
    request_id: str = "",
    shm_outputs: dict[str, tuple[str, int, int]] | None = None,
    shm=None,
    parameters: dict | None = None,
    fallback_to_wire: bool = False,
) -> pb.ModelInferResponse:
    """``shm_outputs`` maps output name -> (region, offset, byte_size):
    those tensors are written into the registry's region and travel as
    metadata + shared-memory parameters with no raw content (Triton
    system-shared-memory extension, response side).

    ``fallback_to_wire``: an output that exceeds its requested window
    ships as raw content instead of raising — the serving path passes
    True so a client whose learned output sizes lag a growing batch
    still gets its response (and learns the larger size from it);
    the strict default stays for direct codec users."""
    resp = pb.ModelInferResponse(
        model_name=model_name, model_version=model_version, id=request_id
    )
    set_request_params(resp, parameters)
    for name in sorted(outputs):
        arr = np.asarray(outputs[name])
        t = resp.outputs.add(
            name=name, datatype=datatype_of(arr), shape=arr.shape
        )
        target = (shm_outputs or {}).get(name)
        if target is None:
            resp.raw_output_contents.append(serialize_tensor(arr))
            continue
        region, offset, byte_size = target
        if arr.nbytes > byte_size:
            if not fallback_to_wire:
                raise ValueError(
                    f"output {name!r} is {arr.nbytes} bytes but the "
                    f"requested shared-memory window is {byte_size}"
                )
            resp.raw_output_contents.append(serialize_tensor(arr))
            continue
        # single designed copy: readback view -> client's mapped page
        # (write() handles contiguity; no intermediate materialization)
        shm.write(region, offset, arr)
        set_shm_params(t, region, offset, arr.nbytes)
    return resp


def parse_infer_response(
    resp: pb.ModelInferResponse, regions=None
) -> dict[str, np.ndarray]:
    """Wire -> arrays. Outputs whose parameters carry shared-memory
    coordinates are read from ``regions`` (output name or region name
    -> client-owned SharedMemoryRegion) instead of raw content."""
    wire_outputs = [t for t in resp.outputs if shm_params(t) is None]
    if len(resp.raw_output_contents) != len(wire_outputs):
        raise ValueError(
            f"{len(wire_outputs)} wire output tensors but "
            f"{len(resp.raw_output_contents)} raw buffers"
        )
    raws = iter(resp.raw_output_contents)
    out = {}
    for t in resp.outputs:
        target = shm_params(t)
        if target is None:
            out[t.name] = deserialize_tensor(next(raws), t.datatype, t.shape)
            continue
        name, offset, byte_size = target
        region = (regions or {}).get(name) or (regions or {}).get(t.name)
        if region is None:
            raise ValueError(
                f"response output {t.name!r} lives in shared-memory region "
                f"{name!r} but no matching client region was provided"
            )
        out[t.name] = deserialize_tensor(
            region.read(offset, byte_size), t.datatype, t.shape
        )
    return out
