"""numpy <-> KServe v2 raw tensor codec, zero-copy where possible.

The reference deserializes ``raw_output_contents`` with a per-scalar
``struct.unpack_from`` python loop (clients/postprocess/
base_postprocess.py:15-37) — O(N) interpreter round-trips per tensor.
Here both directions are single buffer views: ``np.frombuffer`` on
receive (no copy; the protobuf bytes own the memory) and
``ndarray.tobytes()`` / memoryview on send.

Datatype strings follow the KServe v2 table; BF16 travels as uint16
words (the standard Triton convention) and is viewed back at the jax
boundary.
"""

from __future__ import annotations

import ml_dtypes  # ships with jax
import numpy as np

from triton_client_tpu.channel.kserve import pb
from triton_client_tpu.config import config_dtypes

# KServe v2 datatype string <-> numpy dtype (little-endian wire order,
# matching the reference's struct '<' formats, base_postprocess.py:20).
# Derived from the single table in config._DTYPES; BF16 is the one
# special case (no stock-numpy dtype) and maps to ml_dtypes.bfloat16.
_BF16 = np.dtype(ml_dtypes.bfloat16)
_TO_NP: dict[str, np.dtype] = {
    k: (_BF16 if v is None else np.dtype(v)) for k, v in config_dtypes().items()
}
_FROM_NP = {v: k for k, v in _TO_NP.items()}

_CONFIG_DTYPE = {
    "BOOL": pb.TYPE_BOOL,
    "UINT8": pb.TYPE_UINT8,
    "UINT16": pb.TYPE_UINT16,
    "UINT32": pb.TYPE_UINT32,
    "UINT64": pb.TYPE_UINT64,
    "INT8": pb.TYPE_INT8,
    "INT16": pb.TYPE_INT16,
    "INT32": pb.TYPE_INT32,
    "INT64": pb.TYPE_INT64,
    "FP16": pb.TYPE_FP16,
    "FP32": pb.TYPE_FP32,
    "FP64": pb.TYPE_FP64,
    "BF16": pb.TYPE_BF16,
}


def datatype_of(arr: np.ndarray) -> str:
    dtype = arr.dtype.newbyteorder("=")
    if dtype not in _FROM_NP:
        raise ValueError(f"unsupported wire dtype {arr.dtype}")
    return _FROM_NP[dtype]


def config_datatype(datatype: str) -> int:
    return _CONFIG_DTYPE.get(datatype, pb.TYPE_INVALID)


def serialize_tensor(arr: np.ndarray) -> bytes:
    """Array -> little-endian raw bytes (C order). A no-copy memoryview
    when the array is already contiguous little-endian."""
    arr = np.ascontiguousarray(arr)
    if arr.dtype.byteorder == ">":
        arr = arr.astype(arr.dtype.newbyteorder("<"))
    return arr.tobytes()


def deserialize_tensor(raw: bytes, datatype: str, shape) -> np.ndarray:
    """Raw bytes -> array view over the buffer (zero copy)."""
    if datatype not in _TO_NP:
        raise ValueError(f"unsupported wire datatype '{datatype}'")
    arr = np.frombuffer(raw, dtype=_TO_NP[datatype])
    return arr.reshape(tuple(int(d) for d in shape))


def build_infer_request(
    model_name: str,
    inputs: dict[str, np.ndarray],
    model_version: str = "",
    request_id: str = "",
) -> pb.ModelInferRequest:
    req = pb.ModelInferRequest(
        model_name=model_name, model_version=model_version, id=request_id
    )
    # Sorted for a deterministic input<->raw_input_contents pairing
    # (the wire pairs them by position).
    for name in sorted(inputs):
        arr = np.asarray(inputs[name])
        req.inputs.add(name=name, datatype=datatype_of(arr), shape=arr.shape)
        req.raw_input_contents.append(serialize_tensor(arr))
    return req


def parse_infer_request(req: pb.ModelInferRequest) -> dict[str, np.ndarray]:
    if len(req.raw_input_contents) != len(req.inputs):
        raise ValueError(
            f"{len(req.inputs)} input tensors but "
            f"{len(req.raw_input_contents)} raw buffers"
        )
    return {
        t.name: deserialize_tensor(raw, t.datatype, t.shape)
        for t, raw in zip(req.inputs, req.raw_input_contents)
    }


def build_infer_response(
    model_name: str,
    outputs: dict[str, np.ndarray],
    model_version: str = "",
    request_id: str = "",
) -> pb.ModelInferResponse:
    resp = pb.ModelInferResponse(
        model_name=model_name, model_version=model_version, id=request_id
    )
    for name in sorted(outputs):
        arr = np.asarray(outputs[name])
        resp.outputs.add(name=name, datatype=datatype_of(arr), shape=arr.shape)
        resp.raw_output_contents.append(serialize_tensor(arr))
    return resp


def parse_infer_response(resp: pb.ModelInferResponse) -> dict[str, np.ndarray]:
    return {
        t.name: deserialize_tensor(raw, t.datatype, t.shape)
        for t, raw in zip(resp.outputs, resp.raw_output_contents)
    }
