"""KServe v2 wire protocol: proto messages, gRPC stubs, tensor codec.

``kserve_v2_pb2.py`` is generated from ``kserve_v2.proto`` by
``protoc --python_out=.`` (regenerate with ``make -C . proto`` or the
command in the proto header comment). The gRPC service stubs are
hand-written in ``service.py`` against the generic grpc API (the image
has grpcio but not grpcio-tools).
"""

from triton_client_tpu.channel.kserve import kserve_v2_pb2 as pb  # noqa: F401
