"""Hand-written gRPC stubs for inference.GRPCInferenceService.

grpcio is in the image but grpcio-tools is not, so instead of generated
``_pb2_grpc.py`` these stubs are built on grpc's generic API: the client
side creates ``unary_unary``/``stream_stream`` multicallables and the
server side registers a ``method_handlers_generic_handler``. Method
paths and serialization match what grpcio-tools would generate, so the
wire is indistinguishable from a stock tritonclient/Triton pairing.
"""

from __future__ import annotations

import grpc

from triton_client_tpu.channel.kserve import pb

_SERVICE = "inference.GRPCInferenceService"

# method name -> (request type, response type, is_streaming)
_METHODS = {
    "ServerLive": (pb.ServerLiveRequest, pb.ServerLiveResponse, False),
    "ServerReady": (pb.ServerReadyRequest, pb.ServerReadyResponse, False),
    "ModelReady": (pb.ModelReadyRequest, pb.ModelReadyResponse, False),
    "ServerMetadata": (pb.ServerMetadataRequest, pb.ServerMetadataResponse, False),
    "ModelMetadata": (pb.ModelMetadataRequest, pb.ModelMetadataResponse, False),
    "ModelInfer": (pb.ModelInferRequest, pb.ModelInferResponse, False),
    "ModelStreamInfer": (pb.ModelInferRequest, pb.ModelStreamInferResponse, True),
    "ModelConfig": (pb.ModelConfigRequest, pb.ModelConfigResponse, False),
    "RepositoryIndex": (pb.RepositoryIndexRequest, pb.RepositoryIndexResponse, False),
    "SystemSharedMemoryStatus": (
        pb.SystemSharedMemoryStatusRequest,
        pb.SystemSharedMemoryStatusResponse,
        False,
    ),
    "SystemSharedMemoryRegister": (
        pb.SystemSharedMemoryRegisterRequest,
        pb.SystemSharedMemoryRegisterResponse,
        False,
    ),
    "SystemSharedMemoryUnregister": (
        pb.SystemSharedMemoryUnregisterRequest,
        pb.SystemSharedMemoryUnregisterResponse,
        False,
    ),
}


class GRPCInferenceServiceStub:
    """Client stub; same surface as a generated ``*_pb2_grpc`` stub."""

    def __init__(self, channel: grpc.Channel) -> None:
        for name, (req_t, resp_t, streaming) in _METHODS.items():
            path = f"/{_SERVICE}/{name}"
            if streaming:
                call = channel.stream_stream(
                    path,
                    request_serializer=req_t.SerializeToString,
                    response_deserializer=resp_t.FromString,
                )
            else:
                call = channel.unary_unary(
                    path,
                    request_serializer=req_t.SerializeToString,
                    response_deserializer=resp_t.FromString,
                )
            setattr(self, name, call)


class GRPCInferenceServiceServicer:
    """Base servicer: override the methods the server implements."""

    def _unimplemented(self, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "method not implemented")

    def ServerLive(self, request, context):
        self._unimplemented(context)

    def ServerReady(self, request, context):
        self._unimplemented(context)

    def ModelReady(self, request, context):
        self._unimplemented(context)

    def ServerMetadata(self, request, context):
        self._unimplemented(context)

    def ModelMetadata(self, request, context):
        self._unimplemented(context)

    def ModelInfer(self, request, context):
        self._unimplemented(context)

    def ModelStreamInfer(self, request_iterator, context):
        self._unimplemented(context)

    def ModelConfig(self, request, context):
        self._unimplemented(context)

    def RepositoryIndex(self, request, context):
        self._unimplemented(context)

    def SystemSharedMemoryStatus(self, request, context):
        self._unimplemented(context)

    def SystemSharedMemoryRegister(self, request, context):
        self._unimplemented(context)

    def SystemSharedMemoryUnregister(self, request, context):
        self._unimplemented(context)


def add_servicer_to_server(
    servicer: GRPCInferenceServiceServicer, server: grpc.Server
) -> None:
    handlers = {}
    for name, (req_t, resp_t, streaming) in _METHODS.items():
        make = (
            grpc.stream_stream_rpc_method_handler
            if streaming
            else grpc.unary_unary_rpc_method_handler
        )
        handlers[name] = make(
            getattr(servicer, name),
            request_deserializer=req_t.FromString,
            response_serializer=resp_t.SerializeToString,
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(_SERVICE, handlers),)
    )
