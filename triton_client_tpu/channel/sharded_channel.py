"""ShardedTPUChannel: one server saturating a whole mesh.

The replicate-params / shard-batch serving shape used by TPU LLM
serving stacks (PAPERS.md — Ragged Paged Attention, Gemma-on-TPU),
applied to the perception stack: one model, all devices of a
``parallel/mesh.py`` mesh, one executable per padded batch bucket.

  * **params** are placed ONCE with ``replicated(mesh)`` sharding — at
    launcher build for an explicit ``RegisteredModel.params`` tree, or
    implicitly by XLA for the closure-captured weights every in-tree
    pipeline carries (replication happens at first trace per bucket,
    then every launch reads the local HBM copy).
  * **batches** are padded to the shared bucket table
    (:mod:`triton_client_tpu.runtime.padding` — ``bucket_for`` keeps
    each padded size divisible by the data-axis width) and split over
    the ``data`` axis via ``jax.device_put(arr, batch_sharding(mesh))``,
    so each device runs batch/N rows of the SAME program — per-request
    numerics are bitwise identical to the single-device channel because
    data parallelism never changes a row's compute and pad rows
    replicate a real row before being sliced back off.
  * **dispatch** keeps PR 1's staged/launch/lazy-readback overlap via
    the shared :class:`~triton_client_tpu.channel.staged.StagedChannel`
    engine: staging slots are per MESH (one admission window over all
    devices), so batch N+1's host->device scatter overlaps batch N's
    mesh-wide execution. The launcher is a cached
    ``jax.jit(..., in_shardings=(batch_sharding, None),
    donate_argnums=...)`` so consecutive padded batches reuse the same
    per-device HBM input shards.

``BatchingChannel`` stacks in front unchanged through the ``inner``
channel interface and reads :attr:`batch_multiple` (the data-axis
width) to size merge groups up to ``max_batch x data_axis`` and align
its pad buckets, so batcher padding and shard padding never disagree.

Models whose spec declares ``max_batch_size <= 1`` (pointpillars: the
leading ``points`` dim is a point-count bucket, not a batch) cannot be
row-split; they run fully replicated on the mesh — same answers,
no speedup — so one server can still serve a mixed model set.
"""

from __future__ import annotations

import jax
import numpy as np

from triton_client_tpu.channel.staged import (
    SEGMENT_IDS_KEY,
    StagedChannel,
    cast_wire_input,
)
from triton_client_tpu.obs.roofline import name_launcher
from triton_client_tpu.parallel.mesh import (
    data_axis_size,
    replicate_params,
    serving_shardings,
)
from triton_client_tpu.parallel.ragged_kernels import (
    ShardedRaggedLayout,
    shard_segment_ids,
    unshard_segments,
)
from triton_client_tpu.runtime.padding import bucket_for, pad_batch, unpad_rows


class ShardedTPUChannel(StagedChannel):
    """Data-parallel serving channel over every device of the mesh."""

    # -- placement ------------------------------------------------------------

    @property
    def batch_multiple(self) -> int:
        """The data-axis width: the batcher sizes merge groups and pad
        buckets off this so a merged batch always splits evenly."""
        return data_axis_size(self._mesh)

    def _batched_names(self, model) -> frozenset[str]:
        """Inputs carrying the request batch on their leading dim.

        Triton's own convention: a model is batchable iff its spec
        declares ``max_batch_size > 1``, and then every input whose
        leading dim is dynamic (-1) is batch-leading. Models at the
        default ``max_batch_size=1`` have NO batch inputs here — their
        dynamic leading dims mean something else (pointpillars' point
        count) and splitting them over devices would change answers."""
        if model.spec.max_batch_size <= 1:
            return frozenset()
        return frozenset(
            t.name for t in model.spec.inputs if t.shape and t.shape[0] == -1
        )

    def _place_inputs(self, model, request):
        batch_s, repl_s = serving_shardings(self._mesh)
        multiple = self.batch_multiple
        batched = self._batched_names(model)
        # the request batch: leading dim of the first declared batched
        # input (spec order, so every request of a model agrees)
        n = None
        for t in model.spec.inputs:
            if t.name in batched and t.name in request.inputs:
                n = int(np.asarray(request.inputs[t.name]).shape[0])
                break
        target = bucket_for(n, multiple) if n is not None else None
        device_inputs = {}
        for name, arr in request.inputs.items():
            arr = cast_wire_input(model, name, np.asarray(arr))
            if (
                n is not None
                and name in batched
                and arr.ndim > 0
                and arr.shape[0] == n
            ):
                # pad rows replicate a real row (bitwise-safe; see
                # runtime/padding.py), then split rows over the data
                # axis — the only H2D path that scatters
                device_inputs[name] = jax.device_put(
                    pad_batch(arr, target), batch_s
                )
            else:
                device_inputs[name] = jax.device_put(arr, repl_s)
        # meta: (real rows, padded rows) so resolve can slice the pad
        # back off before the host copy pays for it
        meta = (n, target) if n is not None and target != n else None
        return device_inputs, meta

    def _place_ragged(self, model, request):
        """Packed-ragged placement over the mesh: the continuous
        batcher packed this request SHARD-MAJOR (``request.ragged`` is
        a :class:`ShardedRaggedLayout` built at ``batch_multiple``
        shards — every input's leading dim is ``n_shards * per_shard``),
        so one batch-sharded ``device_put`` hands each device exactly
        its contiguous segment group. Segment ids are shard-LOCAL: no
        segment straddles a device, so the launched body needs no
        cross-device collectives."""
        sl = request.ragged
        batch_s, repl_s = serving_shardings(self._mesh)
        w = sl.n_shards
        device_inputs = {}
        for name, arr in request.inputs.items():
            arr = cast_wire_input(model, name, np.asarray(arr))
            use = (
                batch_s
                if arr.ndim > 0 and arr.shape[0] % w == 0
                else repl_s
            )
            device_inputs[name] = jax.device_put(arr, use)
        device_inputs[SEGMENT_IDS_KEY] = jax.device_put(
            shard_segment_ids(sl), batch_s
        )
        return device_inputs, sl

    # -- launch ---------------------------------------------------------------

    def _make_ragged_launcher(self, model, num_segments: int):
        """Sharded ragged launcher: reshape every shard-major input to
        ``(n_shards, per_shard, ...)`` and ``vmap`` the model's
        segment-aware body over the shard axis — under the batch
        sharding each device then runs ONLY its own shard's segments
        (the shard-local ids keep every reduce device-local, the SPMD
        partitioner never inserts a collective). ``num_segments`` is
        the per-shard capacity (:attr:`ShardedRaggedLayout.seg_pad`)."""
        from triton_client_tpu.config import config_dtypes

        batch_s, _ = serving_shardings(self._mesh)
        w = data_axis_size(self._mesh)
        ragged_fn = model.ragged_fn

        # named distinctly from the dense `launcher`: this jit does NOT
        # donate, and tpulint's donor index pools jit-bound names
        # module-wide
        def ragged_launcher(device_inputs):
            inputs = dict(device_inputs)
            ids = inputs.pop(SEGMENT_IDS_KEY).reshape(w, -1)
            sharded = {
                k: v.reshape(w, v.shape[0] // w, *v.shape[1:])
                for k, v in inputs.items()
            }
            out = jax.vmap(
                lambda inp, i: ragged_fn(inp, i, num_segments)
            )(sharded, ids)
            return {
                k: v.reshape(w * v.shape[1], *v.shape[2:])
                for k, v in out.items()
            }

        # stamped with the model's launcher name (runtime only — the
        # local binding above keeps lint's donor index unambiguous) so
        # profiler op events attribute by HLO module (obs/opstats.py)
        ragged_launcher = jax.jit(name_launcher(ragged_launcher, model))

        out_dtype = {
            t.name: config_dtypes().get(t.dtype) for t in model.spec.outputs
        }
        return ragged_launcher, out_dtype

    def _make_launcher(self, model):
        """Cached sharded launcher: donated arg carries the batched
        donatable inputs with an explicit ``in_shardings`` batch
        sharding (so XLA reuses the per-device input shards across
        consecutive padded batches), everything else propagates its
        device_put placement. An explicit ``model.params`` tree is
        replicated onto the mesh ONCE here and closed over as a
        committed jit argument — including int8 ``QuantizedParam``
        leaves (runtime/precision.py registered pytree nodes): the
        policy quantized the tree at registration, so the SMALL tree is
        what ships to every device."""
        from triton_client_tpu.config import config_dtypes

        batch_s, repl_s = serving_shardings(self._mesh)
        batched = self._batched_names(model)
        donate_names = (
            frozenset(model.spec.donatable_inputs()) & batched
            if self._donate
            else frozenset()
        )
        device_fn = self._device_body(model)
        out_dtype = {
            t.name: config_dtypes().get(t.dtype) for t in model.spec.outputs
        }
        if model.params is not None:
            placed = replicate_params(model.params, self._mesh)
            if self._lifecycle is not None:
                # refine the lifecycle manager's HBM accounting with the
                # measured per-device bytes of the placed tree (.nbytes
                # is sharding metadata — no host sync)
                nbytes = sum(
                    int(x.nbytes)
                    for x in jax.tree_util.tree_leaves(placed)
                    if hasattr(x, "nbytes")
                )
                self._lifecycle.note_cost(
                    model.spec.name, model.spec.version, nbytes
                )
            jitted = jax.jit(
                name_launcher(
                    lambda params, batched, rest: device_fn(
                        {**batched, **rest}, params
                    ),
                    model,
                ),
                in_shardings=(repl_s, batch_s, None),
                donate_argnums=(1,),
            )
            outer = lambda d, k: jitted(placed, d, k)  # noqa: E731
            # cost-measurement seam (obs/roofline.py): the channel's
            # measured flops/bytes capture lowers the launcher with the
            # first launch's args — forward to the underlying jit with
            # the closed-over params in place (lowering only traces;
            # nothing is donated, hence the distinct parameter names)
            outer.lower = lambda db, kb: jitted.lower(placed, db, kb)
            return outer, donate_names, out_dtype
        launcher = jax.jit(
            name_launcher(
                lambda donated, kept: device_fn({**donated, **kept}), model
            ),
            in_shardings=(batch_s, None),
            donate_argnums=(0,),
        )
        return launcher, donate_names, out_dtype

    # -- readback -------------------------------------------------------------

    def _host_outputs(self, outputs, out_dtype, meta) -> dict:
        """Slice pad rows off batch-leading outputs (lazy device slice —
        the host copy only ever pays for real rows), then the base
        wire-dtype readback."""
        if isinstance(meta, ShardedRaggedLayout):
            # gather real segments per shard back into request order
            # (lazy per-shard slices; dead seg_pad slots never copy)
            outputs = {
                k: unshard_segments(v, meta)
                if getattr(v, "ndim", 0) >= 1
                and v.shape[0] == meta.n_shards * meta.seg_pad
                else v
                for k, v in outputs.items()
            }
            return StagedChannel._host_outputs(self, outputs, out_dtype, None)
        if meta is not None:
            n, target = meta
            outputs = {
                k: unpad_rows(v, n)
                if getattr(v, "ndim", 0) >= 1 and v.shape[0] == target
                else v
                for k, v in outputs.items()
            }
        return super()._host_outputs(outputs, out_dtype, meta)
