"""Channel protocol: register / metadata / infer.

Mirrors the seam of the reference's BaseChannel
(communicator/channel/base_channel.py:12-34) with two deliberate
departures:

  * requests/responses are typed dicts of numpy arrays, not a mutable
    protobuf ModelInferRequest the driver re-fills per frame
    (grpc_channel.py:63-78) — no serialization on the in-process path;
  * do_inference takes the request explicitly instead of reading
    channel-held mutable state, so channels are thread-safe and the
    driver can pipeline frame N+1's preprocess against frame N's infer.
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Mapping

import numpy as np


@dataclasses.dataclass
class InferRequest:
    model_name: str
    inputs: Mapping[str, np.ndarray]
    model_version: str = ""
    request_id: str = ""
    # request-scoped telemetry (obs.trace.RequestTrace / MultiTrace).
    # None on the un-traced hot path: channels guard on the attribute,
    # so disabled tracing costs one attribute read per phase.
    trace: object | None = dataclasses.field(
        default=None, repr=False, compare=False
    )
    # SLO deadline plane (obs.slo.SLOTracker): the absolute
    # perf_counter deadline stamped at admission, carried through the
    # batcher (a merged group takes the min of its members') to the
    # staged launchers, which count launches past it. None = no SLO.
    deadline_s: float | None = dataclasses.field(
        default=None, repr=False, compare=False
    )
    # scheduling/reporting class: attainment counters split on it, and
    # the continuous-batching scheduler (ROADMAP item 1) will order on
    # it. Higher = more important.
    priority: int = dataclasses.field(default=0, repr=False, compare=False)
    # packed-ragged marker (parallel.ragged_kernels.RaggedLayout): set
    # by the continuous batcher when this request's inputs are a packed
    # concatenation of several member requests' rows. None on every
    # dense request — channels guard on the attribute, so the dense
    # path pays one attribute read.
    ragged: object | None = dataclasses.field(
        default=None, repr=False, compare=False
    )
    # per-input-tensor wire parameters (input name -> params dict),
    # e.g. runtime/wire_encoding's ``content_encoding`` for inputs that
    # travel compressed (JPEG bytes, quantized pointclouds) and decode
    # server-side. Only remote channels read it; None on the hot path.
    input_params: Mapping[str, dict] | None = dataclasses.field(
        default=None, repr=False, compare=False
    )
    # streaming-session identity (runtime/sessions.py): frames of one
    # stream carry the same sequence_id; start/end bracket the stream's
    # life. Empty = stateless request — every existing path. Stateful
    # requests are solo-batched, affinity-routed, and never hedged.
    sequence_id: str = dataclasses.field(default="", repr=False, compare=False)
    sequence_start: bool = dataclasses.field(
        default=False, repr=False, compare=False
    )
    sequence_end: bool = dataclasses.field(
        default=False, repr=False, compare=False
    )


@dataclasses.dataclass
class InferResponse:
    model_name: str
    outputs: dict[str, np.ndarray]
    model_version: str = ""
    request_id: str = ""
    # device-side compute seconds, for the observability stack
    latency_s: float = 0.0
    # response-level kserve parameters decoded off the wire (e.g. the
    # server's compact span summary under obs.trace.SUMMARY_PARAM_KEY).
    # None on in-process channels and un-traced responses.
    parameters: dict | None = dataclasses.field(
        default=None, repr=False, compare=False
    )


class InferFuture:
    """Handle for an in-flight inference round-trip.

    ``result()`` blocks until the response is ready and returns it (or
    raises the deferred error). The reference defines an ``--async``
    flag it never exercises (main.py:59-70); this future is the real
    thing: channels issue the work on do_inference_async and the driver
    keeps several requests in flight, overlapping host preprocess with
    device/remote compute. Resolution is single-consumer: the driver
    retires each future exactly once, in issue order.

    Transports whose underlying handle can signal completion or be
    abandoned (gRPC call futures) wire the optional ``cancel`` /
    ``subscribe`` hooks; the front-door router (runtime/router.py)
    uses them to take the first hedged winner and cancel the loser.
    Lazy futures (the base-channel fallback, deferred TPU readback)
    leave them unset: ``cancel()`` is then a no-op returning False, and
    ``add_done_callback`` fires immediately — meaning only "result()
    may be called", which for a lazy future is always true.
    """

    __slots__ = ("_resolve", "_done", "_value", "_error", "_cancel",
                 "_subscribe")

    def __init__(self, resolve, cancel=None, subscribe=None) -> None:
        self._resolve = resolve
        self._done = False
        self._value = None
        self._error: BaseException | None = None
        self._cancel = cancel
        self._subscribe = subscribe

    @classmethod
    def completed(cls, value) -> "InferFuture":
        fut = cls(lambda: value)
        fut._done, fut._value = True, value
        return fut

    @classmethod
    def failed(cls, error: BaseException) -> "InferFuture":
        fut = cls(None)
        fut._done, fut._error = True, error
        return fut

    def result(self):
        if not self._done:
            try:
                self._value = self._resolve()
            except BaseException as e:
                self._error = e
            finally:
                self._done = True
                self._resolve = None  # free the closure (it may pin buffers)
        if self._error is not None:
            raise self._error
        return self._value

    def map(self, fn) -> "InferFuture":
        """A future whose result is ``fn(self.result())`` (lazy)."""
        return InferFuture(lambda: fn(self.result()))

    def cancel(self) -> bool:
        """Best-effort abandon of the in-flight work. Returns True only
        when the transport accepted the cancellation (the gRPC call had
        not completed); a lazy or already-retired future returns False.
        After a successful cancel, result() raises the transport's
        CANCELLED error — the caller must not expect a value."""
        if self._done or self._cancel is None:
            return False
        try:
            return bool(self._cancel())
        except Exception:
            return False

    def add_done_callback(self, fn) -> None:
        """Run ``fn()`` (no arguments) once result() will no longer
        block. Transport-backed futures invoke it from the transport's
        completion thread — keep it tiny and non-blocking (the router
        posts to a queue). Lazy futures invoke it immediately on the
        calling thread: their result() is always callable, it just does
        the work inline. fn must not raise; a raise is swallowed after
        logging nothing (completion threads must never die)."""
        sub = self._subscribe
        if sub is not None and not self._done:
            try:
                sub(fn)
                return
            except Exception:
                pass
        try:
            fn()
        except Exception:
            pass


class BaseChannel(abc.ABC):
    """Transport abstraction between drivers (L4) and models."""

    @abc.abstractmethod
    def register_channel(self) -> None:
        """Establish the transport (claim devices / dial the endpoint)."""

    @abc.abstractmethod
    def fetch_channel(self):
        """Return the underlying transport handle."""

    @abc.abstractmethod
    def get_metadata(self, model_name: str, model_version: str = ""):
        """Return the ModelSpec for a served model."""

    @abc.abstractmethod
    def do_inference(self, request: InferRequest) -> InferResponse:
        """Run one inference round-trip."""

    def do_inference_async(self, request: InferRequest) -> InferFuture:
        """Issue an inference without blocking for the response.

        Transports that can genuinely overlap (gRPC futures, JAX async
        dispatch) override this; the base implementation degrades to the
        blocking call wrapped in a completed future, so every channel
        supports the async driver path with unchanged semantics."""
        try:
            return InferFuture.completed(self.do_inference(request))
        except Exception as e:  # KeyboardInterrupt/SystemExit stay immediate
            return InferFuture.failed(e)
