"""Channel protocol: register / metadata / infer.

Mirrors the seam of the reference's BaseChannel
(communicator/channel/base_channel.py:12-34) with two deliberate
departures:

  * requests/responses are typed dicts of numpy arrays, not a mutable
    protobuf ModelInferRequest the driver re-fills per frame
    (grpc_channel.py:63-78) — no serialization on the in-process path;
  * do_inference takes the request explicitly instead of reading
    channel-held mutable state, so channels are thread-safe and the
    driver can pipeline frame N+1's preprocess against frame N's infer.
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Mapping

import numpy as np


@dataclasses.dataclass
class InferRequest:
    model_name: str
    inputs: Mapping[str, np.ndarray]
    model_version: str = ""
    request_id: str = ""


@dataclasses.dataclass
class InferResponse:
    model_name: str
    outputs: dict[str, np.ndarray]
    model_version: str = ""
    request_id: str = ""
    # device-side compute seconds, for the observability stack
    latency_s: float = 0.0


class BaseChannel(abc.ABC):
    """Transport abstraction between drivers (L4) and models."""

    @abc.abstractmethod
    def register_channel(self) -> None:
        """Establish the transport (claim devices / dial the endpoint)."""

    @abc.abstractmethod
    def fetch_channel(self):
        """Return the underlying transport handle."""

    @abc.abstractmethod
    def get_metadata(self, model_name: str, model_version: str = ""):
        """Return the ModelSpec for a served model."""

    @abc.abstractmethod
    def do_inference(self, request: InferRequest) -> InferResponse:
        """Run one inference round-trip."""
