"""L3 transport seam.

The reference's BaseChannel (communicator/channel/base_channel.py:12-34)
is the boundary this framework swings on: where the reference's only
implementation crosses a network to a remote Triton server
(grpc_channel.py), the primary implementation here is an in-process
dispatch to jit-compiled functions on the local TPU mesh.
"""

from triton_client_tpu.channel.base import (
    BaseChannel,
    InferRequest,
    InferResponse,
)
from triton_client_tpu.channel.sharded_channel import ShardedTPUChannel
from triton_client_tpu.channel.staged import StagedChannel
from triton_client_tpu.channel.tpu_channel import TPUChannel

__all__ = [
    "BaseChannel",
    "GRPCChannel",
    "InferRequest",
    "InferResponse",
    "ShardedTPUChannel",
    "StagedChannel",
    "TPUChannel",
]


def __getattr__(name):
    # Lazy: the remote path needs grpcio/protobuf (optional extra); the
    # in-process TPUChannel path must import without them.
    if name == "GRPCChannel":
        from triton_client_tpu.channel.grpc_channel import GRPCChannel

        return GRPCChannel
    raise AttributeError(name)
