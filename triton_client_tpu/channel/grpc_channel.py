"""GRPCChannel: KServe v2 client channel (remote-inference path).

The drop-in analogue of the reference's GRPCChannel
(communicator/channel/grpc_channel.py:8-84) so a driver can point at a
remote server — this framework's InferenceServer on a TPU host, or a
stock Triton — through the same BaseChannel seam the in-process
TPUChannel implements. Departures from the reference:

  * the message-size cap starts at a 64 MiB floor and grows on demand:
    get_metadata() sizes the served contract and re-dials with a larger
    cap when the model needs one — not ``batch_size * 8568044``
    hardcoded (grpc_channel.py:26-29, README.md:118 "make dynamic");
  * requests are built per call from typed arrays (zero-copy codec) —
    no shared mutable ModelInferRequest (grpc_channel.py:63-71), so the
    channel is thread-safe and drivers can pipeline;
  * transient RPC failures retry with exponential backoff instead of
    crashing the callback (the reference has no retry story, SURVEY.md
    §5 "failure detection: none").
"""

from __future__ import annotations

import collections
import itertools
import json
import logging
import os
import random
import threading
import time
import weakref

import grpc
import numpy as np

from triton_client_tpu.channel import transport as transports
from triton_client_tpu.channel.base import (
    BaseChannel,
    InferFuture,
    InferRequest,
    InferResponse,
)
from triton_client_tpu.channel.kserve import codec, pb, service
from triton_client_tpu.config import FRAMING_BYTES, ModelSpec, TensorSpec
from triton_client_tpu.obs.trace import SUMMARY_PARAM_KEY, TraceContext
from triton_client_tpu.runtime.shared_memory import ShmRegionPool

log = logging.getLogger(__name__)


def _wire_params(request: InferRequest) -> dict | None:
    """Request-level kserve parameters for one outbound ModelInfer:
    the W3C-style trace context (when the request's trace carries one)
    and the scheduling priority. None on the common untraced path so
    the codec skips the parameters map entirely."""
    params = None
    tr = request.trace
    ctx = getattr(tr, "context", None) if tr is not None else None
    if ctx is not None:
        params = {TraceContext.PARAM_KEY: ctx.encode()}
    if request.priority:
        if params is None:
            params = {}
        params["priority"] = int(request.priority)
    if request.sequence_id:
        if params is None:
            params = {}
        params[codec.SEQUENCE_ID_PARAM] = str(request.sequence_id)
        if request.sequence_start:
            params[codec.SEQUENCE_START_PARAM] = True
        if request.sequence_end:
            params[codec.SEQUENCE_END_PARAM] = True
    return params


def _response_params(resp) -> dict | None:
    """Response-level parameters decoded off the wire — today just the
    server's compact span summary, which the router (or any tracing
    client) grafts onto its own timeline."""
    raw = codec.get_string_param(resp, SUMMARY_PARAM_KEY)
    if raw is None:
        return None
    return {SUMMARY_PARAM_KEY: raw}

_RETRYABLE = (
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.DEADLINE_EXCEEDED,
    grpc.StatusCode.RESOURCE_EXHAUSTED,
)
# ModelInfer may have executed server-side when the deadline fires, so
# only connection-level failures are safe to re-issue automatically.
# RESOURCE_EXHAUSTED is additionally a DELIBERATE server decision (the
# admission controller shed the request); re-issuing it would feed the
# exact overload the server is shedding — clients must back off or
# drop, so it is surfaced immediately and counted (stats()).
_INFER_RETRYABLE = (grpc.StatusCode.UNAVAILABLE,)

# retry backoff ceiling: with jitter, retries from a client fleet decor-
# relate instead of arriving in synchronized waves at each 2^n step
_BACKOFF_CAP_S = 5.0


class DeadlineExceededRpcError(grpc.RpcError):
    """Client-local deadline failure, raised WITHOUT touching the wire.

    The retry ladder synthesizes this when the request's remaining
    deadline budget is gone — either already expired, or so short the
    next backoff sleep would expire it. It subclasses grpc.RpcError and
    answers code()/details() so every caller's status-code dispatch
    (the router, _record_infer_error, tests) handles it exactly like a
    server-sent DEADLINE_EXCEEDED."""

    def __init__(self, details: str) -> None:
        super().__init__(details)
        self._details = details

    def code(self) -> grpc.StatusCode:
        return grpc.StatusCode.DEADLINE_EXCEEDED

    def details(self) -> str:
        return self._details

# shared-memory region-name tag: process-wide monotonic so no two
# channel instances (live or dead) ever share a name prefix
_SHM_CHANNEL_SEQ = itertools.count()

# A server that answers the shm extension with one of these codes does
# not serve it at all (stock gRPC UNIMPLEMENTED, the server's same-host
# PERMISSION_DENIED gate for tunneled "loopback" dials, fake test
# servicers' UNKNOWN): an auto-negotiated channel falls back to the
# wire permanently instead of failing every request. INVALID_ARGUMENT
# is deliberately absent — that is the restart-recovery signal.
_SHM_UNSUPPORTED = (
    grpc.StatusCode.UNIMPLEMENTED,
    grpc.StatusCode.PERMISSION_DENIED,
    grpc.StatusCode.UNKNOWN,
)

# per-output alignment inside a slot's output arena (cache-line)
_SHM_OUT_ALIGN = 64


class GRPCChannel(BaseChannel):
    def __init__(
        self,
        endpoint: str,
        max_message_bytes: int = 64 << 20,
        timeout_s: float = 30.0,
        retries: int = 3,
        backoff_s: float = 0.1,
        use_shared_memory: bool | None = None,
        pipeline_depth: int = 4,
    ) -> None:
        """``use_shared_memory``: same-host transport — inputs are
        written into client-owned POSIX shm segments and requests carry
        only region coordinates (Triton system-shared-memory
        extension), skipping the protobuf serialize/copy/deserialize of
        the tensor payload in both processes. ``None`` (the default)
        auto-detects: loopback and ``unix:`` endpoints with a usable
        /dev/shm ride shm, everything else rides the wire
        (channel/transport.py's eligibility matrix); a same-host-
        looking endpoint whose server rejects the extension (a
        tunnel, a stock server without it) degrades to the wire once
        and permanently. ``True``/``False`` force the decision.

        Regions live in a pool of ``pipeline_depth`` slots, each
        generation-tagged per input and sized to the largest array
        seen: ``do_inference``, ``do_inference_async`` and
        ``infer_stream`` all ride shm concurrently — the
        ``pipeline_depth+1``-th in-flight request blocks until a slot
        frees (natural backpressure mirroring the server's staging
        pipeline). Responses ride shm too once the channel has seen a
        model's output sizes (requested-output windows in a per-slot
        arena the server writes readback bytes into directly)."""
        self._endpoint = endpoint
        self._max_message_bytes = max_message_bytes
        self._timeout_s = timeout_s
        self._retries = retries
        self._backoff_s = backoff_s
        self._channel: grpc.Channel | None = None
        self._stub: service.GRPCInferenceServiceStub | None = None
        self._retired: list[grpc.Channel] = []
        self._shm_auto = use_shared_memory is None
        self._use_shm = (
            transports.shm_eligible(endpoint)
            if use_shared_memory is None
            else bool(use_shared_memory)
        )
        self._pipeline_depth = max(1, int(pipeline_depth))
        # region names were keyed on id(self), which CPython reuses
        # after GC: a dead channel whose close() failed to unregister
        # server-side left a stale registry entry that a NEW channel
        # reusing the id would collide with forever. A process-wide
        # monotonic tag can never recur within the process.
        self._shm_tag = next(_SHM_CHANNEL_SEQ)
        self._pool: ShmRegionPool | None = None
        self._pool_lock = threading.Lock()
        # learned response contract: model -> output name -> max bytes
        # seen. The first request for a model gets its response over
        # the wire; every later one carries requested-output windows
        # sized from this map, so responses bypass the wire too.
        self._learned_out: dict[str, dict[str, int]] = {}
        # client-side overload ledger: sheds the server sent back
        # (RESOURCE_EXHAUSTED on ModelInfer — never retried) vs
        # transient retries the ladder absorbed
        self._infer_rejections = 0
        self._retries_total = 0
        self.register_channel()

    @property
    def transport(self) -> str:
        """Negotiated transport label: ``grpc`` / ``uds`` / ``shm`` /
        ``uds+shm`` (channel/transport.py). Reported by stats(), the
        route CLI, and bench rows."""
        return transports.negotiated(self._endpoint, self._use_shm)

    # -- BaseChannel protocol -------------------------------------------------

    def register_channel(self) -> None:
        self._channel = grpc.insecure_channel(
            self._endpoint,
            options=[
                ("grpc.max_send_message_length", self._max_message_bytes),
                ("grpc.max_receive_message_length", self._max_message_bytes),
            ],
        )
        self._stub = service.GRPCInferenceServiceStub(self._channel)

    def fetch_channel(self) -> grpc.Channel:
        return self._channel

    def get_metadata(self, model_name: str, model_version: str = "") -> ModelSpec:
        meta = self._call(
            self._stub.ModelMetadata,
            pb.ModelMetadataRequest(name=model_name, version=model_version),
        )
        config = self._call(
            self._stub.ModelConfig,
            pb.ModelConfigRequest(name=model_name, version=model_version),
        ).config
        import json

        spec = ModelSpec(
            name=meta.name,
            version=model_version or (meta.versions[-1] if meta.versions else "1"),
            platform=meta.platform,
            inputs=tuple(
                TensorSpec(t.name, tuple(t.shape), t.datatype) for t in meta.inputs
            ),
            outputs=tuple(
                TensorSpec(t.name, tuple(t.shape), t.datatype) for t in meta.outputs
            ),
            max_batch_size=config.max_batch_size,
            extra={k: json.loads(v) for k, v in config.parameters.items()},
        )
        needed = 2 * spec.wire_bytes() + FRAMING_BYTES
        if needed > self._max_message_bytes:
            # Re-dial with the larger cap. The old channel is retired,
            # not closed: closing would cancel RPCs other threads have
            # in flight on it; it is drained and closed in close().
            self._max_message_bytes = needed
            if self._channel is not None:
                self._retired.append(self._channel)
            self.register_channel()
        return spec

    def do_inference(self, request: InferRequest) -> InferResponse:
        # fail-fast BEFORE any transport work: the shm path's region
        # registration is itself a wire RPC, and an already-expired
        # deadline must surface as DEADLINE_EXCEEDED, not whatever
        # that RPC happens to return
        if (
            request.deadline_s is not None
            and request.deadline_s - time.perf_counter() <= 0
        ):
            raise DeadlineExceededRpcError(
                "deadline expired before ModelInfer was issued"
            )
        if self._use_shm:
            try:
                return self._do_inference_shm(request)
            except grpc.RpcError as e:
                if not self._maybe_disable_shm(e):
                    raise
                # degraded to the wire (server lacks the extension)
        wire = codec.build_infer_request(
            model_name=request.model_name,
            inputs=request.inputs,
            model_version=request.model_version,
            request_id=request.request_id,
            parameters=_wire_params(request),
            input_parameters=request.input_params,
        )
        t0 = time.perf_counter()
        try:
            resp = self._call(
                self._stub.ModelInfer,
                wire,
                retryable=_INFER_RETRYABLE,
                deadline_s=request.deadline_s,
            )
        except grpc.RpcError as e:
            self._record_infer_error(e)
            raise
        return InferResponse(
            model_name=resp.model_name,
            model_version=resp.model_version,
            outputs=codec.parse_infer_response(resp),
            request_id=resp.id,
            latency_s=time.perf_counter() - t0,
            parameters=_response_params(resp),
        )

    # -- shared-memory transport ----------------------------------------------

    def _shm_pool(self) -> ShmRegionPool:
        pool = self._pool
        if pool is not None:
            return pool
        with self._pool_lock:
            if self._pool is None:
                # the pool's RPC callbacks must not hold a strong ref
                # back to the channel: channel->pool->bound-method->
                # channel is a cycle, and a CLI that simply drops its
                # channel then relies on refcount-immediate __del__ to
                # unregister + unlink /dev/shm segments — gc-deferred
                # teardown leaves regions registered on the server
                def _weak(method):
                    ref = weakref.WeakMethod(method)

                    def call(*a):
                        fn = ref()
                        if fn is not None:
                            fn(*a)

                    return call

                self._pool = ShmRegionPool(
                    tag=f"tct_{os.getpid()}_{self._shm_tag}",
                    depth=self._pipeline_depth,
                    register_fn=_weak(self._shm_register),
                    unregister_fn=_weak(self._shm_unregister_quiet),
                )
            return self._pool

    def _shm_register(self, name: str, key: str, byte_size: int) -> None:
        # no retry: register is not idempotent (duplicate names are
        # rejected), and it is a fast metadata RPC — a transient
        # failure surfaces to the caller, who may simply call again
        self._call(
            self._stub.SystemSharedMemoryRegister,
            pb.SystemSharedMemoryRegisterRequest(
                name=name, key=key, offset=0, byte_size=byte_size
            ),
            retryable=(),
        )

    def _shm_unregister_quiet(self, name: str) -> None:
        """Best-effort unregister (growth path, recovery's duplicate-
        name guard, teardown): failure must never mask the operation
        that needed it."""
        try:
            self._stub.SystemSharedMemoryUnregister(
                pb.SystemSharedMemoryUnregisterRequest(name=name),
                timeout=min(self._timeout_s, 2.0),
            )
        except grpc.RpcError as e:
            log.debug("unregister of shm region %s failed (%s)", name, e)

    def _maybe_disable_shm(self, e: grpc.RpcError) -> bool:
        """Auto-negotiation escape hatch: a server that answers the shm
        extension with UNIMPLEMENTED / PERMISSION_DENIED / UNKNOWN does
        not serve it (stock server, tunneled dial that only LOOKS
        loopback, fake test servicer) — flip this channel to the wire
        permanently and tell the caller to re-issue there. Forced
        ``use_shared_memory=True`` never degrades."""
        code = e.code() if hasattr(e, "code") else None
        if self._shm_auto and code in _SHM_UNSUPPORTED:
            log.info(
                "endpoint %s does not serve the shared-memory extension "
                "(%s); negotiated transport falls back to the wire",
                self._endpoint, code,
            )
            self._use_shm = False
            return True
        return False

    def _stage_shm(
        self,
        request: InferRequest,
        extra_params: dict | None = None,
        acquire_timeout_s: float | None = None,
    ):
        """Acquire a pool slot, write the request's inputs into its
        regions, and build the coordinate-carrying wire message.
        Returns ``(wire, slot)`` with the slot owned by the caller (it
        must be released when the response is parsed or the request
        abandoned). Known output sizes additionally attach requested-
        output windows in the slot's arena so the response bypasses
        the wire too. ``acquire_timeout_s`` overrides how long to wait
        for a free slot (the async path passes 0: a caller that
        pipelines PAST the pool depth overflows onto the wire rather
        than deadlocking its own issuing thread, since slots only free
        when that thread resolves futures)."""
        pool = self._shm_pool()
        slot = pool.acquire(
            timeout_s=self._timeout_s
            if acquire_timeout_s is None
            else acquire_timeout_s
        )
        try:
            shm_inputs = {}
            arrays = {}
            for name, value in request.inputs.items():
                arr = np.asarray(value)
                region = slot.region_for(f"i_{name}", arr.nbytes)
                region.write(arr)
                shm_inputs[name] = (region.key.lstrip("/"), 0, arr.nbytes)
                arrays[name] = arr
            params = _wire_params(request)
            if extra_params:
                params = {**(params or {}), **extra_params}
            wire = codec.build_infer_request_shm(
                model_name=request.model_name,
                inputs=arrays,
                shm_inputs=shm_inputs,
                model_version=request.model_version,
                request_id=request.request_id,
                parameters=params,
                input_parameters=request.input_params,
            )
            self._request_shm_outputs(wire, slot, request.model_name)
            return wire, slot
        except BaseException:
            pool.release(slot)
            raise

    def _request_shm_outputs(self, wire, slot, model_name: str) -> None:
        """Attach requested-output windows (learned sizes, cache-line
        aligned) in the slot's output arena. No-op until a first
        response has taught the channel this model's output sizes."""
        sizes = self._learned_out.get(model_name)
        if not sizes:
            return
        offsets = {}
        total = 0
        for name in sorted(sizes):
            offsets[name] = total
            total += -(-sizes[name] // _SHM_OUT_ALIGN) * _SHM_OUT_ALIGN
        arena = slot.region_for("o", total)
        rname = arena.key.lstrip("/")
        for name, off in offsets.items():
            codec.add_requested_output(wire, name, rname, off, sizes[name])

    def _parse_shm_response(
        self, resp, slot, model_name: str, t0: float
    ) -> InferResponse:
        regions = {}
        arena = slot.regions.get("o")
        if arena is not None:
            regions[arena.key.lstrip("/")] = arena
        outputs = codec.parse_infer_response(resp, regions=regions)
        if arena is not None:
            # arena views die with the slot (the next request on this
            # slot overwrites them): materialize arena-backed outputs
            # into owned arrays — the single designed host copy on the
            # response path, replacing protobuf serialize + framing +
            # parse. Wire-backed views keep their protobuf buffer.
            arena_outs = {
                t.name for t in resp.outputs
                if codec.shm_params(t) is not None
            }
            for name in arena_outs:
                outputs[name] = np.copy(outputs[name])
        sizes = self._learned_out.setdefault(model_name, {})
        for name, arr in outputs.items():
            if sizes.get(name, 0) < arr.nbytes:
                sizes[name] = arr.nbytes
        return InferResponse(
            model_name=resp.model_name,
            model_version=resp.model_version,
            outputs=outputs,
            request_id=resp.id,
            latency_s=time.perf_counter() - t0,
            parameters=_response_params(resp),
        )

    def _recover_shm(self, e: grpc.RpcError, wire, request: InferRequest):
        """A restarted server has an empty registry: its
        INVALID_ARGUMENT 'not registered' is recoverable by
        re-registering the pool's segments and re-issuing once — the
        wire path recovers from restarts via the UNAVAILABLE ladder,
        the shm path must not be worse."""
        if not (
            e.code() == grpc.StatusCode.INVALID_ARGUMENT
            and "not registered" in (e.details() or "")
        ):
            self._record_infer_error(e)
            raise e
        pool = self._shm_pool()
        log.warning(
            "server lost shared-memory registrations (%s); "
            "re-registering %d region(s)",
            e.details(), len(pool.regions()),
        )
        pool.reregister_all()
        return self._call(
            self._stub.ModelInfer,
            wire,
            retryable=_INFER_RETRYABLE,
            deadline_s=request.deadline_s,
        )

    def _do_inference_shm(self, request: InferRequest) -> InferResponse:
        wire, slot = self._stage_shm(request)
        pool = self._pool
        try:
            t0 = time.perf_counter()
            try:
                # UNAVAILABLE-only retry, same contract as the wire path
                resp = self._call(
                    self._stub.ModelInfer,
                    wire,
                    retryable=_INFER_RETRYABLE,
                    deadline_s=request.deadline_s,
                )
            except grpc.RpcError as e:
                resp = self._recover_shm(e, wire, request)
            return self._parse_shm_response(
                resp, slot, request.model_name, t0
            )
        finally:
            pool.release(slot)

    def do_inference_async(self, request: InferRequest) -> InferFuture:
        """Non-blocking ModelInfer via a gRPC call future (the --async
        path): the RPC is on the wire when this returns; result() parses
        the response. A connection-level failure (UNAVAILABLE — the only
        code safe to re-issue, see _call) falls back to the sync retry
        ladder at resolution time; all other errors surface at result().

        On a shm-negotiated channel the async path rides shm too: each
        in-flight request owns a pool slot (released at resolution), so
        up to ``pipeline_depth`` async calls overlap without ever
        aliasing a live region — the pre-round-13 wire fallback and its
        one-time warning are gone.

        The returned future is cancellable and subscribable (see
        InferFuture): cancel() abandons the wire call, and
        add_done_callback fires on the gRPC completion thread — the
        router's hedging relies on both to take the first winner and
        release the loser's replica slot. The resolution-time retry
        fallback honors request.deadline_s, so a failover retry never
        sleeps past the caller's budget."""
        # same pre-transport fail-fast as do_inference: async contract
        # says errors surface at result(), so wrap it in a future
        if (
            request.deadline_s is not None
            and request.deadline_s - time.perf_counter() <= 0
        ):
            return InferFuture.failed(
                DeadlineExceededRpcError(
                    "deadline expired before async ModelInfer was issued"
                )
            )
        if self._use_shm:
            try:
                return self._do_inference_async_shm(request)
            except TimeoutError:
                # pool exhausted: the overflow request rides the wire
                # (see _stage_shm — blocking here could deadlock a
                # single-threaded pipelining driver)
                pass
            except grpc.RpcError as e:
                if not self._maybe_disable_shm(e):
                    # async contract: errors surface at result()
                    return InferFuture.failed(e)
        try:
            wire = codec.build_infer_request(
                model_name=request.model_name,
                inputs=request.inputs,
                model_version=request.model_version,
                request_id=request.request_id,
                parameters=_wire_params(request),
                input_parameters=request.input_params,
            )
            t0 = time.perf_counter()
            call = self._issue_async(wire, request.deadline_s)
        except Exception as e:  # async contract: errors surface at result()
            return InferFuture.failed(e)

        def resolve() -> InferResponse:
            try:
                resp = call.result()
            except grpc.RpcError as e:
                resp = self._async_retry(e, wire, request)
            return InferResponse(
                model_name=resp.model_name,
                model_version=resp.model_version,
                outputs=codec.parse_infer_response(resp),
                request_id=resp.id,
                latency_s=time.perf_counter() - t0,
                parameters=_response_params(resp),
            )

        return InferFuture(
            resolve,
            cancel=call.cancel,
            subscribe=lambda fn: call.add_done_callback(lambda _c: fn()),
        )

    def _issue_async(self, wire, deadline_s: float | None):
        t0 = time.perf_counter()
        timeout = self._timeout_s
        if deadline_s is not None:
            remaining = deadline_s - t0
            if remaining <= 0:
                raise DeadlineExceededRpcError(
                    "deadline expired before async ModelInfer was issued"
                )
            timeout = min(timeout, remaining)
        return self._stub.ModelInfer.future(wire, timeout=timeout)

    def _async_retry(self, e: grpc.RpcError, wire, request: InferRequest):
        """Resolution-time fallback shared by the wire and shm async
        paths. Only connection-level failures (UNAVAILABLE) are
        re-issued automatically — the code least likely to mean the
        request executed server-side (no such gRPC code guarantees
        it). DEADLINE_EXCEEDED/RESOURCE_EXHAUSTED requests frequently
        HAVE executed, so re-running those is unsafe for
        non-idempotent models and doubles load exactly when the server
        is saturated. CANCELLED means our own cancel() won the race —
        never re-issue it."""
        self._record_infer_error(e)
        code = e.code() if hasattr(e, "code") else None
        if code not in _INFER_RETRYABLE:
            raise e
        log.warning(
            "async ModelInfer failed (%s); re-issuing on the "
            "sync retry path", code,
        )
        return self._call(
            self._stub.ModelInfer,
            wire,
            retryable=_INFER_RETRYABLE,
            deadline_s=request.deadline_s,
        )

    def _do_inference_async_shm(self, request: InferRequest) -> InferFuture:
        wire, slot = self._stage_shm(request, acquire_timeout_s=0.0)
        pool = self._pool
        try:
            t0 = time.perf_counter()
            call = self._issue_async(wire, request.deadline_s)
        except BaseException:
            pool.release(slot)
            raise

        def resolve() -> InferResponse:
            try:
                try:
                    resp = call.result()
                except grpc.RpcError as e:
                    if (
                        e.code() == grpc.StatusCode.INVALID_ARGUMENT
                        and "not registered" in (e.details() or "")
                    ):
                        resp = self._recover_shm(e, wire, request)
                    else:
                        resp = self._async_retry(e, wire, request)
                return self._parse_shm_response(
                    resp, slot, request.model_name, t0
                )
            finally:
                pool.release(slot)

        def cancel() -> bool:
            ok = call.cancel()
            if ok:
                # the server may still write this request's outputs
                # into the arena arbitrarily late: retire it (next use
                # re-creates a fresh generation) so the slot's next
                # owner can never be corrupted by a ghost write
                slot.retire("o")
                pool.release(slot)
            return ok

        return InferFuture(
            resolve,
            cancel=cancel,
            subscribe=lambda fn: call.add_done_callback(lambda _c: fn()),
        )

    # -- extras ---------------------------------------------------------------

    def server_live(self, timeout_s: float | None = None) -> bool:
        try:
            return self._call(
                self._stub.ServerLive, pb.ServerLiveRequest(),
                timeout_s=timeout_s,
            ).live
        except grpc.RpcError:
            return False

    def server_ready(self, timeout_s: float | None = None) -> bool:
        """Readiness (vs liveness): a DRAINING server stays live but
        flips not-ready first, so orchestrators pull it from rotation
        before its in-flight work finishes. ``timeout_s`` overrides the
        channel deadline for this probe — the router's health loop
        probes every replica each interval and must not hang an
        interval's budget on one dead endpoint."""
        try:
            return self._call(
                self._stub.ServerReady, pb.ServerReadyRequest(),
                timeout_s=timeout_s,
            ).ready
        except grpc.RpcError:
            return False

    def model_ready(
        self,
        model_name: str,
        model_version: str = "",
        timeout_s: float | None = None,
    ) -> bool:
        """Per-model readiness (KServe ModelReady): the router probes
        this for its configured model set so a replica that is live but
        has not yet loaded/warmed the model stays out of rotation."""
        try:
            return self._call(
                self._stub.ModelReady,
                pb.ModelReadyRequest(name=model_name, version=model_version),
                retryable=(),
                timeout_s=timeout_s,
            ).ready
        except grpc.RpcError:
            return False

    def repository_index(self) -> list[tuple[str, str, str]]:
        """[(name, version, state)] from the server's RepositoryIndex
        (the 'what is actually being served' query the reference could
        only get from Triton's logs)."""
        resp = self._call(
            self._stub.RepositoryIndex, pb.RepositoryIndexRequest()
        )
        return [(m.name, m.version, m.state) for m in resp.models]

    def _stream_groups(self, requests, group_size: int):
        """Batch consecutive compatible requests into frame groups of
        up to ``group_size`` for the multi-frame stream protocol. A
        request joins a group only when it matches the group head on
        model/version/priority and every input's shape+dtype, carries
        no trace or per-input params, and all inputs have a leading
        axis to pack along; anything else flushes the group and streams
        as a singleton. Grouping buffers up to group_size requests, so
        it suits open-loop producers (a camera, a replayed log) — a
        closed-loop caller that waits on responses must keep
        ``group_size=1``."""

        def groupable(r: InferRequest) -> bool:
            if r.trace is not None or r.input_params:
                return False
            if r.sequence_id:
                # a packed group travels under the HEAD's parameters —
                # session frames must each carry their own sequence
                # params (and two streams must never share a message)
                return False
            return all(np.asarray(v).ndim >= 1 for v in r.inputs.values())

        def compatible(a: InferRequest, b: InferRequest) -> bool:
            if (
                a.model_name != b.model_name
                or a.model_version != b.model_version
                or a.priority != b.priority
                or set(a.inputs) != set(b.inputs)
            ):
                return False
            return all(
                np.asarray(v).shape == np.asarray(b.inputs[k]).shape
                and np.asarray(v).dtype == np.asarray(b.inputs[k]).dtype
                for k, v in a.inputs.items()
            )

        group: list[InferRequest] = []
        for r in requests:
            if group_size > 1 and groupable(r):
                if group and not compatible(group[0], r):
                    yield group
                    group = []
                group.append(r)
                if len(group) >= group_size:
                    yield group
                    group = []
            else:
                if group:
                    yield group
                    group = []
                yield [r]
        if group:
            yield group

    def _stage_stream_group(self, members: list[InferRequest]):
        """One wire message for a group of G compatible requests:
        members' inputs are packed back-to-back along the leading axis
        — into a pooled shm region per input on a shm channel (no
        intermediate concatenation; the region write IS the pack), or
        into joined raw content on the wire. Returns ``(wire, slot)``;
        slot is None on the wire path. Responses always ride the wire:
        a stream multiplexes many in-flight requests per slot, so
        there is no per-request output arena to target."""
        first = members[0]
        g = len(members)
        slot = (
            self._shm_pool().acquire(timeout_s=self._timeout_s)
            if self._use_shm
            else None
        )
        try:
            req = pb.ModelInferRequest(
                model_name=first.model_name,
                model_version=first.model_version,
                id=first.request_id,
            )
            params = dict(_wire_params(first) or {})
            if g > 1:
                params[codec.STREAM_GROUP_PARAM] = g
                ids = [m.request_id for m in members]
                if any(ids):
                    params[codec.STREAM_GROUP_IDS_PARAM] = json.dumps(ids)
            codec.set_request_params(req, params)
            for name in sorted(first.inputs):
                arrs = [np.asarray(m.inputs[name]) for m in members]
                a0 = arrs[0]
                shape = (
                    (g * a0.shape[0],) + tuple(a0.shape[1:])
                    if g > 1
                    else a0.shape
                )
                t = req.inputs.add(
                    name=name, datatype=codec.datatype_of(a0), shape=shape
                )
                if g == 1 and first.input_params:
                    codec.set_request_params(
                        t, first.input_params.get(name)
                    )
                if slot is not None:
                    region = slot.region_for(f"i_{name}", g * a0.nbytes)
                    for i, a in enumerate(arrs):
                        region.write(a, offset=i * a0.nbytes)
                    codec.set_shm_params(
                        t, region.key.lstrip("/"), 0, g * a0.nbytes
                    )
                else:
                    req.raw_input_contents.append(
                        b"".join(codec.serialize_tensor(a) for a in arrs)
                    )
            return req, slot
        except BaseException:
            if slot is not None:
                self._pool.release(slot)
            raise

    def infer_stream(
        self,
        requests,
        stream_timeout_s: float | None = 3600.0,
        group_size: int = 1,
    ):
        """Bidirectional streaming inference (the reference's unused
        --streaming flag, main.py:66-70, made real). ``requests`` is an
        iterable of InferRequest; yields InferResponse in request order.

        On a shm-negotiated channel every stream entry stages its
        inputs through the region pool (one slot per in-flight group,
        released when the group's last response lands), so the stream
        path skips the tensor serialize/copy/deserialize exactly like
        unary shm — the pre-round-13 wire fallback is gone.

        ``group_size > 1`` enables the multi-frame protocol: up to that
        many consecutive compatible requests pack into ONE stream
        message (frames concatenated on the leading axis) that the
        server fans back into individual batcher requests, so a long
        tunnel RTT is paid once per group instead of once per frame.
        The server streams one response per member as each resolves; a
        whole-group failure is prefixed ``stream group failed:`` so it
        consumes all member responses at once.

        ``stream_timeout_s`` bounds the WHOLE stream (gRPC deadlines are
        per-call): a stalled server or a silent network partition
        surfaces as DEADLINE_EXCEEDED instead of hanging the client
        forever — the unary path gets the same protection from
        ``timeout_s`` per request. Pass None for an unbounded session
        (long-lived live streams)."""
        # appended by wire_iter on gRPC's request-consumer thread,
        # consumed in order here: the server answers each message only
        # after receiving it, so an entry is always enqueued before its
        # first response arrives (deque ops are atomic under the GIL)
        entries: collections.deque = collections.deque()

        def wire_iter():
            for members in self._stream_groups(requests, group_size):
                wire, slot = self._stage_stream_group(members)
                entries.append(
                    {"members": members, "slot": slot,
                     "remaining": len(members)}
                )
                yield wire

        call = self._stub.ModelStreamInfer(
            wire_iter(), timeout=stream_timeout_s
        )
        try:
            for resp in call:
                entry = entries[0]
                if resp.error_message:
                    msg = resp.error_message
                    whole_entry = (
                        len(entry["members"]) == 1
                        or msg.startswith("stream group failed: ")
                    )
                    if whole_entry:
                        entries.popleft()
                        if entry["slot"] is not None:
                            self._pool.release(entry["slot"])
                        if (
                            entry["slot"] is not None
                            and "not registered" in msg
                        ):
                            # server lost its registry mid-stream (see
                            # _recover_shm): re-register the pool and
                            # re-issue this entry's members unary so
                            # the stream keeps its one-response-per-
                            # request contract
                            log.warning(
                                "stream entry hit an empty server shm "
                                "registry (%s); re-registering and "
                                "re-issuing %d member(s)",
                                msg, len(entry["members"]),
                            )
                            self._shm_pool().reregister_all()
                            for m in entry["members"]:
                                yield self.do_inference(m)
                            continue
                    raise RuntimeError(msg)
                entry["remaining"] -= 1
                if entry["remaining"] <= 0:
                    entries.popleft()
                    if entry["slot"] is not None:
                        self._pool.release(entry["slot"])
                inner = resp.infer_response
                yield InferResponse(
                    model_name=inner.model_name,
                    model_version=inner.model_version,
                    outputs=codec.parse_infer_response(inner),
                    request_id=inner.id,
                    parameters=_response_params(inner),
                )
        finally:
            call.cancel()
            while entries:
                entry = entries.popleft()
                if entry["slot"] is not None:
                    self._pool.release(entry["slot"])

    def close(self) -> None:
        # client owns the shm segments: the pool unregisters server-
        # side (best effort — the server may already be gone) and
        # unlinks every slot's regions
        pool = self._pool
        if pool is not None:
            pool.close()
        if self._channel is not None:
            self._channel.close()
        for ch in self._retired:
            ch.close()
        self._retired.clear()

    def __del__(self):
        # best-effort: a dropped channel (the CLIs let main()'s locals
        # go out of scope) must still unregister + unlink its shm
        # segments — /dev/shm files outlive the process otherwise
        try:
            self.close()
        except Exception:
            pass

    # -- internals ------------------------------------------------------------

    def _record_infer_error(self, e) -> None:
        """Count server sheds distinctly: a RESOURCE_EXHAUSTED on
        ModelInfer is the admission controller rejecting on purpose —
        load the client should drop or defer, not a fault to retry."""
        try:
            if e.code() == grpc.StatusCode.RESOURCE_EXHAUSTED:
                self._infer_rejections += 1
        except (AttributeError, ValueError):
            pass

    def stats(self) -> dict:
        """Client-side counters: ``infer_rejections`` (ModelInfer
        requests the server shed with RESOURCE_EXHAUSTED — never
        retried), ``retries`` (transient failures the backoff ladder
        re-issued), the negotiated ``transport`` label, and the shm
        ``pool``'s occupancy/alias counters once it exists."""
        out = {
            "infer_rejections": self._infer_rejections,
            "retries": self._retries_total,
            "transport": self.transport,
        }
        if self._pool is not None:
            out["shm_pool"] = self._pool.stats()
        return out

    def _call(
        self,
        method,
        request,
        retryable=_RETRYABLE,
        deadline_s: float | None = None,
        timeout_s: float | None = None,
    ):
        """Retry ladder with capped exponential backoff and full
        jitter. ``retryable`` is the set of status codes safe to
        re-issue for THIS method: idempotent queries (metadata,
        liveness, index) retry on the full set, while ModelInfer must
        pass only connection-level codes (UNAVAILABLE) — a
        DEADLINE_EXCEEDED/RESOURCE_EXHAUSTED request may have executed
        server-side, and re-running it is unsafe for non-idempotent
        models and doubles load exactly when the server is saturated.
        The jitter (uniform over (delay/2, delay]) decorrelates a fleet
        of clients retrying against one recovering server, so the
        retries do not arrive as synchronized 2^n waves.

        ``deadline_s`` is the request's ABSOLUTE perf_counter deadline
        (InferRequest.deadline_s). It caps every attempt's wire timeout
        to the remaining budget AND caps the cumulative backoff sleep:
        if the budget is spent, or the next sleep would spend it, the
        ladder fails fast with a client-local DeadlineExceededRpcError
        instead of sleeping past a deadline nobody is waiting on.
        ``timeout_s`` overrides the channel's per-attempt timeout for
        THIS call (the router's health probes want a short one without
        re-dialing a second channel)."""
        delay = self._backoff_s
        per_attempt = self._timeout_s if timeout_s is None else timeout_s
        for attempt in range(self._retries + 1):
            timeout = per_attempt
            if deadline_s is not None:
                remaining = deadline_s - time.perf_counter()
                if remaining <= 0:
                    raise DeadlineExceededRpcError(
                        "deadline expired before attempt %d of rpc %s"
                        % (attempt + 1, getattr(method, "_method", method))
                    )
                timeout = min(per_attempt, remaining)
            try:
                return method(request, timeout=timeout)
            except grpc.RpcError as e:
                code = e.code() if hasattr(e, "code") else None
                if attempt >= self._retries or code not in retryable:
                    raise
                sleep_s = delay * random.uniform(0.5, 1.0)
                if (
                    deadline_s is not None
                    and time.perf_counter() + sleep_s >= deadline_s
                ):
                    # the backoff sleep would outlive the caller's
                    # deadline: every further attempt is wasted work
                    # delivered to nobody — fail fast instead
                    raise DeadlineExceededRpcError(
                        "remaining deadline %.3fs < backoff %.3fs after "
                        "%s (attempt %d/%d)"
                        % (
                            deadline_s - time.perf_counter(),
                            sleep_s,
                            code,
                            attempt + 1,
                            self._retries,
                        )
                    ) from e
                log.warning(
                    "rpc %s failed (%s); retry %d/%d in %.2fs",
                    getattr(method, "_method", method),
                    code,
                    attempt + 1,
                    self._retries,
                    sleep_s,
                )
                self._retries_total += 1
                time.sleep(sleep_s)
                delay = min(delay * 2, _BACKOFF_CAP_S)
