"""GRPCChannel: KServe v2 client channel (remote-inference path).

The drop-in analogue of the reference's GRPCChannel
(communicator/channel/grpc_channel.py:8-84) so a driver can point at a
remote server — this framework's InferenceServer on a TPU host, or a
stock Triton — through the same BaseChannel seam the in-process
TPUChannel implements. Departures from the reference:

  * the message-size cap starts at a 64 MiB floor and grows on demand:
    get_metadata() sizes the served contract and re-dials with a larger
    cap when the model needs one — not ``batch_size * 8568044``
    hardcoded (grpc_channel.py:26-29, README.md:118 "make dynamic");
  * requests are built per call from typed arrays (zero-copy codec) —
    no shared mutable ModelInferRequest (grpc_channel.py:63-71), so the
    channel is thread-safe and drivers can pipeline;
  * transient RPC failures retry with exponential backoff instead of
    crashing the callback (the reference has no retry story, SURVEY.md
    §5 "failure detection: none").
"""

from __future__ import annotations

import itertools
import logging
import os
import random
import time

import grpc

from triton_client_tpu.channel.base import (
    BaseChannel,
    InferFuture,
    InferRequest,
    InferResponse,
)
from triton_client_tpu.channel.kserve import codec, pb, service
from triton_client_tpu.config import FRAMING_BYTES, ModelSpec, TensorSpec
from triton_client_tpu.obs.trace import SUMMARY_PARAM_KEY, TraceContext

log = logging.getLogger(__name__)


def _wire_params(request: InferRequest) -> dict | None:
    """Request-level kserve parameters for one outbound ModelInfer:
    the W3C-style trace context (when the request's trace carries one)
    and the scheduling priority. None on the common untraced path so
    the codec skips the parameters map entirely."""
    params = None
    tr = request.trace
    ctx = getattr(tr, "context", None) if tr is not None else None
    if ctx is not None:
        params = {TraceContext.PARAM_KEY: ctx.encode()}
    if request.priority:
        if params is None:
            params = {}
        params["priority"] = int(request.priority)
    return params


def _response_params(resp) -> dict | None:
    """Response-level parameters decoded off the wire — today just the
    server's compact span summary, which the router (or any tracing
    client) grafts onto its own timeline."""
    raw = codec.get_string_param(resp, SUMMARY_PARAM_KEY)
    if raw is None:
        return None
    return {SUMMARY_PARAM_KEY: raw}

_RETRYABLE = (
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.DEADLINE_EXCEEDED,
    grpc.StatusCode.RESOURCE_EXHAUSTED,
)
# ModelInfer may have executed server-side when the deadline fires, so
# only connection-level failures are safe to re-issue automatically.
# RESOURCE_EXHAUSTED is additionally a DELIBERATE server decision (the
# admission controller shed the request); re-issuing it would feed the
# exact overload the server is shedding — clients must back off or
# drop, so it is surfaced immediately and counted (stats()).
_INFER_RETRYABLE = (grpc.StatusCode.UNAVAILABLE,)

# retry backoff ceiling: with jitter, retries from a client fleet decor-
# relate instead of arriving in synchronized waves at each 2^n step
_BACKOFF_CAP_S = 5.0


class DeadlineExceededRpcError(grpc.RpcError):
    """Client-local deadline failure, raised WITHOUT touching the wire.

    The retry ladder synthesizes this when the request's remaining
    deadline budget is gone — either already expired, or so short the
    next backoff sleep would expire it. It subclasses grpc.RpcError and
    answers code()/details() so every caller's status-code dispatch
    (the router, _record_infer_error, tests) handles it exactly like a
    server-sent DEADLINE_EXCEEDED."""

    def __init__(self, details: str) -> None:
        super().__init__(details)
        self._details = details

    def code(self) -> grpc.StatusCode:
        return grpc.StatusCode.DEADLINE_EXCEEDED

    def details(self) -> str:
        return self._details

# shared-memory region-name tag: process-wide monotonic so no two
# channel instances (live or dead) ever share a name prefix
_SHM_CHANNEL_SEQ = itertools.count()


class GRPCChannel(BaseChannel):
    def __init__(
        self,
        endpoint: str,
        max_message_bytes: int = 64 << 20,
        timeout_s: float = 30.0,
        retries: int = 3,
        backoff_s: float = 0.1,
        use_shared_memory: bool = False,
    ) -> None:
        """``use_shared_memory``: same-host transport — inputs are
        written into client-owned POSIX shm segments and requests carry
        only region coordinates (Triton system-shared-memory
        extension), skipping the protobuf serialize/copy/deserialize of
        the tensor payload in both processes. Regions are created and
        registered lazily per input name and sized to the largest array
        seen. The shm path serializes do_inference calls on this
        channel (a region must stay untouched until its response
        arrives); use one channel per concurrent client. Only the
        synchronous do_inference path uses shm — do_inference_async and
        infer_stream fall back to the wire (a region may not be reused
        while a request is in flight, which is exactly what pipelined
        calls do; a warning is logged once)."""
        self._endpoint = endpoint
        self._max_message_bytes = max_message_bytes
        self._timeout_s = timeout_s
        self._retries = retries
        self._backoff_s = backoff_s
        self._channel: grpc.Channel | None = None
        self._stub: service.GRPCInferenceServiceStub | None = None
        self._retired: list[grpc.Channel] = []
        self._use_shm = use_shared_memory
        self._shm_regions: dict = {}  # input name -> SharedMemoryRegion
        self._shm_gen: dict = {}      # input name -> segment generation
        # region names were keyed on id(self), which CPython reuses
        # after GC: a dead channel whose close() failed to unregister
        # server-side left a stale registry entry that a NEW channel
        # reusing the id would collide with forever. A process-wide
        # monotonic tag can never recur within the process.
        self._shm_tag = next(_SHM_CHANNEL_SEQ)
        self._shm_lock = None
        self._shm_async_warned = False
        # client-side overload ledger: sheds the server sent back
        # (RESOURCE_EXHAUSTED on ModelInfer — never retried) vs
        # transient retries the ladder absorbed
        self._infer_rejections = 0
        self._retries_total = 0
        if use_shared_memory:
            import threading

            self._shm_lock = threading.Lock()
        self.register_channel()

    # -- BaseChannel protocol -------------------------------------------------

    def register_channel(self) -> None:
        self._channel = grpc.insecure_channel(
            self._endpoint,
            options=[
                ("grpc.max_send_message_length", self._max_message_bytes),
                ("grpc.max_receive_message_length", self._max_message_bytes),
            ],
        )
        self._stub = service.GRPCInferenceServiceStub(self._channel)

    def fetch_channel(self) -> grpc.Channel:
        return self._channel

    def get_metadata(self, model_name: str, model_version: str = "") -> ModelSpec:
        meta = self._call(
            self._stub.ModelMetadata,
            pb.ModelMetadataRequest(name=model_name, version=model_version),
        )
        config = self._call(
            self._stub.ModelConfig,
            pb.ModelConfigRequest(name=model_name, version=model_version),
        ).config
        import json

        spec = ModelSpec(
            name=meta.name,
            version=model_version or (meta.versions[-1] if meta.versions else "1"),
            platform=meta.platform,
            inputs=tuple(
                TensorSpec(t.name, tuple(t.shape), t.datatype) for t in meta.inputs
            ),
            outputs=tuple(
                TensorSpec(t.name, tuple(t.shape), t.datatype) for t in meta.outputs
            ),
            max_batch_size=config.max_batch_size,
            extra={k: json.loads(v) for k, v in config.parameters.items()},
        )
        needed = 2 * spec.wire_bytes() + FRAMING_BYTES
        if needed > self._max_message_bytes:
            # Re-dial with the larger cap. The old channel is retired,
            # not closed: closing would cancel RPCs other threads have
            # in flight on it; it is drained and closed in close().
            self._max_message_bytes = needed
            if self._channel is not None:
                self._retired.append(self._channel)
            self.register_channel()
        return spec

    def do_inference(self, request: InferRequest) -> InferResponse:
        if self._use_shm:
            return self._do_inference_shm(request)
        wire = codec.build_infer_request(
            model_name=request.model_name,
            inputs=request.inputs,
            model_version=request.model_version,
            request_id=request.request_id,
            parameters=_wire_params(request),
        )
        t0 = time.perf_counter()
        try:
            resp = self._call(
                self._stub.ModelInfer,
                wire,
                retryable=_INFER_RETRYABLE,
                deadline_s=request.deadline_s,
            )
        except grpc.RpcError as e:
            self._record_infer_error(e)
            raise
        return InferResponse(
            model_name=resp.model_name,
            model_version=resp.model_version,
            outputs=codec.parse_infer_response(resp),
            request_id=resp.id,
            latency_s=time.perf_counter() - t0,
            parameters=_response_params(resp),
        )

    # -- shared-memory transport ----------------------------------------------

    def _warn_shm_wire_fallback(self) -> None:
        if self._use_shm and not self._shm_async_warned:
            self._shm_async_warned = True
            log.warning(
                "use_shared_memory only covers synchronous do_inference; "
                "async/streamed requests travel over the wire (pipelined "
                "calls would reuse a region while it is still in flight)"
            )

    def _shm_region_for(self, name: str, nbytes: int):
        """Client-owned region for one input, grown when outsized.
        Region/segment names are unique per channel instance so many
        clients can share a server. Growth generation-tags the segment
        name (the registry rejects duplicate names) and replaces the
        old registration only AFTER the new one succeeds, so a failed
        register RPC leaks nothing and leaves the old region usable."""
        from triton_client_tpu.runtime.shared_memory import SharedMemoryRegion

        region = self._shm_regions.get(name)
        if region is not None and region.size >= nbytes:
            return region
        # every attempt burns a generation so a failed register (which
        # may have executed server-side) never reuses its segment name
        gen = self._shm_gen.get(name, 0)
        self._shm_gen[name] = gen + 1
        rname = f"tct_{os.getpid()}_{self._shm_tag}_{name}_{gen}"
        new = SharedMemoryRegion.create(f"/{rname}", max(nbytes, 1))
        try:
            # no retry: register is not idempotent (duplicate names are
            # rejected), and it is a fast metadata RPC — a transient
            # failure surfaces to the caller, who may simply call again
            self._call(
                self._stub.SystemSharedMemoryRegister,
                pb.SystemSharedMemoryRegisterRequest(
                    name=rname, key=new.key, offset=0, byte_size=new.size
                ),
                retryable=(),
            )
        except Exception:
            new.close()  # unlinks; the server maps the file by its own
            # fd if it did register, so unlinking is safe either way
            raise
        if region is not None:
            old_name = region.key.lstrip("/")
            try:
                self._call(
                    self._stub.SystemSharedMemoryUnregister,
                    pb.SystemSharedMemoryUnregisterRequest(name=old_name),
                    retryable=(),
                )
            except grpc.RpcError:
                log.warning(
                    "could not unregister outgrown region %s", old_name
                )
            region.close()
        self._shm_regions[name] = new
        return new

    def _do_inference_shm(self, request: InferRequest) -> InferResponse:
        import numpy as np

        with self._shm_lock:
            shm_inputs = {}
            arrays = {}
            for name, value in request.inputs.items():
                arr = np.ascontiguousarray(np.asarray(value))
                arrays[name] = arr
                region = self._shm_region_for(name, arr.nbytes)
                region.write(arr)
                rname = region.key.lstrip("/")
                shm_inputs[name] = (rname, 0, arr.nbytes)
            wire = codec.build_infer_request_shm(
                model_name=request.model_name,
                inputs=arrays,
                shm_inputs=shm_inputs,
                model_version=request.model_version,
                request_id=request.request_id,
                parameters=_wire_params(request),
            )
            t0 = time.perf_counter()
            try:
                # UNAVAILABLE-only retry, same contract as the wire path
                resp = self._call(
                    self._stub.ModelInfer, wire, retryable=_INFER_RETRYABLE
                )
            except grpc.RpcError as e:
                # a restarted server has an empty registry: its
                # INVALID_ARGUMENT 'not registered' is recoverable by
                # re-registering our cached segments and re-issuing
                # once — the wire path recovers from restarts via the
                # UNAVAILABLE ladder, the shm path must not be worse
                if not (
                    e.code() == grpc.StatusCode.INVALID_ARGUMENT
                    and "not registered" in (e.details() or "")
                ):
                    raise
                log.warning(
                    "server lost shared-memory registrations (%s); "
                    "re-registering %d region(s)",
                    e.details(), len(self._shm_regions),
                )
                for region in self._shm_regions.values():
                    rname = region.key.lstrip("/")
                    try:
                        # unregister first: if only SOME regions were
                        # lost, a blind re-register would hit the
                        # duplicate-name rejection (unknown-name
                        # unregister is a no-op). It is ONLY that
                        # guard — a transient failure here must not
                        # abort the recovery mid-loop and mask the
                        # original 'not registered' with an unrelated
                        # error while _shm_regions sits half-recovered
                        self._stub.SystemSharedMemoryUnregister(
                            pb.SystemSharedMemoryUnregisterRequest(
                                name=rname
                            ),
                            timeout=self._timeout_s,
                        )
                    except grpc.RpcError as ue:
                        log.warning(
                            "duplicate-name guard unregister of %s "
                            "failed (%s); attempting register anyway",
                            rname, ue,
                        )
                    # a failed register surfaces here with the
                    # recovery context still in the log above
                    self._call(
                        self._stub.SystemSharedMemoryRegister,
                        pb.SystemSharedMemoryRegisterRequest(
                            name=rname,
                            key=region.key,
                            offset=0,
                            byte_size=region.size,
                        ),
                        retryable=(),
                    )
                resp = self._call(
                    self._stub.ModelInfer, wire, retryable=_INFER_RETRYABLE
                )
            return InferResponse(
                model_name=resp.model_name,
                model_version=resp.model_version,
                outputs=codec.parse_infer_response(resp),
                request_id=resp.id,
                latency_s=time.perf_counter() - t0,
                parameters=_response_params(resp),
            )

    def do_inference_async(self, request: InferRequest) -> InferFuture:
        """Non-blocking ModelInfer via a gRPC call future (the --async
        path): the RPC is on the wire when this returns; result() parses
        the response. A connection-level failure (UNAVAILABLE — the only
        code safe to re-issue, see _call) falls back to the sync retry
        ladder at resolution time; all other errors surface at result().

        The returned future is cancellable and subscribable (see
        InferFuture): cancel() abandons the wire call, and
        add_done_callback fires on the gRPC completion thread — the
        router's hedging relies on both to take the first winner and
        release the loser's replica slot. The resolution-time retry
        fallback honors request.deadline_s, so a failover retry never
        sleeps past the caller's budget."""
        self._warn_shm_wire_fallback()
        try:
            wire = codec.build_infer_request(
                model_name=request.model_name,
                inputs=request.inputs,
                model_version=request.model_version,
                request_id=request.request_id,
                parameters=_wire_params(request),
            )
            t0 = time.perf_counter()
            timeout = self._timeout_s
            if request.deadline_s is not None:
                remaining = request.deadline_s - t0
                if remaining <= 0:
                    raise DeadlineExceededRpcError(
                        "deadline expired before async ModelInfer was issued"
                    )
                timeout = min(timeout, remaining)
            call = self._stub.ModelInfer.future(wire, timeout=timeout)
        except Exception as e:  # async contract: errors surface at result()
            return InferFuture.failed(e)

        def resolve() -> InferResponse:
            try:
                resp = call.result()
            except grpc.RpcError as e:
                self._record_infer_error(e)
                code = e.code() if hasattr(e, "code") else None
                # Only connection-level failures (UNAVAILABLE) are
                # re-issued automatically — the code least likely to mean
                # the request executed server-side (no such gRPC code
                # guarantees it). DEADLINE_EXCEEDED/RESOURCE_EXHAUSTED
                # requests frequently HAVE executed, so re-running those
                # is unsafe for non-idempotent models and doubles load
                # exactly when the server is saturated. CANCELLED means
                # our own cancel() won the race — never re-issue it.
                if code not in _INFER_RETRYABLE:
                    raise
                log.warning(
                    "async ModelInfer failed (%s); re-issuing on the "
                    "sync retry path", code,
                )
                resp = self._call(
                    self._stub.ModelInfer,
                    wire,
                    retryable=_INFER_RETRYABLE,
                    deadline_s=request.deadline_s,
                )
            return InferResponse(
                model_name=resp.model_name,
                model_version=resp.model_version,
                outputs=codec.parse_infer_response(resp),
                request_id=resp.id,
                latency_s=time.perf_counter() - t0,
                parameters=_response_params(resp),
            )

        return InferFuture(
            resolve,
            cancel=call.cancel,
            subscribe=lambda fn: call.add_done_callback(lambda _c: fn()),
        )

    # -- extras ---------------------------------------------------------------

    def server_live(self, timeout_s: float | None = None) -> bool:
        try:
            return self._call(
                self._stub.ServerLive, pb.ServerLiveRequest(),
                timeout_s=timeout_s,
            ).live
        except grpc.RpcError:
            return False

    def server_ready(self, timeout_s: float | None = None) -> bool:
        """Readiness (vs liveness): a DRAINING server stays live but
        flips not-ready first, so orchestrators pull it from rotation
        before its in-flight work finishes. ``timeout_s`` overrides the
        channel deadline for this probe — the router's health loop
        probes every replica each interval and must not hang an
        interval's budget on one dead endpoint."""
        try:
            return self._call(
                self._stub.ServerReady, pb.ServerReadyRequest(),
                timeout_s=timeout_s,
            ).ready
        except grpc.RpcError:
            return False

    def model_ready(
        self,
        model_name: str,
        model_version: str = "",
        timeout_s: float | None = None,
    ) -> bool:
        """Per-model readiness (KServe ModelReady): the router probes
        this for its configured model set so a replica that is live but
        has not yet loaded/warmed the model stays out of rotation."""
        try:
            return self._call(
                self._stub.ModelReady,
                pb.ModelReadyRequest(name=model_name, version=model_version),
                retryable=(),
                timeout_s=timeout_s,
            ).ready
        except grpc.RpcError:
            return False

    def repository_index(self) -> list[tuple[str, str, str]]:
        """[(name, version, state)] from the server's RepositoryIndex
        (the 'what is actually being served' query the reference could
        only get from Triton's logs)."""
        resp = self._call(
            self._stub.RepositoryIndex, pb.RepositoryIndexRequest()
        )
        return [(m.name, m.version, m.state) for m in resp.models]

    def infer_stream(self, requests, stream_timeout_s: float | None = 3600.0):
        """Bidirectional streaming inference (the reference's unused
        --streaming flag, main.py:66-70, made real). ``requests`` is an
        iterable of InferRequest; yields InferResponse.

        ``stream_timeout_s`` bounds the WHOLE stream (gRPC deadlines are
        per-call): a stalled server or a silent network partition
        surfaces as DEADLINE_EXCEEDED instead of hanging the client
        forever — the unary path gets the same protection from
        ``timeout_s`` per request. Pass None for an unbounded session
        (long-lived live streams)."""
        self._warn_shm_wire_fallback()

        def wire_iter():
            for r in requests:
                yield codec.build_infer_request(
                    model_name=r.model_name,
                    inputs=r.inputs,
                    model_version=r.model_version,
                    request_id=r.request_id,
                    parameters=_wire_params(r),
                )

        for resp in self._stub.ModelStreamInfer(
            wire_iter(), timeout=stream_timeout_s
        ):
            if resp.error_message:
                raise RuntimeError(resp.error_message)
            inner = resp.infer_response
            yield InferResponse(
                model_name=inner.model_name,
                model_version=inner.model_version,
                outputs=codec.parse_infer_response(inner),
                request_id=inner.id,
                parameters=_response_params(inner),
            )

    def close(self) -> None:
        # client owns the shm segments: unregister server-side (best
        # effort — the server may already be gone), then unlink. Taken
        # under the shm lock so an in-flight do_inference finishes
        # before its regions are torn down.
        import contextlib

        with self._shm_lock or contextlib.nullcontext():
            for name, region in self._shm_regions.items():
                try:
                    # no retry ladder: cleanup against a dead server
                    # must not stall shutdown for the backoff budget
                    self._stub.SystemSharedMemoryUnregister(
                        pb.SystemSharedMemoryUnregisterRequest(
                            name=region.key.lstrip("/")
                        ),
                        timeout=min(self._timeout_s, 2.0),
                    )
                except grpc.RpcError:
                    pass
                region.close()
            self._shm_regions.clear()
        if self._channel is not None:
            self._channel.close()
        for ch in self._retired:
            ch.close()
        self._retired.clear()

    def __del__(self):
        # best-effort: a dropped channel (the CLIs let main()'s locals
        # go out of scope) must still unregister + unlink its shm
        # segments — /dev/shm files outlive the process otherwise
        try:
            self.close()
        except Exception:
            pass

    # -- internals ------------------------------------------------------------

    def _record_infer_error(self, e) -> None:
        """Count server sheds distinctly: a RESOURCE_EXHAUSTED on
        ModelInfer is the admission controller rejecting on purpose —
        load the client should drop or defer, not a fault to retry."""
        try:
            if e.code() == grpc.StatusCode.RESOURCE_EXHAUSTED:
                self._infer_rejections += 1
        except (AttributeError, ValueError):
            pass

    def stats(self) -> dict:
        """Client-side counters: ``infer_rejections`` (ModelInfer
        requests the server shed with RESOURCE_EXHAUSTED — never
        retried) and ``retries`` (transient failures the backoff ladder
        re-issued)."""
        return {
            "infer_rejections": self._infer_rejections,
            "retries": self._retries_total,
        }

    def _call(
        self,
        method,
        request,
        retryable=_RETRYABLE,
        deadline_s: float | None = None,
        timeout_s: float | None = None,
    ):
        """Retry ladder with capped exponential backoff and full
        jitter. ``retryable`` is the set of status codes safe to
        re-issue for THIS method: idempotent queries (metadata,
        liveness, index) retry on the full set, while ModelInfer must
        pass only connection-level codes (UNAVAILABLE) — a
        DEADLINE_EXCEEDED/RESOURCE_EXHAUSTED request may have executed
        server-side, and re-running it is unsafe for non-idempotent
        models and doubles load exactly when the server is saturated.
        The jitter (uniform over (delay/2, delay]) decorrelates a fleet
        of clients retrying against one recovering server, so the
        retries do not arrive as synchronized 2^n waves.

        ``deadline_s`` is the request's ABSOLUTE perf_counter deadline
        (InferRequest.deadline_s). It caps every attempt's wire timeout
        to the remaining budget AND caps the cumulative backoff sleep:
        if the budget is spent, or the next sleep would spend it, the
        ladder fails fast with a client-local DeadlineExceededRpcError
        instead of sleeping past a deadline nobody is waiting on.
        ``timeout_s`` overrides the channel's per-attempt timeout for
        THIS call (the router's health probes want a short one without
        re-dialing a second channel)."""
        delay = self._backoff_s
        per_attempt = self._timeout_s if timeout_s is None else timeout_s
        for attempt in range(self._retries + 1):
            timeout = per_attempt
            if deadline_s is not None:
                remaining = deadline_s - time.perf_counter()
                if remaining <= 0:
                    raise DeadlineExceededRpcError(
                        "deadline expired before attempt %d of rpc %s"
                        % (attempt + 1, getattr(method, "_method", method))
                    )
                timeout = min(per_attempt, remaining)
            try:
                return method(request, timeout=timeout)
            except grpc.RpcError as e:
                code = e.code() if hasattr(e, "code") else None
                if attempt >= self._retries or code not in retryable:
                    raise
                sleep_s = delay * random.uniform(0.5, 1.0)
                if (
                    deadline_s is not None
                    and time.perf_counter() + sleep_s >= deadline_s
                ):
                    # the backoff sleep would outlive the caller's
                    # deadline: every further attempt is wasted work
                    # delivered to nobody — fail fast instead
                    raise DeadlineExceededRpcError(
                        "remaining deadline %.3fs < backoff %.3fs after "
                        "%s (attempt %d/%d)"
                        % (
                            deadline_s - time.perf_counter(),
                            sleep_s,
                            code,
                            attempt + 1,
                            self._retries,
                        )
                    ) from e
                log.warning(
                    "rpc %s failed (%s); retry %d/%d in %.2fs",
                    getattr(method, "_method", method),
                    code,
                    attempt + 1,
                    self._retries,
                    sleep_s,
                )
                self._retries_total += 1
                time.sleep(sleep_s)
                delay = min(delay * 2, _BACKOFF_CAP_S)
