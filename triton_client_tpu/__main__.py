"""``python -m triton_client_tpu <command>`` dispatch.

Commands map 1:1 onto the reference's entry scripts:
  detect2d   — main.py / bag2d.py (live vs replay chosen by --input)
  detect3d   — main3d.py / bag3d.py
  evaluate   — evaluate.py
  serve      — tritonserver --model-repository equivalent (KServe v2)
  train      — sharded fine-tuning on the mesh (export -> serve)
  deploy     — deploy.sh parity (convert checkpoint -> push repo entry)
  fetch-model — download_model_s3_keycloak.py parity (OIDC + S3)
  pc-extract — tools/pc_extractor.py (bag -> .npy point clouds)
  bag-stitch — tools/bag_stitch.py (truncate a bag)
  repo-index — list a model repository (local dir or grpc:<addr>)
  bag-info   — rosbag info equivalent
  trace-dump — Chrome-trace JSON of recent requests from a serving
               process's telemetry port (serve --metrics-port);
               --ops ranks XLA ops by device time instead
  trace-join — merge client/router/replica trace dumps onto one
               timeline (per-source pid rows + clock offsets)
  roofline   — per-model compute-/bandwidth-bound classification with
               the attainable-fps ceiling (live /snapshot or bench
               JSON; measured flops/bytes from XLA's cost model)
  lint       — tpulint AST hazard analysis (recompilation / donation /
               host-sync / lock / telemetry / concurrency / zero-copy /
               Pallas-kernel rules; docs/LINTING.md)
  route      — probe a replica set (health/readiness/labels per
               endpoint — the FrontDoorRouter's rotation view)
"""

from __future__ import annotations

import sys

COMMANDS = (
    "detect2d",
    "detect3d",
    "evaluate",
    "serve",
    "train",
    "deploy",
    "fetch-model",
    "pc-extract",
    "bag-stitch",
    "bag-info",
    "repo-index",
    "trace-dump",
    "trace-join",
    "roofline",
    "lint",
    "route",
)


def main() -> None:
    if len(sys.argv) < 2 or sys.argv[1] in ("-h", "--help"):
        print(__doc__)
        print(f"commands: {', '.join(COMMANDS)}")
        raise SystemExit(0 if len(sys.argv) >= 2 else 2)
    cmd, argv = sys.argv[1], sys.argv[2:]
    if cmd == "detect2d":
        from triton_client_tpu.cli.detect2d import main as run
    elif cmd == "detect3d":
        from triton_client_tpu.cli.detect3d import main as run
    elif cmd == "evaluate":
        from triton_client_tpu.cli.evaluate import main as run
    elif cmd == "serve":
        from triton_client_tpu.cli.serve import main as run
    elif cmd == "train":
        from triton_client_tpu.cli.train import main as run
    elif cmd == "deploy":
        from triton_client_tpu.deploy.push import main as run
    elif cmd == "fetch-model":
        from triton_client_tpu.deploy.fetch import main as run
    elif cmd == "pc-extract":
        from triton_client_tpu.cli.tools import pc_extract as run
    elif cmd == "bag-stitch":
        from triton_client_tpu.cli.tools import bag_stitch as run
    elif cmd == "bag-info":
        from triton_client_tpu.cli.tools import bag_info as run
    elif cmd == "repo-index":
        from triton_client_tpu.cli.tools import repo_index as run
    elif cmd == "trace-dump":
        from triton_client_tpu.cli.tools import trace_dump as run
    elif cmd == "trace-join":
        from triton_client_tpu.cli.tools import trace_join as run
    elif cmd == "roofline":
        from triton_client_tpu.cli.tools import roofline as run
    elif cmd == "lint":
        from triton_client_tpu.cli.tools import lint as run
    elif cmd == "route":
        from triton_client_tpu.cli.tools import route as run
    else:
        print(f"unknown command '{cmd}'; commands: {', '.join(COMMANDS)}")
        raise SystemExit(2)
    run(argv)


if __name__ == "__main__":
    main()
