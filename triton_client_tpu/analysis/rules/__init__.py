"""Rule families. Importing this package registers every rule with the
engine registry (each module calls ``@register`` at import time).

  TPL1xx  recompilation hazards     (rules.recompile)
  TPL2xx  buffer-donation misuse    (rules.donation)
  TPL3xx  host sync on the hot path (rules.hostsync)
  TPL4xx  lock discipline           (rules.locks)
  TPL5xx  telemetry correctness     (rules.telemetry)
  TPL6xx  whole-program concurrency (rules.concurrency)
  TPL7xx  zero-copy / host path     (rules.zerocopy)
  TPL8xx  Pallas kernel analysis    (rules.pallas)

Adding a family: create ``rules/<name>.py``, subclass ``engine.Rule``
with a fresh TPLnxx code, decorate with ``@register``, import it here,
document it in docs/LINTING.md, and add positive/negative fixtures to
``tests/test_tpulint.py``.
"""

from triton_client_tpu.analysis.rules import (  # noqa: F401
    concurrency,
    donation,
    hostsync,
    locks,
    pallas,
    recompile,
    telemetry,
    zerocopy,
)
