"""TPL6xx — whole-program concurrency hazards.

TPL4xx proves per-class lock *discipline*; this family proves the
properties that need the package-wide :mod:`analysis.threads` model:

  TPL601  lock-order cycle: two (or more) locks are nested in opposite
          orders on different call paths — two threads taking the two
          paths concurrently deadlock. Also flags re-acquiring a
          non-reentrant ``threading.Lock`` while it is already held
          (the length-1 cycle: self-deadlock on the calling thread).
  TPL602  cross-thread-root race: an instance attribute of a
          lock-carrying class is mutated from two or more distinct
          thread roots (dispatcher loop, watchdog, executor callbacks,
          signal handlers, the caller's thread...) and at least one of
          those mutation sites holds no lock.
  TPL603  check-then-act atomicity violation: a guarded attribute is
          tested WITHOUT the lock and then mutated UNDER the lock
          inside the same ``if`` — the classic broken double-checked
          init, racing threads both pass the stale check. The fix is
          re-checking under the lock (which suppresses the finding).

All three lean on over-approximations that only ever SUPPRESS race
findings and ADD deadlock edges (see threads.py); genuine
single-writer designs (the watchdog heartbeat) are baselined with a
justification rather than special-cased here.
"""

from __future__ import annotations

import ast
from typing import Iterator

from triton_client_tpu.analysis.engine import (
    Finding,
    Package,
    Rule,
    register,
    walk_held,
)


def _short(qualname: str) -> str:
    """Class.method tail of a dotted qualname (module prefix dropped)."""
    parts = qualname.split(".")
    for i, p in enumerate(parts):
        if p[:1].isupper():
            return ".".join(parts[i:])
    return ".".join(parts[-2:]) if len(parts) > 1 else qualname


@register
class LockOrderRule(Rule):
    code = "TPL601"
    name = "lock-order-cycle"
    doc = (
        "Two locks are acquired in opposite orders on different call "
        "paths (potential deadlock), or a non-reentrant lock is "
        "re-acquired while already held (self-deadlock). Pick one "
        "global nesting order, or drop to a single lock."
    )

    def check(self, package: Package) -> Iterator[Finding]:
        model = package.threads
        for cycle, witnesses in model.lock_cycles():
            order = " -> ".join(cycle + (cycle[0],))
            for site in witnesses:
                held = sorted(
                    h for h in model.held_at(site) if h in cycle
                )
                yield self.finding(
                    site.module,
                    site.node,
                    f"lock-order cycle {order}: `{site.lock}` is "
                    f"acquired here while holding {', '.join(held)} — "
                    "an opposite-order path exists, so two threads can "
                    "deadlock",
                    context=_short(site.function),
                )
        for site in model.reacquisitions:
            yield self.finding(
                site.module,
                site.node,
                f"non-reentrant `{site.lock}` is re-acquired while "
                "already held on this path (self-deadlock); use RLock, "
                "or the `*_locked` caller-holds-it convention",
                context=_short(site.function),
            )


@register
class ThreadEscapeRule(Rule):
    code = "TPL602"
    name = "cross-thread-race"
    doc = (
        "An instance attribute of a lock-carrying class is mutated "
        "from two or more distinct thread roots with at least one "
        "mutation holding no lock — a data race under load. Guard "
        "every mutation with the class lock or confine the attribute "
        "to a single thread."
    )

    def check(self, package: Package) -> Iterator[Finding]:
        model = package.threads
        for (family, attr), sites in sorted(model.mutations.items()):
            if not model.lock_attrs.get(family):
                # a class with no locks at all never promised mutual
                # exclusion; TPL602 polices classes that did
                continue
            groups: set[str] = set()
            for s in sites:
                groups |= model.roots_reaching(s.function)
            if len(groups) < 2:
                continue
            bare = [s for s in sites if not model.held_at(s)]
            if not bare:
                continue
            for s in bare:
                yield self.finding(
                    s.module,
                    s.node,
                    f"`self.{attr}` is mutated lock-free here but is "
                    f"written from {len(groups)} thread roots "
                    f"({', '.join(_short(g) for g in sorted(groups))})"
                    " — guard it or confine it to one thread",
                    context=_short(s.function),
                )


@register
class CheckThenActRule(Rule):
    code = "TPL603"
    name = "check-then-act"
    doc = (
        "A lock-guarded attribute is tested without the lock and then "
        "mutated under the lock in the same `if` — both racing threads "
        "pass the stale check. Re-check the condition after acquiring "
        "the lock (double-checked init) or move the test under it."
    )

    def check(self, package: Package) -> Iterator[Finding]:
        model = package.threads
        # attributes that are mutated under a lock SOMEWHERE: only for
        # those does an unlocked check promise something the lock keeps
        guarded: dict[str, set[str]] = {}
        for (family, attr), sites in model.mutations.items():
            if any(model.held_at(s) for s in sites):
                guarded.setdefault(family, set()).add(attr)
        for qn, info in sorted(package.callgraph.functions.items()):
            cls = model._class_of(qn, info)
            if not cls:
                continue
            family = model.family(cls)
            attrs = guarded.get(family)
            if not attrs:
                continue
            yield from self._check_function(model, info, qn, cls, attrs)

    def _check_function(
        self, model, info, qn: str, cls: str, attrs: set[str]
    ) -> Iterator[Finding]:
        if info.node.name in ("__init__", "__new__", "__post_init__"):
            return

        def lock_of(expr: ast.AST) -> str | None:
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
            ):
                return model.lock_id(cls, expr.attr)
            return None

        entry = model.entry_held.get(qn, frozenset())
        for node, held in walk_held(info.node, lock_of):
            if not isinstance(node, ast.If) or held or entry:
                continue
            tested = _self_attrs_read(node.test) & attrs
            if not tested:
                continue
            for inner in ast.walk(node):
                if not isinstance(inner, ast.With):
                    continue
                if not any(
                    lock_of(item.context_expr) for item in inner.items
                ):
                    continue
                acted = _mutated_attrs(inner) & tested
                rechecked = _rechecked_attrs(inner)
                for attr in sorted(acted - rechecked):
                    yield self.finding(
                        info.module,
                        inner,
                        f"check-then-act on `self.{attr}`: tested "
                        "without the lock, mutated under it — racing "
                        "threads both pass the stale check; re-check "
                        "under the lock",
                        context=_short(qn),
                    )


def _self_attrs_read(expr: ast.AST) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(expr):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            out.add(node.attr)
    return out


def _mutated_attrs(tree: ast.AST) -> set[str]:
    from triton_client_tpu.analysis.threads import _mutations

    out: set[str] = set()
    for node in ast.walk(tree):
        for attr, _site in _mutations(node):
            out.add(attr)
    return out


def _rechecked_attrs(with_node: ast.With) -> set[str]:
    """Attributes re-tested by an `if`/`while` INSIDE the lock body —
    the double-checked pattern that makes check-then-act safe."""
    out: set[str] = set()
    for node in ast.walk(with_node):
        if node is with_node:
            continue
        if isinstance(node, (ast.If, ast.While)):
            out |= _self_attrs_read(node.test)
        elif isinstance(node, ast.Assert):
            out |= _self_attrs_read(node.test)
    return out
