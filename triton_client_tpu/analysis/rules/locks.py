"""TPL4xx — lock discipline over shared instance state.

The serving stack is aggressively multi-threaded (gRPC handler threads,
the batch dispatcher, executor workers, staging threads), and its
convention is lock-per-structure: ``self._lock`` guards ``_pending``,
``self._ready_cv`` guards the dispatch state, ``self._slot_cv`` guards
the channel slots. The bug class this rule catches is an attribute that
is *sometimes* mutated under the class's lock and *sometimes* bare —
the bare site is either a forgotten guard (a data race that loses
counter increments under load) or evidence the attribute doesn't need
the lock at all (in which case the guarded sites are lying to readers).

  TPL401  attribute mutated both under a ``with self.<lock>:`` block
          and outside one, in the same class; every unguarded mutation
          site is flagged. ``__init__``/``__new__`` are exempt (the
          object is not yet shared during construction), and so are
          methods named ``*_locked`` — the codebase convention (e.g.
          ``_form_group_locked``) for "caller already holds the lock".

"Lock" means any attribute the class binds to ``threading.Lock /
RLock / Condition / Semaphore`` in ``__init__``, plus anything named
``*lock*`` / ``*_cv`` used as a context manager.
"""

from __future__ import annotations

import ast
from typing import Iterator

from triton_client_tpu.analysis.engine import (
    Finding,
    Module,
    Package,
    Rule,
    call_name,
    register,
)

_LOCK_FACTORIES = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "threading.Semaphore",
    "threading.BoundedSemaphore",
    "Lock",
    "RLock",
    "Condition",
    "Semaphore",
}
_EXEMPT_METHODS = {"__init__", "__new__", "__post_init__"}


def _lock_attrs(cls: ast.ClassDef) -> set[str]:
    """Attribute names holding a lock/condition in this class."""
    out: set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if call_name(node.value) in _LOCK_FACTORIES:
                for tgt in node.targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        out.add(tgt.attr)
    for node in ast.walk(cls):
        if isinstance(node, ast.With):
            for item in node.items:
                ctx = item.context_expr
                if (
                    isinstance(ctx, ast.Attribute)
                    and isinstance(ctx.value, ast.Name)
                    and ctx.value.id == "self"
                    and ("lock" in ctx.attr.lower() or ctx.attr.endswith("_cv"))
                ):
                    out.add(ctx.attr)
    return out


def _self_attr_of_target(tgt: ast.AST) -> str | None:
    """The self-attribute a store mutates: `self.x = ...` -> x,
    `self.x[k] = / += ...` -> x (subscript stores mutate the container
    the attribute holds)."""
    node = tgt
    while isinstance(node, ast.Subscript):
        node = node.value
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


@register
class LockDisciplineRule(Rule):
    code = "TPL401"
    name = "mixed-lock-discipline"
    doc = (
        "An instance attribute is mutated both inside a `with "
        "self.<lock>:` block and outside one in the same class — the "
        "unguarded site races with every guarded reader/writer."
    )

    def check(self, package: Package) -> Iterator[Finding]:
        for module in package.modules:
            for cls in ast.walk(module.tree):
                if not isinstance(cls, ast.ClassDef):
                    continue
                locks = _lock_attrs(cls)
                if not locks:
                    continue
                yield from self._check_class(module, cls, locks)

    def _check_class(
        self, module: Module, cls: ast.ClassDef, locks: set[str]
    ) -> Iterator[Finding]:
        guarded: set[str] = set()
        # (attr, node, method) mutation sites outside any lock
        bare: list[tuple[str, ast.AST, str]] = []

        def mutations(node: ast.AST) -> Iterator[tuple[str, ast.AST]]:
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    attr = _self_attr_of_target(tgt)
                    if attr:
                        yield attr, node
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                attr = _self_attr_of_target(node.target)
                if attr:
                    yield attr, node
            elif isinstance(node, ast.Call):
                # mutating method calls on a self attribute:
                # self._q.append(x), self._cache.pop(k), ...
                f = node.func
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr
                    in (
                        "append",
                        "appendleft",
                        "extend",
                        "extendleft",
                        "pop",
                        "popleft",
                        "add",
                        "remove",
                        "discard",
                        "clear",
                        "update",
                        "setdefault",
                        "put",
                        "put_nowait",
                    )
                ):
                    attr = _self_attr_of_target(f.value)
                    if attr:
                        yield attr, node

        def is_lock_with(node: ast.With) -> bool:
            for item in node.items:
                ctx = item.context_expr
                if (
                    isinstance(ctx, ast.Attribute)
                    and isinstance(ctx.value, ast.Name)
                    and ctx.value.id == "self"
                    and ctx.attr in locks
                ):
                    return True
            return False

        def walk(node: ast.AST, under_lock: bool, method: str) -> None:
            for child in ast.iter_child_nodes(node):
                child_method = method
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    # direct methods reset lock state; nested defs
                    # (closures) inherit the enclosing method name but
                    # NOT the lock — they usually run later, unlocked
                    child_method = method or child.name
                    if child.name in _EXEMPT_METHODS or child.name.endswith(
                        "_locked"
                    ):
                        continue
                    walk(child, False, child_method)
                    continue
                child_lock = under_lock
                if isinstance(child, ast.With) and is_lock_with(child):
                    child_lock = True
                for attr, site in mutations(child):
                    if attr in locks:
                        continue
                    if child_lock:
                        guarded.add(attr)
                    else:
                        bare.append((attr, site, method))
                walk(child, child_lock, child_method)

        walk(cls, False, "")
        for attr, site, method in bare:
            if attr in guarded:
                yield self.finding(
                    module,
                    site,
                    f"`self.{attr}` is mutated without the lock here but "
                    "under a lock elsewhere in this class (data race)",
                    context=f"{cls.name}.{method}" if method else cls.name,
                )
