"""TPL8xx — Pallas TPU kernel analysis (tiling, VMEM, DMA, fused routes).

PR 16 put ~1.3k LoC of hand-written Pallas kernels on the serving hot
path, and every planned kernel (int8 MXU paths, ROI-gated recompute)
rides the same machinery. The failure modes concentrate exactly where
``interpret=True`` CPU tests cannot see them: interpret mode ignores
tiling, VMEM capacity and DMA scheduling entirely, so a kernel can be
bitwise-correct in CI and wrong (or 100x slow, or a Mosaic
compile error) on real hardware. These rules audit every
``pl.pallas_call`` site statically, via :mod:`..pallas_model`:

  TPL801  tile alignment — a VMEM block/scratch shape whose trailing
          dim is not a multiple of 128 lanes (or whose sublane dim is
          not a multiple of the dtype tile) silently pads to the full
          native tile: a (1024, 1) int32 block occupies the VMEM of
          (1024, 128) — 128x waste — and every op on it wastes the
          same factor of bandwidth.
  TPL802  VMEM budget — the summed resident bytes (blocks, x2 when
          grid-pipelined double buffering, + scratch) exceed the
          per-core VMEM limit (v5e: 16 MiB). Mosaic fails late and
          cryptically; this fails at review time. Override per call
          with ``# tpulint: vmem=<bytes>`` on the call or wrapper-def
          line when a rig's budget genuinely differs.
  TPL803  grid/block divisibility — a gridded pallas_call whose
          wrapper shows no size guard (a ``%``-test raise/assert or a
          round-up helper): any caller can pass a size the grid does
          not divide and silently drop the remainder rows. The message
          names the callers (PR 3 callgraph) that can reach it.
  TPL804  DMA discipline — an async copy family started without a
          matching ``.wait()`` on every path (flow-sensitive: ``pl.when``
          bodies and ``if`` arms are conditional), or a textually
          identical start repeated with no intervening wait (the
          double-buffer slot-reuse bug: the second start races the
          first copy's landing).
  TPL805  fused-route contract — every stage in ``ops/fused.py``'s
          ``FUSED_STAGES`` must have (a) >= 1 pallas_call under
          ``jax.named_scope("fused:<stage>")``, (b) parameter-plumbed
          ``interpret=`` on each such call (the CPU escape hatch),
          (c) a reachable reference routing test (a ``"<stage>" in ...``
          membership check outside the kernel modules), and (d) a
          bitwise parity test naming the stage in
          ``tests/test_fused_parity.py`` — so no future fusion ships
          ungated.

Extraction is conservative: dims that don't fold to compile-time ints
are skipped, never guessed (docs/LINTING.md has the full catalogue).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterator

from triton_client_tpu.analysis.engine import (
    Finding,
    Module,
    Package,
    Rule,
    call_name,
    register,
)
from triton_client_tpu.analysis.pallas_model import (
    BlockModel,
    KernelModel,
    ScratchModel,
    dma_events,
    functions_with_dma,
    itemsize,
    sublane_multiple,
)

_LANES = 128
#: v5e per-core VMEM (the serving target; see /opt tiling guides and
#: ops/pallas_nms.vmem_fits, which budgets 12 MiB of the same 16).
VMEM_LIMIT_BYTES = 16 * 1024 * 1024

#: per-call budget override: ``# tpulint: vmem=<bytes>`` on the
#: pallas_call's line span or on the wrapper's def line.
_VMEM_PRAGMA_RE = re.compile(r"#\s*tpulint:\s*vmem=(\d+)")

_GUARD_HELPERS = (
    "_round_up", "round_up", "kernel_block_rows", "ragged_row_bucket",
)


def _short(name: str) -> str:
    return name.rsplit(".", 1)[-1] if name else ""


def _shape_str(shape) -> str:
    return "(" + ", ".join("?" if d is None else str(d) for d in shape) + ")"


def _vmem_pragma(module: Module, model: KernelModel) -> int | None:
    lines: list[int] = []
    call = model.call
    lines.extend(
        range(call.lineno, getattr(call, "end_lineno", call.lineno) + 1)
    )
    if model.wrapper is not None:
        lines.append(model.wrapper.lineno)
    for ln in lines:
        if 1 <= ln <= len(module.lines):
            m = _VMEM_PRAGMA_RE.search(module.lines[ln - 1])
            if m:
                return int(m.group(1))
    return None


@register
class TileAlignRule(Rule):
    code = "TPL801"
    name = "pallas-tile-misalignment"
    doc = (
        "A VMEM block or scratch shape whose trailing dim is not a "
        "multiple of 128 lanes (or whose sublane dim is not a multiple "
        "of the dtype tile height) pads to the full native TPU tile in "
        "VMEM — a (N, 1) column block occupies 128x its logical bytes "
        "and taxes every access. Lay the data out lane-major (a (1, N) "
        "row) or pad the trailing dim to 128 explicitly."
    )

    def check(self, package: Package) -> Iterator[Finding]:
        for model in package.pallas.models:
            ctx = _short(model.wrapper_name)
            for block in model.in_blocks + model.out_blocks:
                if block.memory_space != "vmem" or block.shape is None:
                    continue
                yield from self._check_shape(
                    model, block.shape, None, block.node,
                    f"{block.role}_spec BlockSpec", ctx,
                )
            for s in model.scratch:
                if s.kind == "semaphore" or s.shape is None:
                    continue
                yield from self._check_shape(
                    model, s.shape, s.dtype, s.node,
                    f"{s.kind} VMEM scratch", ctx,
                )

    def _check_shape(
        self, model: KernelModel, shape, dtype, node, what: str, ctx: str
    ) -> Iterator[Finding]:
        if len(shape) < 2:
            return
        last = shape[-1]
        if last is not None and last % _LANES != 0:
            yield self.finding(
                model.module,
                node,
                f"{what} {_shape_str(shape)} trailing dim {last} is not a "
                f"multiple of {_LANES} lanes: it pads to the full native "
                "tile in VMEM (lay out lane-major or pad to 128)",
                context=ctx,
            )
        subl = sublane_multiple(dtype)
        second = shape[-2]
        if second is not None and second > subl and second % subl != 0:
            yield self.finding(
                model.module,
                node,
                f"{what} {_shape_str(shape)} sublane dim {second} is not a "
                f"multiple of the {subl}-sublane "
                f"{dtype or 'float32'} tile height",
                context=ctx,
            )


@register
class VmemBudgetRule(Rule):
    code = "TPL802"
    name = "pallas-vmem-budget"
    doc = (
        "The statically-known resident VMEM working set of a "
        "pallas_call (block shapes — doubled under a grid pipeline for "
        "the prefetch buffer — plus scratch and whole-array outputs) "
        "exceeds the per-core VMEM limit (v5e: 16 MiB). Mosaic only "
        "fails at compile time on hardware; override a deliberate "
        "budget with `# tpulint: vmem=<bytes>` on the call line."
    )

    def check(self, package: Package) -> Iterator[Finding]:
        for model in package.pallas.models:
            total, parts = self._estimate(model)
            if total <= 0:
                continue
            limit = _vmem_pragma(model.module, model) or VMEM_LIMIT_BYTES
            if total > limit:
                yield self.finding(
                    model.module,
                    model.call,
                    f"estimated resident VMEM {total} bytes "
                    f"({' + '.join(parts)}) exceeds the "
                    f"{limit}-byte per-core budget; shrink blocks, spill "
                    "to HBM/ANY, or annotate `# tpulint: vmem=<bytes>`",
                    context=_short(model.wrapper_name),
                )

    @staticmethod
    def _estimate(model: KernelModel) -> tuple[int, list[str]]:
        total = 0
        parts: list[str] = []
        double = 2 if model.gridded else 1

        def add(shape, dtype, label, buffered) -> None:
            nonlocal total
            if shape is None or any(d is None for d in shape):
                return
            n = itemsize(dtype)
            for d in shape:
                n *= d
            n *= buffered
            total += n
            parts.append(f"{label} {_shape_str(shape)}={n}")

        out_shape_iter = iter(model.out_shapes)
        for block in model.in_blocks:
            if block.memory_space != "vmem":
                continue
            add(block.shape, None, "in", double if block.shape else 1)
        for block in model.out_blocks:
            shape, dtype = block.shape, None
            if shape is None:
                # blockless out spec: the whole output is resident
                shape, dtype = next(out_shape_iter, (None, None))
            if block.memory_space != "vmem":
                continue
            add(shape, dtype, "out", double if block.shape else 1)
        for s in model.scratch:
            if s.kind == "semaphore":
                continue
            add(s.shape, s.dtype, "scratch", 1)
        return total, parts


@register
class GridDivisibilityRule(Rule):
    code = "TPL803"
    name = "pallas-grid-divisibility"
    doc = (
        "A gridded pallas_call whose wrapper shows no input-size guard "
        "(a %-divisibility raise/assert, or a round-up helper like "
        "kernel_block_rows/_round_up): a caller passing a size the "
        "grid does not divide silently drops the remainder rows. The "
        "finding lists the callers that can reach the wrapper."
    )

    def check(self, package: Package) -> Iterator[Finding]:
        for model in package.pallas.models:
            if not model.gridded or model.wrapper is None:
                continue
            if self._has_guard(model.wrapper):
                continue
            callers = self._callers(package, model.wrapper_name)
            via = (
                " (callers that can reach it: " + ", ".join(callers) + ")"
                if callers
                else ""
            )
            yield self.finding(
                model.module,
                model.call,
                f"gridded pallas_call with grid "
                f"{_shape_str(model.grid or ())} but no divisibility "
                "guard in the wrapper: add a `n % block` raise/assert or "
                f"round inputs up via {_GUARD_HELPERS[2]}{via}",
                context=_short(model.wrapper_name),
            )

    @staticmethod
    def _has_guard(wrapper: ast.AST) -> bool:
        for node in ast.walk(wrapper):
            if isinstance(node, ast.Assert) and any(
                isinstance(n, ast.BinOp) and isinstance(n.op, ast.Mod)
                for n in ast.walk(node.test)
            ):
                return True
            if isinstance(node, ast.If):
                test_has_mod = any(
                    isinstance(n, ast.BinOp) and isinstance(n.op, ast.Mod)
                    for n in ast.walk(node.test)
                )
                body_raises = any(
                    isinstance(s, ast.Raise)
                    for stmt in node.body
                    for s in ast.walk(stmt)
                )
                if test_has_mod and body_raises:
                    return True
            if isinstance(node, ast.Call) and _short(
                call_name(node)
            ) in _GUARD_HELPERS:
                return True
        return False

    @staticmethod
    def _callers(package: Package, wrapper_name: str) -> list[str]:
        graph = package.callgraph
        suffix = "." + wrapper_name
        targets = {
            qn for qn in graph.functions if qn.endswith(suffix)
        }
        callers = sorted(
            caller
            for caller, callees in graph.edges.items()
            if callees & targets
        )
        return [c.split(".")[-1] for c in callers[:6]]


@register
class DmaDisciplineRule(Rule):
    code = "TPL804"
    name = "pallas-dma-discipline"
    doc = (
        "An async copy (`make_async_copy`) started without a matching "
        "`.wait()` on every path — a wait under `pl.when`/`if` does not "
        "cover an unconditional start — or a textually identical start "
        "repeated with no intervening wait (double-buffer slot reuse "
        "before the first copy lands). Both are silent under interpret "
        "mode and data races on hardware."
    )

    def check(self, package: Package) -> Iterator[Finding]:
        for module in package.modules:
            for fn in functions_with_dma(module):
                yield from self._check_fn(module, fn)

    def _check_fn(self, module: Module, fn: ast.FunctionDef) -> Iterator[Finding]:
        events = dma_events(fn)
        families: dict[str, list] = {}
        for ev in events:
            families.setdefault(ev.family, []).append(ev)
        for family, evs in sorted(families.items()):
            starts = [e for e in evs if e.kind == "start"]
            waits = [e for e in evs if e.kind == "wait"]
            if starts and not waits:
                yield self.finding(
                    module,
                    starts[0].node,
                    f"async copy family `{family}` is started but never "
                    "waited in this kernel: the DMA may still be in "
                    "flight when its destination is read (or the kernel "
                    "exits)",
                    context=fn.name,
                )
                continue
            if any(not s.conditional for s in starts) and waits and all(
                w.conditional for w in waits
            ):
                yield self.finding(
                    module,
                    waits[0].node,
                    f"async copy family `{family}` has an unconditional "
                    "start but only conditional waits (`pl.when`/`if`): "
                    "a path exists where the copy is never waited",
                    context=fn.name,
                )
            # slot reuse: the same construction started twice with no
            # intervening wait on the family — the second start targets
            # a buffer the first copy may still be filling
            last_start_sig: str | None = None
            for ev in evs:
                if ev.kind == "wait":
                    last_start_sig = None
                elif ev.conditional:
                    continue
                elif ev.signature == last_start_sig:
                    yield self.finding(
                        module,
                        ev.node,
                        f"async copy family `{family}` re-starts the same "
                        "copy with no intervening wait: double-buffer "
                        "slot reuse before the first copy lands",
                        context=fn.name,
                    )
                else:
                    last_start_sig = ev.signature


@register
class FusedContractRule(Rule):
    code = "TPL805"
    name = "fused-route-contract"
    doc = (
        "Every stage in ops/fused.py's FUSED_STAGES must keep its full "
        "contract: >= 1 pallas_call under jax.named_scope('fused:<stage>'), "
        "parameter-plumbed interpret= on each such call (the CPU escape "
        "hatch), a reference routing membership test ('<stage>' in ...) "
        "outside the kernel modules, and a bitwise parity test naming "
        "the stage in tests/test_fused_parity.py. A fusion missing any "
        "leg ships ungated."
    )

    PARITY_TEST = os.path.join("tests", "test_fused_parity.py")

    def check(self, package: Package) -> Iterator[Finding]:
        fused_mod, stages_node, stages = self._stages(package)
        if fused_mod is None or not stages:
            return  # no fused-route control plane in this package: inert
        parity_names = self._parity_stage_names(fused_mod)
        for stage in stages:
            scope = f"fused:{stage}"
            kernels = package.pallas.by_scope(scope)
            if not kernels:
                yield self.finding(
                    fused_mod,
                    stages_node,
                    f"fused stage '{stage}' has no pallas_call under "
                    f"jax.named_scope('{scope}'): the stage resolves but "
                    "launches nothing",
                    context=scope,
                )
            seen_calls = set()
            for model in kernels:
                key = (model.module.relpath, model.call.lineno)
                if key in seen_calls:
                    continue
                seen_calls.add(key)
                if model.interpret != "plumbed":
                    how = (
                        "hard-codes interpret="
                        if model.interpret == "const"
                        else "has no interpret= kwarg"
                    )
                    yield self.finding(
                        model.module,
                        model.call,
                        f"fused stage '{stage}' pallas_call in "
                        f"`{_short(model.wrapper_name)}` {how}: the CPU "
                        "escape hatch must be plumbed from the wrapper so "
                        "parity tests exercise the same kernel",
                        context=scope,
                    )
            if not self._has_routing(package, stage):
                yield self.finding(
                    fused_mod,
                    stages_node,
                    f"fused stage '{stage}' has no reference routing "
                    f"membership test ('{stage}' in ...) outside the "
                    "kernel modules: there is no reachable reference "
                    "path to fall back to or compare against",
                    context=f"fused:{stage}",
                )
            if parity_names is None:
                yield self.finding(
                    fused_mod,
                    stages_node,
                    f"fused stage '{stage}' has no parity coverage: "
                    f"{self.PARITY_TEST} is missing or unparseable",
                    context=f"fused:{stage}",
                )
            elif stage not in parity_names:
                yield self.finding(
                    fused_mod,
                    stages_node,
                    f"fused stage '{stage}' is not named in any test in "
                    f"{self.PARITY_TEST}: the bitwise parity matrix does "
                    "not cover it",
                    context=f"fused:{stage}",
                )

    @staticmethod
    def _stages(
        package: Package,
    ) -> tuple[Module | None, ast.AST | None, tuple[str, ...]]:
        for module in package.modules:
            rel = module.relpath.replace(os.sep, "/")
            if not rel.endswith("ops/fused.py"):
                continue
            for stmt in module.tree.body:
                if (
                    isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == "FUSED_STAGES"
                    and isinstance(stmt.value, (ast.Tuple, ast.List))
                ):
                    stages = tuple(
                        el.value
                        for el in stmt.value.elts
                        if isinstance(el, ast.Constant)
                        and isinstance(el.value, str)
                    )
                    return module, stmt, stages
            return module, module.tree, ()
        return None, None, ()

    @staticmethod
    def _is_kernel_module(module: Module) -> bool:
        rel = module.relpath.replace(os.sep, "/")
        base = os.path.basename(rel)
        return base.startswith("pallas_") or rel.endswith("ops/fused.py")

    def _has_routing(self, package: Package, stage: str) -> bool:
        for module in package.modules:
            if self._is_kernel_module(module):
                continue
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Compare):
                    continue
                if not any(isinstance(op, (ast.In, ast.NotIn)) for op in node.ops):
                    continue
                exprs = [node.left, *node.comparators]
                for e in list(exprs):
                    if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
                        exprs.extend(e.elts)
                if any(
                    isinstance(e, ast.Constant) and e.value == stage
                    for e in exprs
                ):
                    return True
        return False

    def _parity_stage_names(self, fused_mod: Module) -> set[str] | None:
        """Stage-name string constants inside test_* functions of the
        repo's parity test file (located relative to ops/fused.py's real
        path — the tests tree is OUTSIDE the analyzed package)."""
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(fused_mod.path)
        )))
        path = os.path.join(pkg_root, self.PARITY_TEST)
        try:
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=path)
        except (OSError, SyntaxError, ValueError):
            return None
        names: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.FunctionDef) and node.name.startswith(
                "test_"
            ):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Constant) and isinstance(
                        sub.value, str
                    ):
                        names.add(sub.value)
        return names
