"""TPL7xx — hidden host-side copies on the serving hot path.

ROADMAP item 1: the remaining gap between served fps and the device
ceiling is host work, and the biggest silent contributor is memory
traffic — request-sized arrays copied on their way through the stack.
The codec deliberately receives with zero-copy ``frombuffer(...)
.reshape(...)`` views; one careless ``np.array(...)`` or ``.copy()``
downstream doubles the per-request byte traffic and shows up nowhere
but the capacity number. Like TPL3xx, the family walks the call graph
from :data:`rules.hostsync.HOT_PATH_ROOTS` and audits every reachable
function:

  TPL701  hidden copy: ``np.ascontiguousarray`` / ``np.copy`` /
          ``.tobytes()`` / ``.copy()`` on an array value in a hot-path
          function. Some copies are the design (the wire needs owned
          contiguous bytes) — those are baselined with a justification.
  TPL702  unguarded ``astype``: dtype conversion without a
          dtype-identity guard copies even when dtypes already match.
          ``astype(dt, copy=False)`` or an enclosing ``if ... dtype``
          check is the guard.
  TPL703  broken zero-copy view: a ``frombuffer`` chain immediately
          materialized (``np.array(np.frombuffer(...))``,
          ``frombuffer(...).reshape(...).copy()``) — the zero-copy
          receive path pays for an allocation anyway.
  TPL704  per-element serialization: a loop whose body serializes
          (``.tobytes()`` / ``struct.pack``) element by element —
          one vectorized call does the same work without the
          per-iteration Python and allocator overhead.

Method-call heuristics (``.copy()``) only fire on receivers the local
dataflow proves array-like (a numpy call chain or a name assigned from
one) — ``dict.copy()`` on a params map is not a finding.
"""

from __future__ import annotations

import ast
from typing import Iterator

from triton_client_tpu.analysis.engine import (
    Finding,
    Package,
    Rule,
    call_name,
    register,
)
from triton_client_tpu.analysis.rules.hostsync import (
    HOT_PATH_ROOTS,
    _short_context,
)

_COPY_CALLS = {
    "np.ascontiguousarray": "forces an owned contiguous copy",
    "numpy.ascontiguousarray": "forces an owned contiguous copy",
    "np.copy": "explicit array copy",
    "numpy.copy": "explicit array copy",
}
_FROMBUFFER = {"np.frombuffer", "numpy.frombuffer", "frombuffer"}
# chained ndarray methods that keep a value array-like
_ARRAY_CHAIN_METHODS = {
    "reshape",
    "astype",
    "ravel",
    "view",
    "transpose",
    "squeeze",
    "flatten",
    "copy",
}
_SERIALIZE_IN_LOOP = {"tobytes", "pack", "to_bytes"}


def _is_numpyish(expr: ast.AST, array_names: set[str]) -> bool:
    """Local-dataflow guess: does ``expr`` evaluate to an ndarray?"""
    if isinstance(expr, ast.Name):
        return expr.id in array_names
    if isinstance(expr, ast.Call):
        name = call_name(expr)
        if name.startswith(("np.", "numpy.")) or name in _FROMBUFFER:
            return True
        if (
            isinstance(expr.func, ast.Attribute)
            and expr.func.attr in _ARRAY_CHAIN_METHODS
        ):
            return _is_numpyish(expr.func.value, array_names)
        return False
    if isinstance(expr, ast.Attribute):
        # arr.T / arr.real keep arrays array-like
        return _is_numpyish(expr.value, array_names)
    if isinstance(expr, ast.Subscript):
        return _is_numpyish(expr.value, array_names)
    return False


def _array_locals(fn: ast.AST) -> set[str]:
    """Names assigned from numpy-ish expressions anywhere in ``fn`` —
    order-insensitive on purpose (two passes keep chains like
    ``a = np.frombuffer(...); b = a.reshape(...)`` covered)."""
    names: set[str] = set()
    for _ in range(2):
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and _is_numpyish(
                node.value, names
            ):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        names.add(tgt.id)
    return names


def _contains_frombuffer(expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and call_name(node) in _FROMBUFFER:
            return True
    return False


def _dtype_guarded(ancestors: list[ast.AST]) -> bool:
    """True when some enclosing if/ternary tests a dtype — the
    conversion only runs when dtypes genuinely differ."""
    for node in ancestors:
        test = None
        if isinstance(node, (ast.If, ast.IfExp)):
            test = node.test
        elif isinstance(node, ast.While):
            test = node.test
        if test is None:
            continue
        for sub in ast.walk(test):
            if isinstance(sub, ast.Attribute) and sub.attr == "dtype":
                return True
            if isinstance(sub, ast.Name) and "dtype" in sub.id:
                return True
    return False


def _kw(call: ast.Call, name: str) -> ast.AST | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


class _HotScan:
    """One hot function's scan state: findings accumulate with loop
    deduplication (a TPL704 loop swallows the TPL701s inside it)."""

    def __init__(self, fn: ast.AST) -> None:
        self.fn = fn
        self.array_names = _array_locals(fn)
        self.loop_lines: set[int] = set()
        self.hits: list[tuple[ast.AST, str, str]] = []

    def scan(self) -> list[tuple[ast.AST, str, str]]:
        self._walk(self.fn, [], in_flagged_loop=False)
        return self.hits

    def _walk(
        self, node: ast.AST, ancestors: list[ast.AST], in_flagged_loop: bool
    ) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue  # separate call-graph nodes, scanned there
            flagged_here = False
            if isinstance(child, (ast.For, ast.While)):
                if self._loop_serializes(child):
                    self.hits.append(
                        (
                            child,
                            "TPL704",
                            "per-element serialization loop on the hot "
                            "path — vectorize (one `.tobytes()` /"
                            " `struct.pack` over the whole array)",
                        )
                    )
                    flagged_here = True
            elif isinstance(child, ast.Call):
                self._check_call(child, ancestors, in_flagged_loop)
            self._walk(
                child,
                ancestors + [child],
                in_flagged_loop or flagged_here,
            )

    def _loop_serializes(self, loop: ast.AST) -> bool:
        for node in ast.walk(loop):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(node, ast.Call):
                f = node.func
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr in _SERIALIZE_IN_LOOP
                ):
                    return True
                if call_name(node) == "struct.pack":
                    return True
        return False

    def _check_call(
        self, call: ast.Call, ancestors: list[ast.AST], in_flagged_loop: bool
    ) -> None:
        name = call_name(call)
        # TPL703 first: a materialized frombuffer chain is the sharpest
        # diagnosis, and it subsumes the generic copy finding
        if (
            name in ("np.array", "numpy.array")
            and call.args
            and _contains_frombuffer(call.args[0])
        ):
            self.hits.append(
                (
                    call,
                    "TPL703",
                    "`np.array(...)` materializes a `frombuffer` "
                    "zero-copy view — keep the view (the codec's "
                    "receive path is zero-copy by design)",
                )
            )
            return
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr == "copy":
            if _contains_frombuffer(f.value):
                self.hits.append(
                    (
                        call,
                        "TPL703",
                        "`.copy()` on a `frombuffer` chain defeats the "
                        "zero-copy receive view",
                    )
                )
                return
            if _is_numpyish(f.value, self.array_names):
                self.hits.append(
                    (
                        call,
                        "TPL701",
                        "`.copy()` of an array on the hot path "
                        "(request-sized allocation + memcpy)",
                    )
                )
            return
        if name in _COPY_CALLS:
            self.hits.append(
                (
                    call,
                    "TPL701",
                    f"`{name}` on the hot path ({_COPY_CALLS[name]})",
                )
            )
            return
        if isinstance(f, ast.Attribute) and f.attr == "tobytes":
            if in_flagged_loop:
                return  # the TPL704 loop finding already covers it
            self.hits.append(
                (
                    call,
                    "TPL701",
                    "`.tobytes()` on the hot path (full array copy "
                    "into a bytes object)",
                )
            )
            return
        if isinstance(f, ast.Attribute) and f.attr == "astype":
            copy_kw = _kw(call, "copy")
            if (
                isinstance(copy_kw, ast.Constant)
                and copy_kw.value is False
            ):
                return  # astype(dt, copy=False): identity-safe
            if _dtype_guarded(ancestors):
                return
            self.hits.append(
                (
                    call,
                    "TPL702",
                    "`.astype(...)` without a dtype-identity guard "
                    "copies even when dtypes already match — guard "
                    "with `if arr.dtype != dt:` or pass `copy=False`",
                )
            )


@register
class HiddenCopyRule(Rule):
    code = "TPL701"
    name = "hot-path-hidden-copy"
    doc = (
        "A request-sized array is copied on the serving hot path "
        "(`np.ascontiguousarray`, `.copy()`, `.tobytes()`); every such "
        "copy is host memory traffic ROADMAP item 1 is trying to "
        "eliminate. Designed copies carry a baseline justification."
    )

    emit = ("TPL701",)
    roots: tuple[str, ...] = HOT_PATH_ROOTS

    def check(self, package: Package) -> Iterator[Finding]:
        yield from _check_hot(package, self, self.emit, self.roots)


@register
class UnguardedAstypeRule(HiddenCopyRule):
    code = "TPL702"
    name = "unguarded-astype"
    doc = (
        "`.astype(...)` on the hot path without a dtype-identity guard "
        "or `copy=False` — it allocates and copies even when the dtype "
        "already matches."
    )

    emit = ("TPL702",)


@register
class BrokenViewRule(HiddenCopyRule):
    code = "TPL703"
    name = "broken-zero-copy-view"
    doc = (
        "A `frombuffer` zero-copy view is immediately materialized "
        "(`np.array(...)` / `.copy()`), paying the allocation the view "
        "existed to avoid."
    )

    emit = ("TPL703",)


@register
class ElementLoopRule(HiddenCopyRule):
    code = "TPL704"
    name = "per-element-serialization"
    doc = (
        "A hot-path loop serializes element by element (`.tobytes()`, "
        "`struct.pack` per iteration) — vectorize into one call over "
        "the whole array."
    )

    emit = ("TPL704",)


def _check_hot(
    package: Package, rule: Rule, emit: tuple[str, ...], roots
) -> Iterator[Finding]:
    graph = package.callgraph
    hot = graph.reachable(roots)
    for qn in sorted(hot):
        info = graph.functions.get(qn)
        if info is None:
            continue
        for node, code, msg in _HotScan(info.node).scan():
            if code not in emit:
                continue
            yield rule.finding(
                info.module,
                node,
                msg,
                context=_short_context(qn),
                code=code,
            )
