"""TPL1xx — recompilation / retrace hazards inside jitted code.

XLA compiles one executable per (shape, dtype, static-arg) signature;
anything that makes the traced Python non-deterministic per call either
fails at trace time or silently retraces — and on the serving path a
retrace is a multi-second stall (BASELINE.md measured compile bills).
These rules find the three shapes of that bug this codebase has
actually grown:

  TPL101  Python ``if``/``while``/``for`` branching on a *traced* value
          inside a ``@jax.jit`` body or ``device_fn``. Branching on
          ``x.shape``/``x.ndim``/``x.dtype``/``len(x)`` is fine (those
          are static at trace time); branching on ``x`` itself raises a
          TracerBoolConversionError or bakes in one trace per branch.
  TPL102  ``static_argnums``/``static_argnames``/``donate_argnums``
          passed a *list* literal. Lists are unhashable, so the jit
          cache keys degrade (newer jax versions reject them outright);
          use a tuple.
  TPL103  f-string / ``str()``/``repr()``/``format()`` over a traced
          value inside a jitted body: concretizes the tracer (error) or
          leaks a trace-time constant into strings that then differ per
          trace.
"""

from __future__ import annotations

import ast
from typing import Iterator

from triton_client_tpu.analysis.engine import (
    Finding,
    Module,
    Package,
    Rule,
    call_name,
    context_of,
    dotted_name,
    qualname_contexts,
    register,
)

# attribute reads on a traced value that are static at trace time
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "at"}
_STATIC_CALLS = {"len", "isinstance", "type", "hasattr", "getattr"}
_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}

# Parameter-name convention for STATIC serving config threaded through
# jitted bodies (round 10): a precision policy (runtime/precision.py
# PrecisionPolicy — a frozen dataclass of python strings/floats) rides
# into launched programs by closure or argument, and branching on it
# (`if policy.name == "bf16"`, `policy.scale_for(k) is not None`)
# dispatches on serving CONFIG, not on a tracer — one executable per
# policy is exactly the intent. Names matching this convention are
# excluded from the traced-param set for every TPL1xx rule.
_STATIC_PARAM_SUFFIXES = ("policy", "precision")


def is_static_param_name(name: str) -> bool:
    """True for parameter names that carry static (python) serving
    config by convention: ``policy``, ``precision``, ``*_policy``,
    ``*_precision``."""
    n = name.lower()
    return any(
        n == s or n.endswith("_" + s) for s in _STATIC_PARAM_SUFFIXES
    )


def _is_jit_call(node: ast.Call) -> bool:
    name = call_name(node)
    if name in _JIT_NAMES:
        return True
    # functools.partial(jax.jit, ...) decorators
    if name.endswith("partial") and node.args:
        first = node.args[0]
        return isinstance(first, (ast.Name, ast.Attribute)) and (
            dotted_name(first) in _JIT_NAMES
        )
    return False


def jit_bodies(module: Module) -> Iterator[tuple[ast.AST, list[str], str]]:
    """Yield (function node, traced param names, context) for every
    jit-compiled function the module defines:

      * ``@jax.jit``-decorated defs (incl. ``partial(jax.jit, ...)``)
      * defs named ``device_fn`` (the repository's launch contract:
        TPUChannel wraps them in ``jax.jit(..., donate_argnums)``)
      * lambdas / local defs passed as the first argument of a
        ``jax.jit(...)`` call

    Static args named by ``static_argnums``/``static_argnames`` are
    excluded from the traced set, as are params matching the
    static-config naming convention (:func:`is_static_param_name`).
    """
    contexts = qualname_contexts(module.tree)

    def params(fn: ast.AST, static_nums=(), static_names=()) -> list[str]:
        args = fn.args
        names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
        if names and names[0] in ("self", "cls"):
            names = names[1:]
        out = []
        for i, n in enumerate(names):
            if i in static_nums or n in static_names:
                continue
            if is_static_param_name(n):
                continue  # precision policy config — never a tracer
            out.append(n)
        return out

    def static_spec(call: ast.Call | None) -> tuple[tuple, tuple]:
        nums: tuple = ()
        names: tuple = ()
        if call is None:
            return nums, names
        for kw in call.keywords:
            if kw.arg == "static_argnums":
                try:
                    v = ast.literal_eval(kw.value)
                    nums = tuple(v) if isinstance(v, (list, tuple)) else (v,)
                except (ValueError, SyntaxError):
                    pass
            elif kw.arg == "static_argnames":
                try:
                    v = ast.literal_eval(kw.value)
                    names = tuple([v] if isinstance(v, str) else v)
                except (ValueError, SyntaxError):
                    pass
        return nums, names

    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            jit_deco = None
            for deco in node.decorator_list:
                if isinstance(deco, ast.Call) and _is_jit_call(deco):
                    jit_deco = deco
                elif dotted_name(deco) in _JIT_NAMES:
                    jit_deco = ast.Call(func=deco, args=[], keywords=[])
            if jit_deco is not None or node.name == "device_fn":
                nums, names = static_spec(jit_deco)
                yield node, params(node, nums, names), contexts.get(
                    node, node.name
                )
        elif isinstance(node, ast.Call) and _is_jit_call(node) and node.args:
            fn = node.args[0]
            if isinstance(fn, ast.Lambda):
                nums, names = static_spec(node)
                yield fn, params(fn, nums, names), "<lambda>"


def _traced_uses(test: ast.AST, traced: set[str]) -> list[ast.Name]:
    """Name loads of traced params in ``test`` that are NOT shielded by
    a static attribute/call (``x.shape``, ``len(x)``, ...)."""
    hits: list[ast.Name] = []

    def walk(node: ast.AST) -> None:
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            return  # x.shape / x.dtype — static, don't descend into x
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in _STATIC_CALLS:
                return
        if (
            isinstance(node, ast.Name)
            and isinstance(node.ctx, ast.Load)
            and node.id in traced
        ):
            hits.append(node)
        for child in ast.iter_child_nodes(node):
            walk(child)

    walk(test)
    return hits


@register
class TracedBranchRule(Rule):
    code = "TPL101"
    name = "traced-branch"
    doc = (
        "Python control flow (`if`/`while`/`for`) branches on a traced "
        "value inside a jit-compiled body; use `jnp.where`/"
        "`lax.cond`/`lax.fori_loop`, or mark the argument static."
    )

    def check(self, package: Package) -> Iterator[Finding]:
        for module in package.modules:
            for fn, traced_params, ctx in jit_bodies(module):
                traced = set(traced_params)
                body = fn.body if isinstance(fn.body, list) else [fn.body]
                for stmt in ast.walk(ast.Module(body=body, type_ignores=[])):
                    if isinstance(stmt, (ast.If, ast.While)):
                        for use in _traced_uses(stmt.test, traced):
                            yield self.finding(
                                module,
                                stmt,
                                f"`{type(stmt).__name__.lower()}` branches on "
                                f"traced value `{use.id}` inside a jitted "
                                "body (retrace/TracerBoolConversionError)",
                                context=ctx,
                            )
                    elif isinstance(stmt, ast.For):
                        for use in _traced_uses(stmt.iter, traced):
                            yield self.finding(
                                module,
                                stmt,
                                f"`for` iterates over traced value "
                                f"`{use.id}` inside a jitted body "
                                "(unrolls per trace; use lax.fori_loop/scan)",
                                context=ctx,
                            )


@register
class StaticArgListRule(Rule):
    code = "TPL102"
    name = "unhashable-static-args"
    doc = (
        "`static_argnums`/`static_argnames`/`donate_argnums` passed a "
        "list literal — lists are unhashable, degrading (or breaking) "
        "the jit cache key; use a tuple."
    )

    _KEYS = ("static_argnums", "static_argnames", "donate_argnums")

    def check(self, package: Package) -> Iterator[Finding]:
        for module in package.modules:
            for node in ast.walk(module.tree):
                if not (isinstance(node, ast.Call) and _is_jit_call(node)):
                    continue
                for kw in node.keywords:
                    if kw.arg in self._KEYS and isinstance(kw.value, ast.List):
                        yield self.finding(
                            module,
                            kw.value,
                            f"`{kw.arg}` is a list literal; use a tuple "
                            "(lists are unhashable jit-cache keys)",
                            context=context_of(module, node),
                        )


@register
class TracedStringRule(Rule):
    code = "TPL103"
    name = "traced-string-leak"
    doc = (
        "f-string/`str()`/`repr()`/`format()` over a traced value inside "
        "a jitted body — concretizes the tracer or bakes a trace-time "
        "constant into the string."
    )

    def check(self, package: Package) -> Iterator[Finding]:
        for module in package.modules:
            for fn, traced_params, ctx in jit_bodies(module):
                traced = set(traced_params)
                body = fn.body if isinstance(fn.body, list) else [fn.body]
                for node in ast.walk(ast.Module(body=body, type_ignores=[])):
                    if isinstance(node, ast.FormattedValue):
                        for use in _traced_uses(node.value, traced):
                            yield self.finding(
                                module,
                                node,
                                f"f-string formats traced value `{use.id}` "
                                "inside a jitted body",
                                context=ctx,
                            )
                    elif isinstance(node, ast.Call) and call_name(node) in (
                        "str",
                        "repr",
                        "format",
                    ):
                        for arg in node.args:
                            for use in _traced_uses(arg, traced):
                                yield self.finding(
                                    module,
                                    node,
                                    f"`{call_name(node)}()` over traced "
                                    f"value `{use.id}` inside a jitted body",
                                    context=ctx,
                                )
