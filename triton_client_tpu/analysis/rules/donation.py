"""TPL2xx — buffer-donation misuse.

``jax.jit(..., donate_argnums=...)`` hands the argument's HBM buffer to
XLA for reuse: after the call the donated array is *deleted* — touching
it raises ``RuntimeError: Array has been deleted`` (and only at
runtime, only on backends that honor donation, which is why CI on CPU
never sees it). The serving launch path donates every spec-marked
input (channel/tpu_channel.py ``_launcher``), so the two bug shapes
worth catching at review time are:

  TPL201  read-after-donation: a variable passed in a donated position
          is loaded again later in the same function (flow-sensitive in
          statement order; reassignment clears the taint). This covers
          the "stats()/telemetry span touches a donated buffer later"
          case too — the later touch IS the read.
  TPL202  donating persistent state: the donated argument is an
          attribute (``self._buf``) or subscript into shared state —
          the owner object still holds a reference to a now-deleted
          array, so the next reader anywhere in the process blows up.

Donating callables are found three ways: names bound from a
``jax.jit(..., donate_argnums=...)`` expression anywhere in the module;
names unpacked from a call to a function that *returns* such a callable
— the shape ``launcher, ... = self._launcher(model)`` the channel
actually uses; and (a bounded package-wide fixpoint) functions whose
returned head is itself bound from a known donor factory — required
since the stage/launch engine moved to ``channel/staged.py`` while the
``jax.jit`` factories live in the subclass modules
(``TPUChannel._make_launcher`` / ``ShardedTPUChannel._make_launcher``):
``StagedChannel._launcher`` returns what ``_make_launcher`` built, so
it must inherit the factory's donate positions for the launch call site
to stay tracked.
"""

from __future__ import annotations

import ast
from typing import Iterator

from triton_client_tpu.analysis.engine import (
    Finding,
    Module,
    Package,
    Rule,
    call_name,
    qualname_contexts,
    register,
)


def _donate_positions(call: ast.Call) -> tuple[int, ...]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            try:
                v = ast.literal_eval(kw.value)
            except (ValueError, SyntaxError):
                return ()
            return tuple(v) if isinstance(v, (list, tuple)) else (int(v),)
    return ()


def _is_jit(call: ast.Call) -> bool:
    return call_name(call) in ("jax.jit", "jit", "pjit", "jax.pjit")


def _return_head(ret: ast.Return) -> ast.AST | None:
    """The returned value, or the first element of a returned tuple —
    the factory convention is ``return launcher, donate_names, ...``."""
    head = ret.value
    if isinstance(head, ast.Tuple) and head.elts:
        head = head.elts[0]
    return head


class _DonorIndex:
    """Module-wide map of names that are donating callables.

    ``direct``: {function-scope or module-level name -> donate positions}
    ``via_call``: {callable name (function or method) -> positions} for
    same-module functions whose return value is (or starts with) a
    donating jit callable — callers that unpack the result get the
    first target marked. ``shared_via_call`` merges in the package-wide
    factory map (:func:`build_donor_map`) so a module can consume a
    factory defined elsewhere (the staged/subclass split).
    """

    def __init__(
        self,
        module: Module,
        shared_via_call: dict[str, tuple[int, ...]] | None = None,
    ) -> None:
        self.direct: dict[str, tuple[int, ...]] = {}
        self.via_call: dict[str, tuple[int, ...]] = {}
        jit_names: dict[str, tuple[int, ...]] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                call = node.value
                if _is_jit(call):
                    pos = _donate_positions(call)
                    if pos:
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                jit_names[tgt.id] = pos
                                self.direct[tgt.id] = pos
        # functions returning a donating callable (directly or as the
        # head of a returned tuple)
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for ret in ast.walk(node):
                if not isinstance(ret, ast.Return) or ret.value is None:
                    continue
                head = _return_head(ret)
                if isinstance(head, ast.Name) and head.id in jit_names:
                    self.via_call[node.name] = jit_names[head.id]
                elif isinstance(head, ast.Call) and _is_jit(head):
                    pos = _donate_positions(head)
                    if pos:
                        self.via_call[node.name] = pos
        if shared_via_call:
            for name, pos in shared_via_call.items():
                self.via_call.setdefault(name, pos)


def build_donor_map(package: Package) -> dict[str, tuple[int, ...]]:
    """Package-wide donor-factory map: simple callable name -> donate
    positions, closed over factory-returns-factory chains.

    Seeded with every module's local ``via_call``, then a bounded
    fixpoint: a function whose returned head is a name bound (in that
    function) from a call to a known factory becomes a factory with the
    same positions. One round covers ``StagedChannel._launcher``
    (returns ``_make_launcher``'s launcher); the bound keeps pathological
    chains from looping."""
    via: dict[str, tuple[int, ...]] = {}
    for module in package.modules:
        via.update(_DonorIndex(module).via_call)
    for _ in range(len(package.modules) + 1):
        grew = False
        for module in package.modules:
            for fn in ast.walk(module.tree):
                if (
                    not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
                    or fn.name in via
                ):
                    continue
                bound: dict[str, tuple[int, ...]] = {}
                for node in ast.walk(fn):
                    if isinstance(node, ast.Assign) and isinstance(
                        node.value, ast.Call
                    ):
                        callee = call_name(node.value).split(".")[-1]
                        pos = via.get(callee)
                        if pos:
                            tgt = node.targets[0]
                            if isinstance(tgt, ast.Tuple) and tgt.elts:
                                tgt = tgt.elts[0]
                            if isinstance(tgt, ast.Name):
                                bound[tgt.id] = pos
                if not bound:
                    continue
                for ret in ast.walk(fn):
                    if isinstance(ret, ast.Return) and ret.value is not None:
                        head = _return_head(ret)
                        if isinstance(head, ast.Name) and head.id in bound:
                            via[fn.name] = bound[head.id]
                            grew = True
                            break
        if not grew:
            return via
    return via


def _donating_calls(
    fn: ast.AST, index: _DonorIndex
) -> Iterator[tuple[ast.Call, tuple[int, ...]]]:
    """(call node, donated positions) for donating call sites in fn,
    including local rebinds from `x, ... = self._maker(...)`."""
    local: dict[str, tuple[int, ...]] = dict(index.direct)
    # first pass: local names bound from donor-returning calls
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            callee = call_name(node.value).split(".")[-1]
            pos = index.via_call.get(callee)
            if pos:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Tuple) and tgt.elts:
                    tgt = tgt.elts[0]
                if isinstance(tgt, ast.Name):
                    local[tgt.id] = pos
            elif _is_jit(node.value):
                p = _donate_positions(node.value)
                if p and isinstance(node.targets[0], ast.Name):
                    local[node.targets[0].id] = p
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = call_name(node)
            simple = name.split(".")[-1] if name else ""
            pos = local.get(name) or local.get(simple)
            if pos:
                yield node, pos
            elif _is_jit(node):
                # immediate call: jax.jit(f, donate_argnums=(0,))(x)
                pass
            elif isinstance(node.func, ast.Call) and _is_jit(node.func):
                p = _donate_positions(node.func)
                if p:
                    yield node, p


@register
class ReadAfterDonationRule(Rule):
    code = "TPL201"
    name = "read-after-donation"
    doc = (
        "A variable passed in a `donate_argnums` position is read again "
        "after the donating call — the buffer was handed to XLA and "
        "deleted; reads fail at runtime on donation-capable backends."
    )

    def check(self, package: Package) -> Iterator[Finding]:
        shared = build_donor_map(package)
        for module in package.modules:
            index = _DonorIndex(module, shared)
            contexts = qualname_contexts(module.tree)
            for fn in ast.walk(module.tree):
                if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                ctx = contexts.get(fn, fn.name)
                donated: dict[str, int] = {}  # name -> donation lineno
                for call, positions in _donating_calls(fn, index):
                    for p in positions:
                        if p < len(call.args) and isinstance(
                            call.args[p], ast.Name
                        ):
                            name = call.args[p].id
                            line = call.lineno
                            if name not in donated or line < donated[name]:
                                donated[name] = line
                if not donated:
                    continue
                # reassignments clear the taint from their line onward
                cleared: dict[str, int] = {}
                for node in ast.walk(fn):
                    tgts = []
                    if isinstance(node, ast.Assign):
                        tgts = node.targets
                    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                        tgts = [node.target]
                    for tgt in tgts:
                        for leaf in ast.walk(tgt):
                            if (
                                isinstance(leaf, ast.Name)
                                and leaf.id in donated
                                and leaf.lineno >= donated[leaf.id]
                            ):
                                prev = cleared.get(leaf.id)
                                if prev is None or leaf.lineno < prev:
                                    cleared[leaf.id] = leaf.lineno
                for node in ast.walk(fn):
                    if (
                        isinstance(node, ast.Name)
                        and isinstance(node.ctx, ast.Load)
                        and node.id in donated
                        and node.lineno > donated[node.id]
                        and node.lineno < cleared.get(node.id, 10**9)
                    ):
                        # no line numbers in the message: fingerprints
                        # must survive unrelated line churn
                        yield self.finding(
                            module,
                            node,
                            f"`{node.id}` read after being passed in a "
                            "donated position (buffer deleted by XLA)",
                            context=ctx,
                        )


@register
class DonatePersistentRule(Rule):
    code = "TPL202"
    name = "donate-persistent-buffer"
    doc = (
        "A donated argument is an attribute or subscript of longer-lived "
        "state (`self._buf`, `cache[k]`): the owner keeps a reference to "
        "a deleted array and any later reader crashes."
    )

    def check(self, package: Package) -> Iterator[Finding]:
        shared = build_donor_map(package)
        for module in package.modules:
            index = _DonorIndex(module, shared)
            contexts = qualname_contexts(module.tree)
            for fn in ast.walk(module.tree):
                if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                ctx = contexts.get(fn, fn.name)
                for call, positions in _donating_calls(fn, index):
                    for p in positions:
                        if p >= len(call.args):
                            continue
                        arg = call.args[p]
                        if isinstance(arg, (ast.Attribute, ast.Subscript)):
                            src = ast.unparse(arg)
                            yield self.finding(
                                module,
                                arg,
                                f"donated argument `{src}` is held by "
                                "longer-lived state; donation deletes the "
                                "buffer under that reference",
                                context=ctx,
                            )
