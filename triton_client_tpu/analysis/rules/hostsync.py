"""TPL3xx — host synchronization on the serving hot path.

JAX dispatch is asynchronous: the whole overlapped-serving design
(stage → launch → lazy readback, PR 1) only works because nothing on
the request path forces the host to wait for the device. One stray
``np.asarray``/``.item()``/``float()`` on a device value serializes the
pipeline back to pre-overlap behavior — and profiling shows it as
"device time" because the wait happens inside the span. The rule walks
the package call graph from the serving roots and flags every
host-sync call in a reachable function:

  TPL301  blocking readback (``np.asarray``/``np.array``/
          ``jax.device_get``/``.item()``/``.tolist()``/``float()``/
          ``int()`` over a non-literal) in a hot-path function
  TPL302  explicit device fence (``block_until_ready``) in a hot-path
          function

Some syncs are the *point* (the readback in ``resolve()``, the trace's
execute/readback split): those stay, with a one-line justification in
``tpulint.baseline.json`` — the rule's job is making every sync an
explicit, reviewed decision rather than an accident.

Roots (suffix-matched against dotted qualnames) default to
:data:`HOT_PATH_ROOTS`; ``perf/_harness.py`` reuses this rule with a
single callable as the root set to vet timed regions.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from triton_client_tpu.analysis.engine import (
    Finding,
    Package,
    Rule,
    call_name,
    register,
)

#: The serving hot path: the shared StagedChannel engine (stage/launch
#: and the nested ``resolve`` readback closure) plus each subclass's
#: placement/launcher/readback hooks — the call graph resolves
#: ``self._place_inputs()`` to the base-class stub only, so overrides
#: must be roots themselves — the batcher's dispatch/merge/execute
#: machinery, and the gRPC servicer's issue path.
HOT_PATH_ROOTS = (
    "StagedChannel.stage",
    "StagedChannel.launch",
    "StagedChannel.do_inference",
    "StagedChannel.do_inference_async",
    # stage/launch live on StagedChannel since the round-7 factoring,
    # but a subclass-qualified definition (out-of-tree channels, doc
    # examples, test fixtures) is just as hot — keep the historical
    # names rooted too (suffix patterns that match nothing are inert)
    "TPUChannel.stage",
    "TPUChannel.launch",
    "TPUChannel.do_inference",
    "TPUChannel.do_inference_async",
    "TPUChannel._place_inputs",
    "TPUChannel._make_launcher",
    "ShardedTPUChannel._place_inputs",
    "ShardedTPUChannel._make_launcher",
    "ShardedTPUChannel._host_outputs",
    "BatchingChannel.do_inference",
    "BatchingChannel._on_batch",
    "BatchingChannel._dispatch_once",
    "BatchingChannel._run_group",
    "BatchingChannel._run_solo",
    "BatchingChannel._merge_parts",
    "_Servicer._issue",
    # round-12 overload control: the admission gate and breaker check
    # run per request inside _issue/launch, but live on foreign objects
    # the call graph cannot follow through `self._admission.admit(...)`
    # — root them explicitly so a host sync there is still a finding
    "AdmissionController.admit",
    "AdmissionController.finished",
    "CircuitBreaker.allow",
    "CircuitBreaker.record_success",
    # ISSUE 8 continuous batching: the windowless scheduler's admission
    # and packed-ragged dispatch run per request / per formed batch, and
    # the segment-pack placement/launcher hooks are the ragged
    # equivalents of _place_inputs/_make_launcher — all hot
    "ContinuousBatchingChannel.do_inference",
    "ContinuousBatchingChannel._form_group_locked",
    "ContinuousBatchingChannel._run_group",
    "ContinuousBatchingChannel._run_ragged_group",
    "ContinuousBatchingChannel._pad_target",
    "StagedChannel._place_ragged",
    "StagedChannel._ragged_launcher",
    "StagedChannel._make_ragged_launcher",
    "ShardedTPUChannel._place_ragged",
    "ShardedTPUChannel._make_ragged_launcher",
    # ISSUE 9 multi-tenant lifecycle: acquire/release run per request
    # (RPC thread and stage), note_cost inside the launcher build, and
    # the DRR key/charge run under _ready_cv on every insort/group —
    # a host sync in any of them stalls every tenant at once
    "ModelLifecycleManager.acquire",
    "ModelLifecycleManager.release",
    "ModelLifecycleManager.note_cost",
    "ContinuousBatchingChannel._edf_key",
    "ContinuousBatchingChannel._charge_tenants_locked",
    # ISSUE 10 replicated front door: the router's pick/record/accounting
    # run per request (and per retry/hedge) on the caller's thread; a
    # host sync in any of them stalls every request through the fleet
    "FrontDoorRouter.do_inference",
    "FrontDoorRouter._launch",
    "ReplicaSet.pick",
    "ReplicaSet.release",
    "ReplicaSet.record_success",
    "ReplicaSet.record_failure",
    "RetryBudget.deposit",
    "RetryBudget.try_spend",
    # ISSUE 11 fleet tracing + device-time attribution: context
    # encode/decode run per traced request on the RPC thread, the
    # ledger accumulate runs inside the launch-resolve closure right
    # after the deliberate device fence, and the router's routing core
    # (attempt spans, summary grafting) runs on the caller's thread —
    # a host sync in any of them taxes EVERY traced request
    "TraceContext.encode",
    "TraceContext.decode",
    "DeviceTimeLedger.record",
    "FrontDoorRouter._route",
    "FrontDoorRouter._attempt_span",
    # ISSUE 14 kernel attribution: the sampler's capture tick and the
    # collector's sink run on the telemetry cadence but inside the
    # process serving traffic (and the tick holds the /profile guard);
    # the history tick runs on a timer diffing ledger snapshots under
    # the collector lock — a host sync in any of them turns background
    # observability into a serving stall. The launch-cost capture runs
    # once per model on the first-launch path itself.
    "ContinuousSampler.sample_once",
    "MetricHistory.tick",
    "RuntimeCollector.record_op_sample",
    "StagedChannel._ensure_launch_cost",
    # ISSUE 15 streaming sessions: advance/_step run per session frame
    # between stage and launch (the tracker's jit dispatch must stay
    # async — a host read there serializes every stream), release runs
    # inside the resolve closure, end on the RPC thread, and the
    # router's rendezvous pick on every stateful request. The
    # association core is rooted directly so a host sync inside the
    # device variant of greedy_assign can never hide behind the jit
    # boundary.
    "SessionManager.advance",
    "SessionManager._step",
    "SessionManager.release",
    "SessionManager.end",
    "ReplicaSet.pick_affinity",
    "tracking.greedy_assign",
    # ISSUE 16 fused Pallas kernels: the fused launch seams run inside
    # jit traces on the request path (pipelines route into them at
    # trace time), but rooting them directly means a host sync added to
    # a kernel wrapper — a debug `np.asarray` on a ref, a stray
    # `.item()` on a shape probe — is a finding even before any
    # pipeline test exercises the fused route
    "pallas_decode.fused_decode_nms_2d",
    "pallas_decode.fused_residual_decode",
    "pallas_decode.fused_suppress_pack_3d",
    "pallas_voxel.fused_mean_volume",
    "pallas_voxel.sorted_segment_mean_pallas",
    # ISSUE 17 continuous quality plane: the sampler/mirror seams run
    # per request on the RPC thread (server) or caller thread (router)
    # — route before dispatch, observe after the readback, enqueue is
    # the queue hand-off. They live on foreign objects the call graph
    # cannot follow through `self._quality.route(...)`, so each is
    # rooted directly; all numpy scoring must stay on the mirror's
    # worker thread, never in these.
    "QualityPlane.route",
    "QualityPlane.observe",
    "CanaryController.route",
    "ShadowMirror.enqueue",
    "shadow.sample_decision",
    "shadow.slice_decision",
    "FrontDoorRouter._observe_quality",
    # ISSUE 19 temporal compute reuse: dispatch runs per session frame
    # in _Servicer._issue BEFORE the channel (a host sync there taxes
    # every streaming request, keyframe or not); observe runs per frame
    # post-readback on the reply thread; the coast path's session step
    # must stay one async jit dispatch — a host read inside
    # SessionManager.coast or the plane's tile-selection path would
    # serialize every stream the way a sync in advance/_step would.
    "TemporalReusePlane.dispatch",
    "TemporalReusePlane.observe",
    "TemporalReusePlane._try_partial",
    "SessionManager.coast",
    "MultiCameraDriver._suppress",
)

# module-level call targets that force a host sync
_SYNC_CALLS = {
    "np.asarray": "blocking device->host readback",
    "np.array": "blocking device->host readback",
    "numpy.asarray": "blocking device->host readback",
    "numpy.array": "blocking device->host readback",
    "jax.device_get": "blocking device->host readback",
    "jax.block_until_ready": "device fence",
}
# zero-ambiguity method syncs on array-likes
_SYNC_METHODS = {
    "item": "scalar readback",
    "tolist": "full-array readback",
    "block_until_ready": "device fence",
}
# float() is the classic accidental fence (`float(loss)` in a hot
# loop); int()/bool() are overwhelmingly host-side shape/flag math in
# this codebase, so only float() is flagged.
_SCALAR_CASTS = {"float"}


def _sync_calls_in(fn: ast.AST) -> Iterator[tuple[ast.Call, str, str]]:
    """(call, code, description) for host-sync calls lexically inside
    ``fn`` but NOT inside a nested def (nested defs are their own call
    graph nodes and get scanned under their own qualname)."""

    def walk(node: ast.AST, top: bool) -> Iterator[tuple[ast.Call, str, str]]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(child, ast.Call):
                name = call_name(child)
                if name in _SYNC_CALLS:
                    code = (
                        "TPL302"
                        if "block_until_ready" in name
                        else "TPL301"
                    )
                    yield child, code, f"`{name}` ({_SYNC_CALLS[name]})"
                elif (
                    isinstance(child.func, ast.Attribute)
                    and child.func.attr in _SYNC_METHODS
                ):
                    code = (
                        "TPL302"
                        if child.func.attr == "block_until_ready"
                        else "TPL301"
                    )
                    yield (
                        child,
                        code,
                        f"`.{child.func.attr}()` "
                        f"({_SYNC_METHODS[child.func.attr]})",
                    )
                elif (
                    name in _SCALAR_CASTS
                    and child.args
                    and not isinstance(child.args[0], ast.Constant)
                    and not (
                        isinstance(child.args[0], ast.Call)
                        and call_name(child.args[0])
                        in ("len", "round", "perf_counter", "time.perf_counter")
                    )
                ):
                    yield (
                        child,
                        "TPL301",
                        f"`{name}()` over a non-literal (scalar readback "
                        "if the value is on device)",
                    )
            yield from walk(child, top)

    yield from walk(fn, True)


@register
class HostSyncRule(Rule):
    code = "TPL301"
    name = "hot-path-host-sync"
    doc = (
        "A blocking device->host readback (`np.asarray`, `.item()`, "
        "`float()`, ...) sits in a function reachable from the serving "
        "hot path; it serializes the overlapped pipeline. Move it to "
        "the deferred-readback side or baseline it with a justification."
    )

    roots: tuple[str, ...] = HOT_PATH_ROOTS

    def check(self, package: Package) -> Iterator[Finding]:
        yield from check_reachable(package, self.roots)


def check_reachable(
    package: Package, roots: Iterable[str]
) -> Iterator[Finding]:
    """Shared worker: flag sync calls in every function reachable from
    ``roots``. Used by the registry rule and by perf/_harness.py's
    timed-region assertion."""
    graph = package.callgraph
    hot = graph.reachable(roots)
    rule = HostSyncRule()
    for qn in sorted(hot):
        info = graph.functions.get(qn)
        if info is None:
            continue
        for call, code, desc in _sync_calls_in(info.node):
            yield rule.finding(
                info.module,
                call,
                f"{desc} on the hot path (reachable from serving roots)",
                context=_short_context(qn),
                code=code,
            )


def _short_context(qualname: str) -> str:
    """Drop the module-path prefix: keep Class.method / func.nested."""
    parts = qualname.split(".")
    # heuristics: module path components are lowercase_with_underscores
    # file names; keep from the first CamelCase part or the last two
    for i, p in enumerate(parts):
        if p[:1].isupper():
            return ".".join(parts[i:])
    return ".".join(parts[-2:]) if len(parts) > 1 else qualname
