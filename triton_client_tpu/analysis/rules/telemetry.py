"""TPL5xx — telemetry correctness.

Observability must never lie: an unbalanced span leaves "invisible
time" in the request wall (breaking the >=95% coverage gate from PR 2)
and an unbalanced gauge drifts monotonically until the Grafana panel is
fiction. Both bugs are structural:

  TPL501  ``.begin("name")`` with no ``.end("name")`` anywhere in the
          same module — the span can never close, so every traced
          request shows an open interval that gets silently dropped.
  TPL502  an in-flight gauge increment (``request_started``, ``.inc(``,
          ``_started``-style) whose paired decrement is neither inside
          a ``finally`` block nor inside a function that is itself
          called from a ``finally`` — an exception between the two
          leaks the gauge upward forever.
  TPL503  an SLO scoring call (``observe_request``) that is neither
          inside a ``finally`` block nor inside a function a
          ``finally`` calls — error paths return unscored, so the
          met/missed counters undercount exactly the requests most
          likely to have missed.

Pairs are matched by convention: (``begin``/``end``), (``inc``/``dec``),
(``request_started``/``request_finished``), (``acquire``/``release`` is
deliberately NOT included — lock pairing is TPL4xx's domain and
``with`` statements hide the release).
"""

from __future__ import annotations

import ast
from typing import Iterator

from triton_client_tpu.analysis.engine import (
    Finding,
    Module,
    Package,
    Rule,
    qualname_contexts,
    register,
)

_GAUGE_PAIRS = {
    "inc": "dec",
    "request_started": "request_finished",
}


def _literal_str_arg(call: ast.Call) -> str | None:
    if call.args and isinstance(call.args[0], ast.Constant):
        v = call.args[0].value
        if isinstance(v, str):
            return v
    return None


@register
class UnbalancedSpanRule(Rule):
    code = "TPL501"
    name = "span-begin-without-end"
    doc = (
        "A trace span is opened with `.begin(\"name\")` but no "
        "`.end(\"name\")` for the same literal name exists in the "
        "module — the span never closes and is dropped at finish."
    )

    def check(self, package: Package) -> Iterator[Finding]:
        for module in package.modules:
            begins: list[tuple[ast.Call, str]] = []
            ends: set[str] = set()
            skip = False
            for node in ast.walk(module.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    # the trace classes themselves define begin/end
                    if node.name in ("begin", "end"):
                        skip = True
            if skip:
                continue
            contexts = qualname_contexts(module.tree)
            for node in ast.walk(module.tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                ):
                    continue
                name = _literal_str_arg(node)
                if name is None:
                    continue
                if node.func.attr == "begin":
                    begins.append((node, name))
                elif node.func.attr == "end":
                    ends.add(name)
            for call, name in begins:
                if name not in ends:
                    yield self.finding(
                        module,
                        call,
                        f"span `{name}` is begun but never ended in this "
                        "module (open span is dropped at trace finish)",
                        context=_ctx_of(module, call, contexts),
                    )


@register
class GaugeLeakRule(Rule):
    code = "TPL502"
    name = "gauge-inc-without-finally-dec"
    doc = (
        "An in-flight gauge increment has no matching decrement in a "
        "`finally` block (directly, or via a helper that a `finally` "
        "calls) — any exception in between leaks the gauge upward."
    )

    def check(self, package: Package) -> Iterator[Finding]:
        for module in package.modules:
            defines = {
                node.name
                for node in ast.walk(module.tree)
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            contexts = qualname_contexts(module.tree)
            # every call that appears lexically inside a `finally:`
            finally_calls: set[str] = set()
            dec_sites: dict[str, set[str]] = {}  # dec attr -> funcs containing it
            for node in ast.walk(module.tree):
                if isinstance(node, ast.Try) and node.finalbody:
                    for stmt in node.finalbody:
                        for sub in ast.walk(stmt):
                            if isinstance(sub, ast.Call) and isinstance(
                                sub.func, ast.Attribute
                            ):
                                finally_calls.add(sub.func.attr)
                            elif isinstance(sub, ast.Call) and isinstance(
                                sub.func, ast.Name
                            ):
                                finally_calls.add(sub.func.id)
            for fn in ast.walk(module.tree):
                if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                for sub in ast.walk(fn):
                    if isinstance(sub, ast.Call) and isinstance(
                        sub.func, ast.Attribute
                    ):
                        dec_sites.setdefault(sub.func.attr, set()).add(fn.name)
            for node in ast.walk(module.tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                ):
                    continue
                inc_name = node.func.attr
                dec_name = _GAUGE_PAIRS.get(inc_name)
                if dec_name is None:
                    continue
                if inc_name == "inc" and module.relpath.endswith(
                    ("collector.py",)
                ):
                    # the collector defines the gauges; inc/dec pairing
                    # there is the metric's own contract
                    continue
                ok = dec_name in finally_calls or any(
                    holder in finally_calls
                    for holder in dec_sites.get(dec_name, ())
                    if holder in defines
                )
                if not ok:
                    yield self.finding(
                        module,
                        node,
                        f"`{inc_name}()` has no `{dec_name}()` reachable "
                        "from a `finally` in this module (gauge leaks on "
                        "exceptions)",
                        context=_ctx_of(module, node, contexts),
                    )


@register
class SLOExitPathRule(Rule):
    code = "TPL503"
    name = "slo-observe-not-on-exit-path"
    doc = (
        "An SLO scoring call (`observe_request`) is not in a `finally` "
        "block and not in a helper that a `finally` calls — exception "
        "paths return unscored and the met/missed counters undercount "
        "the requests most likely to have missed."
    )

    def check(self, package: Package) -> Iterator[Finding]:
        for module in package.modules:
            defines = {
                node.name
                for node in ast.walk(module.tree)
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            if "observe_request" in defines:
                # the tracker itself (obs/slo.py) defines the method;
                # its body is the counter's own contract, not a caller
                continue
            contexts = qualname_contexts(module.tree)
            in_finally: set[int] = set()
            finally_calls: set[str] = set()
            for node in ast.walk(module.tree):
                if isinstance(node, ast.Try) and node.finalbody:
                    for stmt in node.finalbody:
                        for sub in ast.walk(stmt):
                            if not isinstance(sub, ast.Call):
                                continue
                            in_finally.add(id(sub))
                            if isinstance(sub.func, ast.Attribute):
                                finally_calls.add(sub.func.attr)
                            elif isinstance(sub.func, ast.Name):
                                finally_calls.add(sub.func.id)
            fn_spans = [
                (fn.name, fn.lineno, getattr(fn, "end_lineno", fn.lineno))
                for fn in ast.walk(module.tree)
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
            ]
            for node in ast.walk(module.tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "observe_request"
                ):
                    continue
                line = node.lineno
                enclosing = {
                    name for name, lo, hi in fn_spans if lo <= line <= hi
                }
                ok = id(node) in in_finally or bool(
                    enclosing & finally_calls
                )
                if not ok:
                    yield self.finding(
                        module,
                        node,
                        "`observe_request()` is not reachable from a "
                        "`finally` in this module (error exits go "
                        "unscored; SLO counters undercount misses)",
                        context=_ctx_of(module, node, contexts),
                    )


def _ctx_of(module: Module, node: ast.AST, contexts: dict) -> str:
    best = ""
    line = getattr(node, "lineno", 0)
    for def_node, name in contexts.items():
        if (
            def_node.lineno <= line
            and getattr(def_node, "end_lineno", def_node.lineno) >= line
            and len(name) > len(best)
        ):
            best = name
    return best
