"""Baseline suppression: the reviewed set of accepted findings.

Some findings are correct *and* intentional — the lazy readback in
``resolve()`` is a host sync on the hot path because readback IS the
hot path's designed sync point. Those live in a committed
``tpulint.baseline.json`` with a one-line justification each; the CLI
exits 0 when every finding is baselined and non-zero the moment a NEW
finding appears. Matching is by :meth:`Finding.fingerprint` (code +
path + lexical context + message), so unrelated line churn does not
invalidate the baseline, while moving/duplicating the hazard does.

Workflow (docs/LINTING.md):
  1. ``python -m triton_client_tpu lint`` — see new findings
  2. fix them, or
  3. ``lint --write-baseline tpulint.baseline.json`` then EDIT the file
     to replace every ``"TODO: justify"`` with a real reason; an
     unjustified entry is itself reported.
"""

from __future__ import annotations

import json
from typing import Iterable

from triton_client_tpu.analysis.engine import Finding

UNJUSTIFIED = "TODO: justify"


class Baseline:
    def __init__(self, entries: dict[str, dict] | None = None) -> None:
        # fingerprint -> {"code", "path", "context", "message",
        #                  "justification"}
        self.entries: dict[str, dict] = dict(entries or {})

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        if not isinstance(doc, dict) or "entries" not in doc:
            raise ValueError(f"{path}: not a tpulint baseline (no 'entries')")
        return cls(doc["entries"])

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(
                {"version": 1, "tool": "tpulint", "entries": self.entries},
                f,
                indent=2,
                sort_keys=True,
            )
            f.write("\n")

    def match(self, finding: Finding) -> bool:
        return finding.fingerprint() in self.entries

    def split(
        self, findings: Iterable[Finding]
    ) -> tuple[list[Finding], list[Finding]]:
        """(new, suppressed) — new findings fail the build."""
        new: list[Finding] = []
        suppressed: list[Finding] = []
        for f in findings:
            (suppressed if self.match(f) else new).append(f)
        return new, suppressed

    def unjustified(self) -> list[str]:
        return sorted(
            fp
            for fp, e in self.entries.items()
            if not str(e.get("justification", "")).strip()
            or e.get("justification") == UNJUSTIFIED
        )

    def stale(self, findings: Iterable[Finding]) -> list[str]:
        """Baseline entries no finding matched — candidates to delete
        (reported as a warning, not an error: rules may be narrowed by
        a --rules selection)."""
        seen = {f.fingerprint() for f in findings}
        return sorted(fp for fp in self.entries if fp not in seen)

    def prune(self, findings: Iterable[Finding]) -> list[str]:
        """Drop entries whose fingerprints no current finding matches
        (the stale set); returns the dropped fingerprints. Entries that
        still match — and their justifications — are untouched."""
        dropped = self.stale(findings)
        for fp in dropped:
            del self.entries[fp]
        return dropped

    @classmethod
    def from_findings(
        cls,
        findings: Iterable[Finding],
        justification: str = UNJUSTIFIED,
        prior: "Baseline | None" = None,
    ) -> "Baseline":
        """Baseline covering exactly ``findings``. Entries whose
        fingerprint survives from ``prior`` KEEP their reviewed
        justification; entries prior did not know start as TODO; prior
        entries nothing matches anymore are pruned (not carried)."""
        old = prior.entries if prior is not None else {}
        entries: dict[str, dict] = {}
        for f in findings:
            fp = f.fingerprint()
            kept = entries.get(fp, old.get(fp, {})).get("justification")
            entries[fp] = {
                "code": f.code,
                "path": f.path,
                "context": f.context,
                "message": f.message,
                "justification": kept if kept else justification,
            }
        return cls(entries)
