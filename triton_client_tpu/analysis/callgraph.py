"""Lightweight package call graph for reachability-scoped rules.

The TPL3xx host-sync family needs "is this function on the serving hot
path?" — i.e. reachable from ``TPUChannel.stage``/``launch``,
``BatchingChannel``'s dispatch machinery, or ``_Servicer._issue``. A
full points-to analysis is overkill for a ~30-module package with a
conventional style, so resolution is name-based with three edges:

  * ``f(...)``          -> same-module function ``f``, else a
                           ``from m import f`` target in the package
  * ``self.m(...)``     -> method ``m`` of the lexically enclosing
                           class (plus any same-package base classes)
  * ``alias.f(...)``    -> function ``f`` of the package module that
                           ``import pkg.mod as alias`` / ``from pkg
                           import mod`` bound

Nested functions (closures like ``launch``'s ``resolve``) are treated
as reachable from their enclosing function — the serving pipeline leans
on closures for deferred work, and a deferred host sync is *exactly*
what TPL3xx exists to catch. Dynamic dispatch through variables is out
of scope; rules that need soundness must not rely on edges the graph
cannot see (unreachable = "not proven hot", never "proven cold").
"""

from __future__ import annotations

import ast
import collections
import dataclasses
from typing import Iterable

from triton_client_tpu.analysis.engine import Module, dotted_name


@dataclasses.dataclass
class FunctionInfo:
    """One function/method definition node in the package."""

    qualname: str  # "pkg.mod.Class.method" (module path dotted, no .py)
    module: Module
    node: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda
    class_name: str = ""  # enclosing class simple name, "" for free funcs


def _module_dotted(relpath: str) -> str:
    name = relpath[:-3] if relpath.endswith(".py") else relpath
    name = name.replace("\\", "/").strip("/").replace("/", ".")
    if name.endswith(".__init__"):
        name = name[: -len(".__init__")]
    return name


class CallGraph:
    def __init__(self, modules: Iterable[Module]) -> None:
        self.functions: dict[str, FunctionInfo] = {}
        self.edges: dict[str, set[str]] = collections.defaultdict(set)
        self._modules = list(modules)
        self._mod_names = {m: _module_dotted(m.relpath) for m in self._modules}
        # simple method index: method name -> {qualnames} (fallback for
        # cross-class self-dispatch through base classes)
        self._methods: dict[str, set[str]] = collections.defaultdict(set)
        self._import_cache: dict[int, dict[str, str]] = {}
        for m in self._modules:
            self._collect_functions(m)
        for m in self._modules:
            self._collect_edges(m)

    # -- construction ------------------------------------------------------

    def _collect_functions(self, module: Module) -> None:
        mod_name = self._mod_names[module]

        def walk(node: ast.AST, prefix: str, class_name: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qn = f"{prefix}.{child.name}"
                    self.functions[qn] = FunctionInfo(
                        qn, module, child, class_name
                    )
                    if class_name:
                        self._methods[child.name].add(qn)
                    walk(child, qn, "")  # nested defs: not methods
                elif isinstance(child, ast.ClassDef):
                    walk(child, f"{prefix}.{child.name}", child.name)
                else:
                    walk(child, prefix, class_name)

        walk(module.tree, mod_name, "")

    def _imports(self, module: Module) -> dict[str, str]:
        """local alias -> dotted target (module or module.attr)."""
        out: dict[str, str] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    out[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module:
                base = node.module
                if node.level:  # relative import: anchor to this package
                    pkg_parts = self._mod_names[module].split(".")
                    anchor = pkg_parts[: -node.level]
                    base = ".".join(anchor + [node.module])
                for a in node.names:
                    out[a.asname or a.name] = f"{base}.{a.name}"
        return out

    def imports_of(self, module: Module) -> dict[str, str]:
        """Cached alias map for ``module`` (threads.py resolves spawn
        targets with the same import model the edge builder uses)."""
        cached = self._import_cache.get(id(module))
        if cached is None:
            cached = self._imports(module)
            self._import_cache[id(module)] = cached
        return cached

    def resolve_call(
        self,
        module: Module,
        call: ast.Call,
        enclosing_class: str,
        owner: str | None = None,
    ) -> set[str]:
        """Package qualnames a call expression may target. Name-based,
        same three edges the module docstring describes; ``owner`` (the
        caller's qualname) additionally resolves bare names to nested
        defs in the caller — ``submit(run)``-style closures."""
        name = dotted_name(call.func)
        if not name:
            return set()
        mod_name = self._mod_names[module]
        imports = self.imports_of(module)
        targets: set[str] = set()
        parts = name.split(".")
        if parts[0] == "self" and len(parts) == 2:
            # self.m() -> enclosing class method, else any same-name
            # method in the package (base-class fallback)
            qn = f"{mod_name}.{enclosing_class}.{parts[1]}"
            if qn in self.functions:
                targets.add(qn)
            else:
                targets |= self._methods.get(parts[1], set())
            return targets
        # plain f() -> nested def in the caller, same module, from-imports
        if len(parts) == 1:
            if owner and f"{owner}.{parts[0]}" in self.functions:
                targets.add(f"{owner}.{parts[0]}")
            qn = f"{mod_name}.{parts[0]}"
            if qn in self.functions:
                targets.add(qn)
            imp = imports.get(parts[0])
            if imp and imp in self.functions:
                targets.add(imp)
            return targets
        # alias.f() / alias.sub.f() -> imported module function
        imp = imports.get(parts[0])
        if imp:
            qn = ".".join([imp] + parts[1:])
            if qn in self.functions:
                targets.add(qn)
        qn = ".".join([mod_name] + parts)  # e.g. Class.method refs
        if qn in self.functions:
            targets.add(qn)
        return targets

    def _collect_edges(self, module: Module) -> None:
        def resolve(call: ast.Call, enclosing_class: str) -> set[str]:
            return self.resolve_call(module, call, enclosing_class)

        def walk(node: ast.AST, owner: str | None, enclosing_class: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if owner is None:
                        qn = None
                        for q, info in self.functions.items():
                            if info.node is child:
                                qn = q
                                break
                        child_owner = qn
                    else:
                        child_owner = f"{owner}.{child.name}"
                        # a nested def is reachable from its encloser:
                        # closures ARE the deferred hot path
                        self.edges[owner].add(child_owner)
                    walk(child, child_owner, enclosing_class)
                elif isinstance(child, ast.ClassDef):
                    walk(child, None, child.name)
                else:
                    if owner is not None and isinstance(child, ast.Call):
                        for t in resolve(child, enclosing_class):
                            self.edges[owner].add(t)
                    walk(child, owner, enclosing_class)

        walk(module.tree, None, "")

    # -- queries ----------------------------------------------------------

    def match(self, patterns: Iterable[str]) -> set[str]:
        """Qualnames whose dotted suffix matches any pattern; a pattern
        ending in '.*' matches every method of the named class/module."""
        out: set[str] = set()
        for pat in patterns:
            if pat.endswith(".*"):
                prefix = pat[:-1]  # keep the dot
                for qn in self.functions:
                    if qn.startswith(prefix) or f".{prefix}" in f".{qn}":
                        out.add(qn)
            else:
                for qn in self.functions:
                    if qn == pat or qn.endswith("." + pat):
                        out.add(qn)
        return out

    def reachable(self, roots: Iterable[str]) -> set[str]:
        """BFS closure over call edges from root patterns."""
        seen = set(self.match(roots))
        queue = collections.deque(seen)
        while queue:
            qn = queue.popleft()
            for nxt in self.edges.get(qn, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    queue.append(nxt)
        return seen
