"""tpulint engine: modules, findings, rule registry, pragma handling.

The analyzer is deliberately stdlib-only (``ast`` + ``tokenize``-free
line scanning): it must run in every environment the serving stack
runs in, including the TPU pods where nothing beyond the runtime deps
is installed. A *rule* is a class with a ``TPLnnn`` code that walks the
parsed package and yields :class:`Finding` records; the *engine* owns
module loading, the rule registry, inline-pragma suppression and the
text/JSON renderers. Baseline suppression (accepted findings carried
in ``tpulint.baseline.json``) lives in :mod:`.baseline`.

Why AST and not runtime checks: the hazards tpulint targets —
use-after-donation, trace-time branching on traced values, host syncs
on the hot path, unguarded shared state — are *structural* properties
of the code (see the compiled-TPU literature cited in docs/LINTING.md);
they are visible in the syntax tree at review time, long before a perf
run would surface them as a regression.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import re
import sys
from typing import Iterable, Iterator

# ``# tpulint: disable=TPL101,TPL2`` — codes may be full (TPL101) or a
# family prefix (TPL1, TPL2xx-style "TPL2"); ``all`` disables every rule.
_PRAGMA_RE = re.compile(r"#\s*tpulint:\s*disable=([A-Za-z0-9_,\s]+)")
_FILE_PRAGMA_RE = re.compile(r"#\s*tpulint:\s*disable-file=([A-Za-z0-9_,\s]+)")


@dataclasses.dataclass
class Finding:
    """One rule violation at a source location.

    ``context`` is the dotted lexical context (``Class.method`` or
    ``function``) the finding sits in; it feeds the fingerprint so
    baselines survive unrelated line-number churn.
    """

    code: str
    name: str
    path: str
    line: int
    col: int
    message: str
    context: str = ""

    def fingerprint(self) -> str:
        """Stable identity for baseline matching: everything except the
        line/column, so a finding keeps its suppression when code above
        it moves."""
        raw = "|".join((self.code, self.path, self.context, self.message))
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    def to_dict(self) -> dict:
        return {
            "code": self.code,
            "name": self.name,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "context": self.context,
            "fingerprint": self.fingerprint(),
        }

    def render(self) -> str:
        ctx = f" [{self.context}]" if self.context else ""
        return f"{self.path}:{self.line}:{self.col}: {self.code} {self.message}{ctx}"


class Module:
    """One parsed source file plus the line-level pragma index."""

    def __init__(self, path: str, relpath: str, source: str) -> None:
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # line -> set of disabled codes/prefixes ("ALL" disables all)
        self._pragmas: dict[int, set[str]] = {}
        self._file_pragmas: set[str] = set()
        for i, text in enumerate(self.lines, start=1):
            m = _PRAGMA_RE.search(text)
            if m:
                self._pragmas[i] = {
                    c.strip().upper() for c in m.group(1).split(",") if c.strip()
                }
            m = _FILE_PRAGMA_RE.search(text)
            if m:
                self._file_pragmas |= {
                    c.strip().upper() for c in m.group(1).split(",") if c.strip()
                }

    def suppressed(self, code: str, line: int) -> bool:
        code = code.upper()

        def match(disabled: set[str]) -> bool:
            return any(
                d == "ALL" or code == d or code.startswith(d) for d in disabled
            )

        if self._file_pragmas and match(self._file_pragmas):
            return True
        disabled = self._pragmas.get(line)
        return bool(disabled) and match(disabled)


class Package:
    """The analyzed module set + shared lazy facilities (call graph,
    thread/lock model)."""

    def __init__(self, modules: list[Module]) -> None:
        self.modules = modules
        self._callgraph = None
        self._threads = None
        self._pallas = None
        self.errors: list[str] = []

    @property
    def callgraph(self):
        if self._callgraph is None:
            from triton_client_tpu.analysis.callgraph import CallGraph

            self._callgraph = CallGraph(self.modules)
        return self._callgraph

    @property
    def threads(self):
        """Lazy :class:`analysis.threads.ThreadModel` — the package-wide
        lock graph + thread-root model the TPL6xx family queries. Built
        once and shared by every rule (same contract as ``callgraph``)."""
        if self._threads is None:
            from triton_client_tpu.analysis.threads import ThreadModel

            self._threads = ThreadModel(self)
        return self._threads

    @property
    def pallas(self):
        """Lazy :class:`analysis.pallas_model.PallasIndex` — every
        ``pl.pallas_call`` site's static kernel model (grid, BlockSpecs,
        scratch, interpret plumbing, named scopes), built once and
        shared by the TPL8xx family (same contract as ``callgraph``)."""
        if self._pallas is None:
            from triton_client_tpu.analysis.pallas_model import PallasIndex

            self._pallas = PallasIndex(self)
        return self._pallas


class Rule:
    """Base rule: subclasses set ``code``/``name``/``doc`` and implement
    ``check(package)``. ``doc`` is the one-paragraph rationale the CLI
    prints for ``lint --list-rules`` (docs/LINTING.md holds the long
    form with bad/good examples)."""

    code: str = "TPL000"
    name: str = "base"
    doc: str = ""

    def check(self, package: Package) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(
        self, module: Module, node: ast.AST, message: str, context: str = "",
        code: str | None = None,
    ) -> Finding:
        return Finding(
            code=code or self.code,
            name=self.name,
            path=module.relpath,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            message=message,
            context=context,
        )


_REGISTRY: dict[str, type[Rule]] = {}


def register(cls: type[Rule]) -> type[Rule]:
    _REGISTRY[cls.code] = cls
    return cls


def registry() -> dict[str, type[Rule]]:
    """code -> rule class; importing .rules populates it exactly once."""
    import triton_client_tpu.analysis.rules  # noqa: F401  (registration side effect)

    return dict(sorted(_REGISTRY.items()))


# -- module loading ---------------------------------------------------------


def _iter_py_files(path: str) -> Iterator[str]:
    if os.path.isfile(path):
        yield path
        return
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(
            d for d in dirs if d not in ("__pycache__", ".git", ".venv")
        )
        for f in sorted(files):
            if f.endswith(".py"):
                yield os.path.join(root, f)


def load_package(
    paths: Iterable[str], root: str | None = None, jobs: int = 1
) -> Package:
    """Parse every .py under ``paths`` into a Package. Unparseable files
    are recorded on ``package.errors`` instead of aborting the run —
    the CLI reports them and exits non-zero (a file the analyzer cannot
    read is a file the rules cannot vouch for).

    ``jobs > 1`` loads files on a thread pool — read + parse of ~40
    modules overlap instead of running serially (the CI gate passes
    ``--jobs``). Results keep the deterministic sorted-walk order
    regardless of completion order."""
    targets: list[tuple[str, str]] = []  # (abspath, relpath)
    root = os.path.abspath(root) if root else os.getcwd()
    for path in paths:
        for fpath in _iter_py_files(path):
            abspath = os.path.abspath(fpath)
            rel = os.path.relpath(abspath, root)
            if rel.startswith(".."):
                rel = abspath
            targets.append((abspath, rel))

    def load_one(target: tuple[str, str]):
        abspath, rel = target
        try:
            with open(abspath, encoding="utf-8") as f:
                source = f.read()
            return Module(abspath, rel, source), None
        except (OSError, SyntaxError, ValueError) as e:
            return None, f"{rel}: {e}"

    if jobs > 1 and len(targets) > 1:
        import concurrent.futures

        with concurrent.futures.ThreadPoolExecutor(
            max_workers=min(jobs, len(targets))
        ) as pool:
            results = list(pool.map(load_one, targets))
    else:
        results = [load_one(t) for t in targets]

    modules = [m for m, _ in results if m is not None]
    pkg = Package(modules)
    pkg.errors = [e for _, e in results if e is not None]
    return pkg


def load_source(
    source: str, path: str = "<string>", relpath: str | None = None
) -> Package:
    """Single-snippet package: the test-fixture entry point."""
    return Package([Module(path, relpath or path, source)])


# -- running ----------------------------------------------------------------


def run_rules(
    package: Package,
    codes: Iterable[str] | None = None,
    stats: dict[str, dict] | None = None,
) -> list[Finding]:
    """Run the (selected) registry over the package; pragma-suppressed
    findings are dropped here, baseline suppression happens in the CLI
    so ``--write-baseline`` can see the full set.

    ``stats``, when given, is filled in place with per-rule cost rows
    ``{code: {"findings": n, "elapsed_ms": ms}}`` (post-pragma counts)
    — the ``lint --stats`` table that keeps the gate's cost visible as
    families grow."""
    import time

    selected = registry()
    if codes:
        wanted = {c.strip().upper() for c in codes}
        selected = {
            code: cls
            for code, cls in selected.items()
            if any(code == w or code.startswith(w) for w in wanted)
        }
    by_path = {m.relpath: m for m in package.modules}
    findings: list[Finding] = []
    for code, cls in selected.items():
        t0 = time.perf_counter()
        kept = 0
        for f in cls().check(package):
            mod = by_path.get(f.path)
            if mod is not None and mod.suppressed(f.code, f.line):
                continue
            findings.append(f)
            kept += 1
        if stats is not None:
            stats[code] = {
                "findings": kept,
                "elapsed_ms": round((time.perf_counter() - t0) * 1e3, 3),
            }
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return findings


def render_text(findings: list[Finding], stream=None) -> None:
    stream = stream or sys.stdout
    for f in findings:
        print(f.render(), file=stream)


def render_json(
    findings: list[Finding], suppressed: int = 0, errors: list[str] | None = None
) -> str:
    return json.dumps(
        {
            "version": 1,
            "tool": "tpulint",
            "findings": [f.to_dict() for f in findings],
            "summary": {
                "total": len(findings),
                "suppressed_by_baseline": suppressed,
                "by_code": _count_by(findings, "code"),
                "by_path": _count_by(findings, "path"),
            },
            "errors": list(errors or ()),
        },
        indent=2,
        sort_keys=True,
    )


def _count_by(findings: list[Finding], attr: str) -> dict:
    out: dict[str, int] = {}
    for f in findings:
        k = getattr(f, attr)
        out[k] = out.get(k, 0) + 1
    return dict(sorted(out.items()))


def render_sarif(
    findings: list[Finding], errors: list[str] | None = None
) -> str:
    """SARIF 2.1.0 document for code-scanning UIs (GitHub, VS Code SARIF
    viewers). ``partialFingerprints`` carries the same line-churn-proof
    fingerprint the baseline uses, so scanning backends dedupe alerts
    across commits exactly the way ``tpulint.baseline.json`` does."""
    rules_meta: dict[str, dict] = {}
    for code, cls in registry().items():
        rules_meta[code] = {
            "id": code,
            "name": cls.name,
            "shortDescription": {"text": cls.name},
            "fullDescription": {"text": " ".join((cls.doc or "").split())},
            "helpUri": "docs/LINTING.md",
        }
    results = []
    for f in findings:
        # codes emitted via Rule.finding(code=...) (TPL302, TPL6xx
        # variants) still resolve to a driver rule entry
        if f.code not in rules_meta:
            rules_meta[f.code] = {
                "id": f.code,
                "name": f.name,
                "shortDescription": {"text": f.name},
                "helpUri": "docs/LINTING.md",
            }
        results.append(
            {
                "ruleId": f.code,
                "level": "error",
                "message": {
                    "text": f.message
                    + (f" [{f.context}]" if f.context else "")
                },
                "locations": [
                    {
                        "physicalLocation": {
                            "artifactLocation": {
                                "uri": f.path.replace(os.sep, "/"),
                            },
                            "region": {
                                "startLine": max(f.line, 1),
                                "startColumn": f.col + 1,
                            },
                        }
                    }
                ],
                "partialFingerprints": {"tpulint/v1": f.fingerprint()},
            }
        )
    for msg in errors or ():
        results.append(
            {
                "ruleId": "TPL000",
                "level": "error",
                "message": {"text": f"analysis error: {msg}"},
            }
        )
    doc = {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
            "master/Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "tpulint",
                        "informationUri": "docs/LINTING.md",
                        "rules": [
                            rules_meta[k] for k in sorted(rules_meta)
                        ],
                    }
                },
                "results": results,
            }
        ],
    }
    return json.dumps(doc, indent=2, sort_keys=True)


# -- shared AST helpers (used by several rule modules) ----------------------


def qualname_contexts(tree: ast.AST) -> dict[ast.AST, str]:
    """node -> dotted lexical context for every function/class def."""
    out: dict[ast.AST, str] = {}

    def walk(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                name = prefix + ("." if prefix else "") + child.name
                out[child] = name
                walk(child, name)
            else:
                walk(child, prefix)

    walk(tree, "")
    return out


def context_of(module: Module, node: ast.AST) -> str:
    """Nearest enclosing function/class context of ``node`` (by position;
    cheap — rules call it per finding, not per node)."""
    best = ""
    target_line = getattr(node, "lineno", 0)
    for def_node, name in qualname_contexts(module.tree).items():
        if (
            def_node.lineno <= target_line
            and getattr(def_node, "end_lineno", def_node.lineno) >= target_line
        ):
            best = name if len(name) > len(best) else best
    return best


def call_name(node: ast.Call) -> str:
    """Dotted best-effort name of a call target ('np.asarray',
    'self._retire', 'float', '' when dynamic)."""
    return dotted_name(node.func)


def dotted_name(node: ast.AST) -> str:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def walk_held(
    fn: ast.AST, lock_of
) -> Iterator[tuple[ast.AST, frozenset]]:
    """Flow-sensitive walk of ``fn``'s body: yield ``(node, held)`` for
    every node lexically inside ``fn`` (nested defs/lambdas excluded —
    they are separate call-graph functions analyzed under their own
    qualname, and a closure does NOT inherit its definer's locks: it
    usually runs later, on another thread, unlocked).

    ``lock_of(expr) -> lock_id | None`` classifies ``with`` context
    expressions; a recognized lock extends the held set for exactly the
    ``with`` body. The ``With`` node itself is yielded with the
    PRE-acquisition held set — that yield IS the acquisition event the
    thread model turns into lock-order edges."""

    def rec(node: ast.AST, held: frozenset) -> Iterator[tuple[ast.AST, frozenset]]:
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if isinstance(child, ast.With):
                yield child, held
                inner = held
                for item in child.items:
                    yield item.context_expr, held
                    yield from rec(item.context_expr, held)
                    lid = lock_of(item.context_expr)
                    if lid:
                        inner = inner | {lid}
                for stmt in child.body:
                    yield stmt, inner
                    yield from rec(stmt, inner)
                continue
            yield child, held
            yield from rec(child, held)

    yield from rec(fn, frozenset())
