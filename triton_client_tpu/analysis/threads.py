"""Package-wide thread & lock model for the TPL6xx concurrency family.

The runtime is a web of cooperating threads — the batch dispatcher, the
stall watchdog, executor workers, the router's probe loop and hedge
completion callbacks, the SIGTERM handler — all mutating shared objects
guarded by per-structure locks. TPL4xx checks guarded-vs-bare
discipline *inside one class*; this model answers the questions that
need the whole package:

  * which locks exist, unified across a class hierarchy (a
    ``ContinuousBatchingChannel`` method holding ``self._ready_cv``
    holds the SAME lock a ``BatchingChannel`` method acquires);
  * which locks are held on entry to every function, propagated
    interprocedurally along the call graph (so a ``*_locked`` helper
    called under ``with self._lock:`` is known to run locked);
  * in what ORDER locks nest — the lock-order digraph whose cycles are
    potential deadlocks (TPL601);
  * which functions run on which THREAD ROOTS — discovered from
    ``threading.Thread/Timer`` spawns, ``Executor.submit``,
    ``add_done_callback``, ``signal.signal``, plus the declared roots
    AST cannot see (gRPC handler threads, the caller's own thread) — so
    an attribute mutated lock-free from two roots is a race (TPL602).

Everything here is an over-approximation in the safe direction for a
linter: held sets union over callers and paths (suppressing, never
inventing, race findings), reachability includes subclass overrides
(``self._run_group()`` in the base dispatch loop may land on the
subclass's override at runtime), and dynamic dispatch the name-based
call graph cannot see simply contributes nothing. "Not flagged" never
means "proven safe"; it means "not provably hazardous".
"""

from __future__ import annotations

import ast
import collections
import dataclasses
from typing import Iterable, Iterator

from triton_client_tpu.analysis.engine import (
    Module,
    call_name,
    walk_held,
)

# factories whose self-attribute bindings make an attribute a lock
_LOCK_FACTORIES = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "Lock",
    "RLock",
    "Condition",
}
# factories safe to re-acquire on the same thread (Condition wraps an
# RLock by default); a plain Lock re-acquired while held self-deadlocks
_REENTRANT_FACTORIES = {
    "threading.RLock",
    "RLock",
    "threading.Condition",
    "Condition",
}
# object construction is single-threaded: mutations there never race
_EXEMPT_METHODS = {"__init__", "__new__", "__post_init__"}

#: Thread roots the AST cannot discover, declared as (suffix pattern,
#: group, why). The *group* is the distinctness key for TPL602 — all
#: "caller" entries are ONE logical root (a caller thread entering via
#: do_inference vs do_inference_async is the same foreign thread), and
#: the gRPC server's handler pool is one root no matter how many
#: servicer methods it enters through. Extend this tuple when a new
#: externally-threaded entry point appears (docs/LINTING.md shows the
#: workflow).
DECLARED_THREAD_ROOTS: tuple[tuple[str, str, str], ...] = (
    (
        "_Servicer.*",
        "rpc",
        "gRPC server handler threads invoke every servicer method",
    ),
    (
        "do_inference",
        "caller",
        "public inference entry point: runs on the caller's thread",
    ),
    (
        "do_inference_async",
        "caller",
        "async issue side of the public entry point",
    ),
    # ISSUE 15 streaming sessions: the frame bracket spans threads —
    # advance runs on the issuing request thread (inside launch),
    # release on the readback executor inside the resolve closure — so
    # the lock-carrying SessionManager races across these two groups
    # unless every mutation holds the pool lock
    (
        "SessionManager.advance",
        "caller",
        "session frame bracket: runs on the issuing request thread",
    ),
    (
        "SessionManager.release",
        "executor",
        "resolve side of the frame bracket: readback executor threads",
    ),
)

# spawn shapes: call-name -> (kind, how to find the target expression)
_THREAD_CTORS = {"threading.Thread", "Thread"}
_TIMER_CTORS = {"threading.Timer", "Timer"}


@dataclasses.dataclass(frozen=True)
class ThreadRoot:
    """One discovered or declared source of a distinct thread of
    execution. ``group`` is the TPL602 distinctness key; ``pattern`` is
    what reachability is seeded from (an exact qualname for discovered
    roots, a suffix pattern for declared ones)."""

    group: str
    kind: str  # thread | timer | executor | callback | signal | declared
    pattern: str
    where: str  # "path.py:line" of the spawn site, or "declared"


@dataclasses.dataclass
class LockSite:
    """One lock acquisition: ``with self.<attr>:`` at ``node`` inside
    ``function``, with ``local_held`` locks already held lexically
    (entry-held locks are added by the model after the fixpoint)."""

    lock: str
    local_held: frozenset
    module: Module
    node: ast.AST
    function: str


@dataclasses.dataclass
class MutationSite:
    """One self-attribute mutation, with its lexically-held lock set."""

    family: str
    attr: str
    local_held: frozenset
    module: Module
    node: ast.AST
    function: str
    method: str  # simple method name (for __init__-style exemptions)


class ThreadModel:
    """The lock graph + thread-root model over one analyzed Package."""

    def __init__(self, package) -> None:
        self.package = package
        self.graph = package.callgraph
        # class hierarchy ----------------------------------------------------
        self._parents: dict[str, str] = {}
        self._class_names: set[str] = set()
        # family root -> {attr -> factory ("" when usage-discovered)}
        self.lock_attrs: dict[str, dict[str, str]] = collections.defaultdict(dict)
        self._collect_classes()
        self._overrides = self._build_overrides()
        # per-function local facts -------------------------------------------
        self.acquisitions: list[LockSite] = []
        self.mutations: dict[tuple[str, str], list[MutationSite]] = (
            collections.defaultdict(list)
        )
        self._call_sites: dict[str, list[tuple[frozenset, tuple[str, ...]]]] = {}
        self._spawns: list[ThreadRoot] = []
        for qn, info in self.graph.functions.items():
            self._analyze_function(qn, info)
        # interprocedural entry-held fixpoint --------------------------------
        self.entry_held: dict[str, frozenset] = {}
        self._fixpoint()
        # lock-order digraph -------------------------------------------------
        # (held_lock -> acquired_lock) -> first witness LockSite
        self.lock_order: dict[tuple[str, str], LockSite] = {}
        self.reacquisitions: list[LockSite] = []
        self._build_lock_order()
        # thread roots + reachability ----------------------------------------
        self.roots: list[ThreadRoot] = self._assemble_roots()
        self.function_roots: dict[str, set[str]] = self._build_root_reach()

    # -- class hierarchy ----------------------------------------------------

    def _collect_classes(self) -> None:
        for module in self.package.modules:
            for cls in ast.walk(module.tree):
                if not isinstance(cls, ast.ClassDef):
                    continue
                self._class_names.add(cls.name)
                for base in cls.bases:
                    name = base.attr if isinstance(base, ast.Attribute) else (
                        base.id if isinstance(base, ast.Name) else ""
                    )
                    if name:
                        self._parents.setdefault(cls.name, name)
        # second pass: lock attributes, keyed by FAMILY root so base and
        # subclass methods agree on lock identity
        for module in self.package.modules:
            for cls in ast.walk(module.tree):
                if not isinstance(cls, ast.ClassDef):
                    continue
                fam = self.family(cls.name)
                for node in ast.walk(cls):
                    if (
                        isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)
                        and call_name(node.value) in _LOCK_FACTORIES
                    ):
                        for tgt in node.targets:
                            if (
                                isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"
                            ):
                                self.lock_attrs[fam][tgt.attr] = call_name(
                                    node.value
                                )
                    elif isinstance(node, ast.With):
                        for item in node.items:
                            ctx = item.context_expr
                            if (
                                isinstance(ctx, ast.Attribute)
                                and isinstance(ctx.value, ast.Name)
                                and ctx.value.id == "self"
                                and (
                                    "lock" in ctx.attr.lower()
                                    or ctx.attr.endswith("_cv")
                                )
                            ):
                                self.lock_attrs[fam].setdefault(ctx.attr, "")

    def family(self, class_name: str) -> str:
        """Root of the (package-local, name-based) base-class chain —
        the scope locks are identified under."""
        seen = set()
        cur = class_name
        while cur in self._parents and cur not in seen:
            seen.add(cur)
            parent = self._parents[cur]
            if parent not in self._class_names:
                break
            cur = parent
        return cur

    def _build_overrides(self) -> dict[str, set[str]]:
        """base-method qualname -> subclass override qualnames. Used to
        widen reachability: a base-class ``self._run_group()`` call may
        dispatch to the subclass override at runtime."""
        # class -> {method name -> qualname}
        by_class: dict[str, dict[str, str]] = collections.defaultdict(dict)
        for qn, info in self.graph.functions.items():
            if info.class_name:
                by_class[info.class_name][info.node.name] = qn
        out: dict[str, set[str]] = collections.defaultdict(set)
        for cls, methods in by_class.items():
            ancestor = self._parents.get(cls)
            seen = set()
            while ancestor and ancestor not in seen:
                seen.add(ancestor)
                for name, qn in methods.items():
                    base_qn = by_class.get(ancestor, {}).get(name)
                    if base_qn and base_qn != qn:
                        out[base_qn].add(qn)
                ancestor = self._parents.get(ancestor)
        return dict(out)

    # -- per-function local analysis ----------------------------------------

    def _class_of(self, qualname: str, info) -> str:
        """Owning class of a function, including closures nested in
        methods (their ``self`` is the method's) — the callgraph only
        records class_name for direct methods."""
        if info.class_name:
            return info.class_name
        for part in reversed(qualname.split(".")):
            if part in self._class_names:
                return part
        return ""

    def lock_id(self, class_name: str, attr: str) -> str | None:
        """Lock identity of ``self.<attr>`` seen from ``class_name``, or
        None when the attribute is not a known lock."""
        if not class_name:
            return None
        fam = self.family(class_name)
        if attr in self.lock_attrs.get(fam, {}) or (
            "lock" in attr.lower() or attr.endswith("_cv")
        ):
            return f"{fam}.{attr}"
        return None

    def reentrant(self, lock: str) -> bool:
        fam, _, attr = lock.rpartition(".")
        return self.lock_attrs.get(fam, {}).get(attr, "") in _REENTRANT_FACTORIES

    def _analyze_function(self, qn: str, info) -> None:
        cls = self._class_of(qn, info)
        module = info.module

        def lock_of(expr: ast.AST) -> str | None:
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
            ):
                return self.lock_id(cls, expr.attr)
            return None

        method = info.node.name
        exempt = method in _EXEMPT_METHODS
        fam = self.family(cls) if cls else ""
        sites: list[tuple[frozenset, tuple[str, ...]]] = []
        for node, held in walk_held(info.node, lock_of):
            if isinstance(node, ast.With):
                for item in node.items:
                    lid = lock_of(item.context_expr)
                    if lid:
                        self.acquisitions.append(
                            LockSite(lid, held, module, node, qn)
                        )
            elif isinstance(node, ast.Call):
                targets = self.graph.resolve_call(
                    module, node, info.class_name or cls, owner=qn
                )
                if targets:
                    sites.append((held, tuple(sorted(targets))))
                self._spawn_of(node, module, qn, cls)
            if fam and not exempt:
                for attr, site in _mutations(node):
                    if attr in self.lock_attrs.get(fam, {}):
                        continue
                    self.mutations[(fam, attr)].append(
                        MutationSite(fam, attr, held, module, site, qn, method)
                    )
        if sites:
            self._call_sites[qn] = sites

    def _spawn_of(
        self, call: ast.Call, module: Module, owner: str, cls: str
    ) -> None:
        """Record a thread root if ``call`` hands a package function to
        another thread of execution."""
        name = call_name(call)
        kind = None
        target: ast.AST | None = None
        if name in _THREAD_CTORS:
            kind = "thread"
            for kw in call.keywords:
                if kw.arg == "target":
                    target = kw.value
        elif name in _TIMER_CTORS:
            kind = "timer"
            if len(call.args) >= 2:
                target = call.args[1]
            for kw in call.keywords:
                if kw.arg == "function":
                    target = kw.value
        elif name == "signal.signal":
            kind = "signal"
            if len(call.args) >= 2:
                target = call.args[1]
        elif isinstance(call.func, ast.Attribute):
            if call.func.attr == "submit" and call.args:
                kind = "executor"
                target = call.args[0]
            elif call.func.attr == "add_done_callback" and call.args:
                kind = "callback"
                target = call.args[0]
        if kind is None or target is None:
            return
        for qn in self._resolve_target(target, module, owner, cls):
            self._spawns.append(
                ThreadRoot(
                    group=qn,
                    kind=kind,
                    pattern=qn,
                    where=f"{module.relpath}:{getattr(call, 'lineno', 0)}",
                )
            )

    def _resolve_target(
        self, expr: ast.AST, module: Module, owner: str, cls: str
    ) -> set[str]:
        """Qualnames a spawn-target expression may name: ``self._loop``,
        a nested closure, a module function, an import."""
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
        ):
            fake = ast.Call(func=expr, args=[], keywords=[])
            return self.graph.resolve_call(module, fake, cls, owner=owner)
        if isinstance(expr, ast.Name):
            fake = ast.Call(
                func=ast.Name(id=expr.id, ctx=ast.Load()), args=[], keywords=[]
            )
            # walk the owner chain so a closure two defs deep resolves
            targets: set[str] = set()
            parts = owner.split(".")
            for i in range(len(parts), 0, -1):
                cand = ".".join(parts[:i] + [expr.id])
                if cand in self.graph.functions:
                    targets.add(cand)
                    break
            targets |= self.graph.resolve_call(module, fake, cls, owner=owner)
            return targets
        return set()

    # -- interprocedural propagation ----------------------------------------

    def _fixpoint(self) -> None:
        """Union-over-callers entry-held sets. Monotone (sets only
        grow), so iterate to fixpoint; the union direction means "some
        caller holds L here", which SUPPRESSES race findings (an access
        might be protected) and ADDS lock-order edges (a path exists on
        which L is held) — both the safe over-approximation for a
        linter that must not invent races and must not miss cycles."""
        changed = True
        while changed:
            changed = False
            for fn, sites in self._call_sites.items():
                base = self.entry_held.get(fn, frozenset())
                for local_held, targets in sites:
                    h = base | local_held
                    if not h:
                        continue
                    for t in targets:
                        # a call resolved to a base method may execute a
                        # subclass override at runtime: the override's
                        # callers hold the same locks
                        for callee in (t, *self._overrides.get(t, ())):
                            cur = self.entry_held.get(callee, frozenset())
                            if not h <= cur:
                                self.entry_held[callee] = cur | h
                                changed = True

    def held_at(self, site) -> frozenset:
        """Full held set at a LockSite/MutationSite: lexical plus
        propagated entry-held locks of the enclosing function."""
        return site.local_held | self.entry_held.get(site.function, frozenset())

    def _build_lock_order(self) -> None:
        for acq in self.acquisitions:
            held = self.held_at(acq)
            for h in held:
                if h == acq.lock:
                    if not self.reentrant(acq.lock):
                        self.reacquisitions.append(acq)
                else:
                    self.lock_order.setdefault((h, acq.lock), acq)

    def lock_cycles(self) -> list[tuple[tuple[str, ...], list[LockSite]]]:
        """Strongly-connected components of the lock-order digraph with
        more than one lock: each is a potential deadlock. Returns
        (sorted lock cycle, witness acquisition sites) pairs, sorted for
        deterministic output."""
        succ: dict[str, set[str]] = collections.defaultdict(set)
        for (a, b) in self.lock_order:
            succ[a].add(b)
        sccs = _tarjan(succ)
        out: list[tuple[tuple[str, ...], list[LockSite]]] = []
        for scc in sccs:
            if len(scc) < 2:
                continue
            cyc = tuple(sorted(scc))
            members = set(scc)
            witnesses = [
                site
                for (a, b), site in sorted(
                    self.lock_order.items(),
                    key=lambda kv: (kv[0][0], kv[0][1]),
                )
                if a in members and b in members
            ]
            out.append((cyc, witnesses))
        out.sort(key=lambda c: c[0])
        return out

    # -- thread roots -------------------------------------------------------

    def _assemble_roots(self) -> list[ThreadRoot]:
        roots: dict[tuple[str, str], ThreadRoot] = {}
        for pattern, group, why in DECLARED_THREAD_ROOTS:
            roots[(group, pattern)] = ThreadRoot(
                group=group, kind="declared", pattern=pattern, where="declared"
            )
        for spawn in self._spawns:
            roots.setdefault((spawn.group, spawn.pattern), spawn)
        return sorted(
            roots.values(), key=lambda r: (r.group, r.pattern, r.where)
        )

    def _reach(self, patterns: Iterable[str]) -> set[str]:
        """BFS closure over call edges PLUS subclass-override edges —
        the dispatcher calling ``self._run_group()`` on the base class
        reaches every override a subclass instance would run."""
        seen = set(self.graph.match(patterns))
        extra = set()
        for qn in seen:
            extra |= self._overrides.get(qn, set())
        seen |= extra
        queue = collections.deque(seen)
        while queue:
            qn = queue.popleft()
            nxt = self.graph.edges.get(qn, set()) | self._overrides.get(
                qn, set()
            )
            for t in nxt:
                if t not in seen:
                    seen.add(t)
                    queue.append(t)
        return seen

    def _build_root_reach(self) -> dict[str, set[str]]:
        by_group: dict[str, set[str]] = collections.defaultdict(set)
        for root in self.roots:
            by_group[root.group].add(root.pattern)
        out: dict[str, set[str]] = collections.defaultdict(set)
        for group, patterns in by_group.items():
            for qn in self._reach(patterns):
                out[qn].add(group)
        return dict(out)

    def roots_reaching(self, qualname: str) -> set[str]:
        """Distinct thread-root groups that can execute ``qualname``."""
        return self.function_roots.get(qualname, set())


# -- shared AST helpers ------------------------------------------------------


_MUTATING_METHODS = {
    "append",
    "appendleft",
    "extend",
    "extendleft",
    "pop",
    "popleft",
    "popitem",
    "add",
    "insert",
    "remove",
    "discard",
    "clear",
    "update",
    "setdefault",
    "put",
    "put_nowait",
}


def _self_attr_of_target(tgt: ast.AST) -> str | None:
    """`self.x = ...` -> x; `self.x[k] = / += ...` -> x (subscript
    stores mutate the container the attribute holds)."""
    node = tgt
    while isinstance(node, ast.Subscript):
        node = node.value
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _mutations(node: ast.AST) -> Iterator[tuple[str, ast.AST]]:
    """(attr, site) for every self-attribute mutation AT ``node`` (not
    recursing — callers drive this from a flow walk that visits every
    node exactly once)."""
    if isinstance(node, ast.Assign):
        for tgt in node.targets:
            attr = _self_attr_of_target(tgt)
            if attr:
                yield attr, node
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        attr = _self_attr_of_target(node.target)
        if attr:
            yield attr, node
    elif isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _MUTATING_METHODS:
            attr = _self_attr_of_target(f.value)
            if attr:
                yield attr, node


def _tarjan(succ: dict[str, set[str]]) -> list[list[str]]:
    """Tarjan SCC, iterative (the lock graph is tiny, but recursion
    depth should not depend on analyzed code shape)."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    counter = [0]
    sccs: list[list[str]] = []
    nodes = set(succ)
    for targets in succ.values():
        nodes |= targets

    for start in sorted(nodes):
        if start in index:
            continue
        work: list[tuple[str, Iterator[str]]] = [
            (start, iter(sorted(succ.get(start, ()))))
        ]
        index[start] = low[start] = counter[0]
        counter[0] += 1
        stack.append(start)
        on_stack.add(start)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(succ.get(nxt, ())))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc: list[str] = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                sccs.append(scc)
    return sccs
