"""Static per-call kernel models for ``pl.pallas_call`` sites (TPL8xx).

tpulint's first seven families stop at the ``pallas_call`` boundary:
they can see a host sync *around* a kernel launch but nothing about the
launch itself. The bugs that live inside the boundary — a block shape
that pads 128x in VMEM, a working set past the per-core VMEM limit, a
grid the caller can starve, an async copy started and never waited —
are silent under ``interpret=True`` on CPU and only surface as wrong
answers or Mosaic errors on real hardware. This module recovers enough
of each call site from the AST for the TPL8xx rules to reason about:

  * the grid (``grid=`` or a ``PrefetchScalarGridSpec``), including
    ``num_scalar_prefetch``;
  * every ``BlockSpec``: block shape, memory space, index-map presence;
  * ``out_shape`` ShapeDtypeStructs (shape + dtype);
  * scratch allocations — both ``scratch_shapes=[pltpu.VMEM(...)]`` at
    the call and ``pl.run_scoped(..., name=pltpu.VMEM(...))`` inside
    the kernel body (partial-bound constants resolved);
  * ``interpret=`` plumbing (parameter-plumbed vs constant vs absent);
  * the enclosing ``jax.named_scope`` strings (the fused-route anchor);
  * the kernel function(s) the call launches, through
    ``functools.partial`` and branch-local ``kernel = ...`` rebinding.

Extraction is best-effort by design: dimensions fold to ``int`` only
when they reduce to module/wrapper-local integer constants (``_LANES``,
``POINT_BLOCK``, ``a // b`` of constants...); anything data-dependent
(``k_pad = _round_up(k, 128)`` over a runtime ``k``) folds to ``None``
and the rules skip it — a lint must not guess. The same conservatism
governs the DMA walk: ``pl.when``-decorated bodies are conditional,
loop bodies are assumed to execute at least once (the double-buffer
schedules this package ships always run >= 1 block).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable, Iterator

from triton_client_tpu.analysis.engine import (
    Module,
    call_name,
    dotted_name,
    qualname_contexts,
)

#: dtype name (the suffix of ``jnp.float32`` etc.) -> itemsize bytes.
DTYPE_BYTES = {
    "float64": 8, "int64": 8, "uint64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool_": 1, "bool": 1,
}

#: dtype -> minimum sublane multiple of the native TPU tile
#: (sublanes x 128 lanes): f32 packs 8 sublanes, 2-byte types 16,
#: 1-byte types 32 (see the Pallas TPU tiling tables).
DTYPE_SUBLANES = {1: 32, 2: 16, 4: 8, 8: 8}


def dtype_name(node: ast.AST) -> str | None:
    """``jnp.float32`` / ``np.int8`` -> 'float32' / 'int8'."""
    name = dotted_name(node)
    tail = name.rsplit(".", 1)[-1] if name else ""
    return tail if tail in DTYPE_BYTES else None


def itemsize(dtype: str | None, default: int = 4) -> int:
    return DTYPE_BYTES.get(dtype or "", default)


def sublane_multiple(dtype: str | None) -> int:
    return DTYPE_SUBLANES.get(itemsize(dtype), 8)


# -- constant folding --------------------------------------------------------


def fold_int(node: ast.AST | None, env: dict[str, int | None]) -> int | None:
    """Best-effort integer fold of ``node`` under ``env``; ``None`` when
    anything non-constant participates."""
    if node is None:
        return None
    if isinstance(node, ast.Constant):
        return node.value if isinstance(node.value, int) and not isinstance(
            node.value, bool
        ) else None
    if isinstance(node, ast.Name):
        return env.get(node.id)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = fold_int(node.operand, env)
        return -v if v is not None else None
    if isinstance(node, ast.BinOp):
        left = fold_int(node.left, env)
        right = fold_int(node.right, env)
        if left is None or right is None:
            return None
        try:
            if isinstance(node.op, ast.Add):
                return left + right
            if isinstance(node.op, ast.Sub):
                return left - right
            if isinstance(node.op, ast.Mult):
                return left * right
            if isinstance(node.op, ast.FloorDiv):
                return left // right
            if isinstance(node.op, ast.Mod):
                return left % right
            if isinstance(node.op, ast.LShift):
                return left << right
            if isinstance(node.op, ast.Pow):
                return left ** right
        except (ZeroDivisionError, OverflowError, ValueError):
            return None
        return None
    if isinstance(node, ast.Call) and call_name(node) in ("max", "min"):
        vals = [fold_int(a, env) for a in node.args]
        if vals and all(v is not None for v in vals):
            return max(vals) if call_name(node) == "max" else min(vals)
    return None


def fold_shape(
    node: ast.AST | None, env: dict[str, int | None]
) -> tuple[int | None, ...] | None:
    """A ``(a, b, ...)`` tuple/list expression -> per-dim ints (None for
    dims that don't fold); None when the node isn't a shape literal."""
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(fold_int(el, env) for el in node.elts)
    return None


def module_const_env(module: Module) -> dict[str, int | None]:
    """Module-level ``NAME = <int expr>`` constants; a second pass folds
    constants defined in terms of earlier ones (``_WINDOW = POINT_BLOCK
    + _LANES``)."""
    env: dict[str, int | None] = {}
    for _ in range(2):
        for stmt in module.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                t = stmt.targets[0]
                if isinstance(t, ast.Name):
                    v = fold_int(stmt.value, env)
                    if v is not None:
                        env[t.id] = v
    return env


def function_env(
    fn: ast.FunctionDef | ast.AsyncFunctionDef, base: dict[str, int | None]
) -> dict[str, int | None]:
    """``base`` extended with the function's own foldable straight-line
    assignments (nested defs excluded — they run elsewhere)."""
    env = dict(base)

    def walk(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.Assign) and len(child.targets) == 1:
                t = child.targets[0]
                if isinstance(t, ast.Name):
                    v = fold_int(child.value, env)
                    if v is not None:
                        env[t.id] = v
            walk(child)

    walk(fn)
    return env


# -- per-call models ---------------------------------------------------------


@dataclasses.dataclass
class BlockModel:
    """One ``BlockSpec``: role 'in'|'out', ``shape`` per-dim ints (None
    for unfoldable dims) or None when blockless (whole-operand),
    ``memory_space`` 'vmem'|'smem'|'any'."""

    role: str
    shape: tuple[int | None, ...] | None
    memory_space: str
    has_index_map: bool
    node: ast.AST


@dataclasses.dataclass
class ScratchModel:
    """One scratch allocation: ``kind`` 'scratch_shapes'|'run_scoped'|
    'semaphore'; semaphores carry no shape/bytes."""

    kind: str
    shape: tuple[int | None, ...] | None
    dtype: str | None
    node: ast.AST


@dataclasses.dataclass
class KernelModel:
    """Everything statically known about one ``pl.pallas_call`` site
    (one model per resolvable kernel/grid-spec branch variant)."""

    module: Module
    call: ast.Call
    wrapper: ast.FunctionDef | None
    wrapper_name: str
    kernel_names: tuple[str, ...]
    kernel_fn: ast.FunctionDef | None
    grid: tuple[int | None, ...] | None
    num_scalar_prefetch: int
    in_blocks: list[BlockModel]
    out_blocks: list[BlockModel]
    out_shapes: list[tuple[tuple[int | None, ...] | None, str | None]]
    scratch: list[ScratchModel]
    interpret: str  # 'plumbed' | 'const' | 'missing'
    named_scopes: tuple[str, ...]

    @property
    def gridded(self) -> bool:
        return bool(self.grid)


# -- BlockSpec / scratch / out_shape parsing --------------------------------


_SPACE_SUFFIX = {"VMEM": "vmem", "SMEM": "smem", "ANY": "any"}


def _parse_blockspec(
    node: ast.AST, env: dict[str, int | None], role: str
) -> BlockModel | None:
    if not isinstance(node, ast.Call):
        return None
    name = call_name(node)
    if not name.endswith("BlockSpec"):
        return None
    shape = fold_shape(node.args[0], env) if node.args else None
    has_map = len(node.args) > 1
    space = "vmem"
    for kw in node.keywords:
        if kw.arg == "index_map":
            has_map = True
        elif kw.arg == "block_shape":
            shape = fold_shape(kw.value, env)
        elif kw.arg == "memory_space":
            tail = dotted_name(kw.value).rsplit(".", 1)[-1]
            space = _SPACE_SUFFIX.get(tail, "vmem")
    return BlockModel(role=role, shape=shape, memory_space=space,
                      has_index_map=has_map, node=node)


def _parse_spec_list(
    node: ast.AST | None, env: dict[str, int | None], role: str,
    wrapper: ast.FunctionDef | None,
) -> list[BlockModel]:
    """in_specs/out_specs expression -> BlockModels. Handles a list or
    tuple of specs, a bare spec, ``[spec] * k`` replication, and a Name
    bound earlier in the wrapper."""
    if node is None:
        return []
    if isinstance(node, ast.Name) and wrapper is not None:
        cands = _assignments_of(wrapper, node.id)
        if cands:
            node = cands[-1][0]
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult):
        seq, count = node.left, fold_int(node.right, env)
        if not isinstance(seq, (ast.List, ast.Tuple)):
            seq, count = node.right, fold_int(node.left, env)
        if isinstance(seq, (ast.List, ast.Tuple)) and count:
            base = [
                b for el in seq.elts
                if (b := _parse_blockspec(el, env, role)) is not None
            ]
            return base * count
        return []
    if isinstance(node, (ast.List, ast.Tuple)):
        return [
            b for el in node.elts
            if (b := _parse_blockspec(el, env, role)) is not None
        ]
    one = _parse_blockspec(node, env, role)
    return [one] if one else []


def _parse_scratch_entry(
    node: ast.AST, env: dict[str, int | None], kind: str
) -> ScratchModel | None:
    if not isinstance(node, ast.Call):
        return None
    name = call_name(node)
    tail = name.rsplit(".", 1)[-1]
    if "SemaphoreType" in name or tail == "DMA":
        return ScratchModel(kind="semaphore", shape=None, dtype=None,
                            node=node)
    if tail in ("VMEM", "SMEM"):
        shape = fold_shape(node.args[0], env) if node.args else None
        dtype = dtype_name(node.args[1]) if len(node.args) > 1 else None
        return ScratchModel(kind=kind, shape=shape, dtype=dtype, node=node)
    return None


def _parse_out_shapes(
    node: ast.AST | None, env: dict[str, int | None]
) -> list[tuple[tuple[int | None, ...] | None, str | None]]:
    if node is None:
        return []
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            out.extend(_parse_out_shapes(el, env))
        return out
    if isinstance(node, ast.Call) and call_name(node).endswith(
        "ShapeDtypeStruct"
    ):
        shape = fold_shape(node.args[0], env) if node.args else None
        dtype = dtype_name(node.args[1]) if len(node.args) > 1 else None
        return [(shape, dtype)]
    return []


# -- branch-aware local resolution ------------------------------------------


def _assignments_of(
    fn: ast.AST, name: str
) -> list[tuple[ast.AST, tuple | None]]:
    """(value, branch_key) for every ``name = ...`` in ``fn`` (nested
    defs excluded). ``branch_key`` identifies the innermost if/else arm
    so ``kernel``/``grid_spec`` pairs rebound together in matching arms
    stay paired."""
    out: list[tuple[ast.AST, tuple | None]] = []

    def walk(node: ast.AST, branch: tuple | None) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.If):
                for stmt in child.body:
                    walk_stmt(stmt, (id(child), "body"))
                for stmt in child.orelse:
                    walk_stmt(stmt, (id(child), "orelse"))
                continue
            walk_stmt(child, branch)

    def walk_stmt(child: ast.AST, branch: tuple | None) -> None:
        if isinstance(child, ast.Assign) and len(child.targets) == 1:
            t = child.targets[0]
            if isinstance(t, ast.Name) and t.id == name:
                out.append((child.value, branch))
        walk(child, branch)

    walk(fn, None)
    return out


def _variants(
    wrapper: ast.FunctionDef | None, call: ast.Call
) -> list[tuple[ast.AST | None, ast.AST | None]]:
    """(kernel_expr, grid_spec_expr) per branch variant of the call —
    a Name argument expands to its branch-local assignments, paired by
    branch arm (the ``if pipeline == "manual"`` pattern)."""
    kernel_expr = call.args[0] if call.args else None
    spec_expr = next(
        (kw.value for kw in call.keywords if kw.arg == "grid_spec"), None
    )

    def expand(expr):
        if isinstance(expr, ast.Name) and wrapper is not None:
            cands = _assignments_of(wrapper, expr.id)
            if cands:
                return cands
        return [(expr, None)]

    kernels = expand(kernel_expr)
    specs = expand(spec_expr)
    branches = sorted(
        {b for _, b in kernels + specs if b is not None},
        key=lambda b: (b[0], b[1]),
    )
    if not branches:
        return [(kernels[-1][0], specs[-1][0])]

    def pick(cands, branch):
        for v, b in reversed(cands):
            if b == branch:
                return v
        for v, b in reversed(cands):
            if b is None:
                return v
        return cands[-1][0]

    return [(pick(kernels, b), pick(specs, b)) for b in branches]


# -- kernel resolution -------------------------------------------------------


def _resolve_kernel(
    expr: ast.AST | None, module: Module, env: dict[str, int | None]
) -> tuple[tuple[str, ...], ast.FunctionDef | None, dict[str, int | None]]:
    """Kernel expression -> (names, module-level FunctionDef, extra env
    from foldable ``functools.partial`` keyword bindings)."""
    extra: dict[str, int | None] = {}
    names: tuple[str, ...] = ()
    if isinstance(expr, ast.Call) and call_name(expr).endswith("partial"):
        if expr.args:
            inner = dotted_name(expr.args[0])
            if inner:
                names = (inner,)
        for kw in expr.keywords:
            if kw.arg:
                extra[kw.arg] = fold_int(kw.value, env)
    elif isinstance(expr, (ast.Name, ast.Attribute)):
        n = dotted_name(expr)
        if n:
            names = (n,)
    fn = None
    if names:
        target = names[0].rsplit(".", 1)[-1]
        for stmt in module.tree.body:
            if isinstance(stmt, ast.FunctionDef) and stmt.name == target:
                fn = stmt
                break
    return names, fn, extra


def _run_scoped_scratch(
    kernel_fn: ast.FunctionDef, env: dict[str, int | None]
) -> list[ScratchModel]:
    out: list[ScratchModel] = []
    for node in ast.walk(kernel_fn):
        if isinstance(node, ast.Call) and call_name(node).endswith(
            "run_scoped"
        ):
            for kw in node.keywords:
                entry = _parse_scratch_entry(kw.value, env, "run_scoped")
                if entry is not None:
                    out.append(entry)
            for arg in node.args[1:]:
                entry = _parse_scratch_entry(arg, env, "run_scoped")
                if entry is not None:
                    out.append(entry)
    return out


# -- named scopes ------------------------------------------------------------


def _named_scopes_around(
    fn: ast.AST, call: ast.Call
) -> tuple[str, ...]:
    """Constant ``jax.named_scope("...")`` strings whose ``with`` body
    lexically contains ``call``."""
    scopes: list[str] = []
    line = call.lineno
    for node in ast.walk(fn):
        if not isinstance(node, ast.With):
            continue
        if not (node.lineno <= line <= getattr(node, "end_lineno", node.lineno)):
            continue
        for item in node.items:
            ctx = item.context_expr
            if (
                isinstance(ctx, ast.Call)
                and call_name(ctx).endswith("named_scope")
                and ctx.args
                and isinstance(ctx.args[0], ast.Constant)
                and isinstance(ctx.args[0].value, str)
            ):
                scopes.append(ctx.args[0].value)
    return tuple(scopes)


# -- extraction entry points -------------------------------------------------


def _is_pallas_call(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and call_name(node).rsplit(
        ".", 1
    )[-1] == "pallas_call"


def _enclosing_function(
    module: Module, node: ast.AST
) -> tuple[ast.FunctionDef | None, str]:
    best: ast.FunctionDef | None = None
    best_name = ""
    line = getattr(node, "lineno", 0)
    for def_node, name in qualname_contexts(module.tree).items():
        if not isinstance(def_node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if (
            def_node.lineno <= line
            and getattr(def_node, "end_lineno", def_node.lineno) >= line
            and (best is None or len(name) > len(best_name))
        ):
            best, best_name = def_node, name
    return best, best_name


def extract_models(module: Module) -> list[KernelModel]:
    """Every ``pl.pallas_call`` site in ``module`` -> KernelModels (one
    per resolvable kernel/grid-spec branch variant)."""
    env_mod = module_const_env(module)
    models: list[KernelModel] = []
    for node in ast.walk(module.tree):
        if not _is_pallas_call(node):
            continue
        wrapper, wrapper_name = _enclosing_function(module, node)
        env = (
            function_env(wrapper, env_mod) if wrapper is not None else env_mod
        )
        kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}

        interp = "missing"
        if "interpret" in kwargs:
            interp = (
                "const"
                if isinstance(kwargs["interpret"], ast.Constant)
                else "plumbed"
            )

        scopes = (
            _named_scopes_around(wrapper, node) if wrapper is not None else ()
        )
        out_shapes = _parse_out_shapes(kwargs.get("out_shape"), env)
        scratch_call = [
            s
            for el in (
                kwargs["scratch_shapes"].elts
                if isinstance(
                    kwargs.get("scratch_shapes"), (ast.List, ast.Tuple)
                )
                else ()
            )
            if (s := _parse_scratch_entry(el, env, "scratch_shapes"))
            is not None
        ]

        for kernel_expr, spec_expr in _variants(wrapper, node):
            grid_node = kwargs.get("grid")
            in_specs_node = kwargs.get("in_specs")
            out_specs_node = kwargs.get("out_specs")
            num_prefetch = 0
            if isinstance(spec_expr, ast.Call):
                spec_kwargs = {
                    kw.arg: kw.value for kw in spec_expr.keywords if kw.arg
                }
                grid_node = spec_kwargs.get("grid", grid_node)
                in_specs_node = spec_kwargs.get("in_specs", in_specs_node)
                out_specs_node = spec_kwargs.get("out_specs", out_specs_node)
                num_prefetch = (
                    fold_int(spec_kwargs.get("num_scalar_prefetch"), env) or 0
                )
            grid = fold_shape(grid_node, env)
            if grid is None and grid_node is not None:
                v = fold_int(grid_node, env)
                grid = (v,) if v is not None else (None,)

            names, kernel_fn, partial_env = _resolve_kernel(
                kernel_expr, module, env
            )
            kenv = dict(env_mod)
            kenv.update({k: v for k, v in partial_env.items() if v is not None})
            scratch = list(scratch_call)
            if kernel_fn is not None:
                scratch.extend(_run_scoped_scratch(kernel_fn, kenv))

            models.append(
                KernelModel(
                    module=module,
                    call=node,
                    wrapper=wrapper,
                    wrapper_name=wrapper_name,
                    kernel_names=names,
                    kernel_fn=kernel_fn,
                    grid=grid,
                    num_scalar_prefetch=num_prefetch,
                    in_blocks=_parse_spec_list(
                        in_specs_node, env, "in", wrapper
                    ),
                    out_blocks=_parse_spec_list(
                        out_specs_node, env, "out", wrapper
                    ),
                    out_shapes=out_shapes,
                    scratch=scratch,
                    interpret=interp,
                    named_scopes=scopes,
                )
            )
    return models


class PallasIndex:
    """Package-wide lazy index of every pallas_call model, built once
    and shared by the TPL8xx rules (the ``Package.pallas`` facility,
    same contract as ``Package.callgraph``/``Package.threads``)."""

    def __init__(self, package) -> None:
        self.models: list[KernelModel] = []
        for module in package.modules:
            try:
                self.models.extend(extract_models(module))
            except RecursionError:  # pathological nesting: skip, don't die
                continue

    def by_scope(self, scope: str) -> list[KernelModel]:
        return [m for m in self.models if scope in m.named_scopes]


# -- DMA discipline walk (TPL804 substrate) ----------------------------------


@dataclasses.dataclass
class DMAEvent:
    """One ``.start()``/``.wait()`` on an async-copy family. ``family``
    is the copy variable or factory-helper name; ``conditional`` means
    the event sits under ``pl.when`` or an ``if`` arm; ``signature`` is
    the textual identity of the copy's construction (slot/index args)
    for duplicate-start detection."""

    family: str
    kind: str  # 'start' | 'wait'
    conditional: bool
    signature: str
    node: ast.AST


def _contains_make_async_copy(node: ast.AST) -> bool:
    return any(
        isinstance(n, ast.Call)
        and call_name(n).rsplit(".", 1)[-1] in (
            "make_async_copy", "make_async_remote_copy"
        )
        for n in ast.walk(node)
    )


def _is_when_decorated(fn: ast.FunctionDef) -> bool:
    return any(
        isinstance(d, ast.Call) and call_name(d).rsplit(".", 1)[-1] == "when"
        for d in fn.decorator_list
    )


def dma_events(fn: ast.FunctionDef) -> list[DMAEvent]:
    """Linear, flow-classified start/wait event stream for every
    async-copy family lexically inside ``fn``.

    Families: a variable assigned from ``make_async_copy`` (family =
    the variable), a nested helper whose body constructs copies and is
    iterated via ``for c in helper(...)`` (family = the helper name —
    the manual double-buffer idiom), or a chained
    ``make_async_copy(...).start()`` (anonymous family, per line).
    ``pl.when``-decorated nested defs and ``if`` arms mark their events
    conditional; ``fori_loop``/``for``/``while`` bodies are treated as
    executing at least once (the schedules here always run >= 1 block —
    a deliberate, documented approximation)."""
    factories: set[str] = set()
    copy_vars: dict[str, str] = {}  # var -> construction signature
    for node in ast.walk(fn):
        if isinstance(node, ast.FunctionDef) and node is not fn:
            if _contains_make_async_copy(node):
                factories.add(node.name)

    events: list[DMAEvent] = []

    def sig_of(call: ast.Call) -> str:
        return ast.dump(call, annotate_fields=False)

    def classify_target(value: ast.AST) -> tuple[str, str] | None:
        """A call expression -> (family, signature) when it constructs
        or produces async copies."""
        if not isinstance(value, ast.Call):
            return None
        tail = call_name(value).rsplit(".", 1)[-1]
        if tail in ("make_async_copy", "make_async_remote_copy"):
            return "<inline>", sig_of(value)
        if tail in factories or call_name(value) in factories:
            return call_name(value).rsplit(".", 1)[-1], sig_of(value)
        return None

    def walk(node: ast.AST, cond: bool, loop_var_family: dict[str, tuple[str, str]]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.FunctionDef):
                if child.name in factories and not any(
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in ("start", "wait")
                    for n in ast.walk(child)
                ):
                    # pure factory helper: constructions are not events
                    continue
                walk(child, cond or _is_when_decorated(child),
                     dict(loop_var_family))
                continue
            if isinstance(child, ast.Lambda):
                continue
            if isinstance(child, ast.If):
                for stmt in child.body:
                    walk_stmt(stmt, True, loop_var_family)
                for stmt in child.orelse:
                    walk_stmt(stmt, True, loop_var_family)
                continue
            if isinstance(child, ast.For):
                fam = classify_target(child.iter)
                inner = dict(loop_var_family)
                if fam is not None and isinstance(child.target, ast.Name):
                    inner[child.target.id] = fam
                for stmt in child.body:
                    walk_stmt(stmt, cond, inner)
                continue
            walk_stmt(child, cond, loop_var_family)

    def walk_stmt(child: ast.AST, cond: bool,
                  loop_var_family: dict[str, tuple[str, str]]) -> None:
        if isinstance(child, ast.Assign) and len(child.targets) == 1:
            t = child.targets[0]
            fam = classify_target(child.value)
            if isinstance(t, ast.Name) and fam is not None:
                copy_vars[t.id] = fam[1]
        if (
            isinstance(child, ast.Call)
            and isinstance(child.func, ast.Attribute)
            and child.func.attr in ("start", "wait")
        ):
            base = child.func.value
            fam_sig: tuple[str, str] | None = None
            if isinstance(base, ast.Name):
                if base.id in loop_var_family:
                    fam_sig = loop_var_family[base.id]
                elif base.id in copy_vars:
                    fam_sig = (base.id, copy_vars[base.id])
            else:
                fam_sig = classify_target(base)
                if fam_sig is not None and fam_sig[0] == "<inline>":
                    fam_sig = (f"<inline>:{child.lineno}", fam_sig[1])
            if fam_sig is not None:
                events.append(
                    DMAEvent(
                        family=fam_sig[0],
                        kind=child.func.attr,
                        conditional=cond,
                        signature=fam_sig[1],
                        node=child,
                    )
                )
        walk(child, cond, loop_var_family)

    walk(fn, False, {})
    return events


def functions_with_dma(module: Module) -> Iterator[ast.FunctionDef]:
    """Top-level (and method-level) functions whose subtree constructs
    async copies — the TPL804 scan set. Nested defs are analyzed as
    part of their encloser, so only outermost defs are yielded."""

    def outermost(body: Iterable[ast.stmt]) -> Iterator[ast.FunctionDef]:
        for stmt in body:
            if isinstance(stmt, ast.FunctionDef):
                yield stmt
            elif isinstance(stmt, ast.ClassDef):
                yield from outermost(stmt.body)

    for fn in outermost(module.tree.body):
        if _contains_make_async_copy(fn):
            yield fn
