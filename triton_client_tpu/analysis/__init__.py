"""tpulint: AST-based hazard analysis for the JAX serving stack.

The fast serving constructs PRs 1–2 introduced (buffer donation,
overlapped dispatch, cross-thread batching, request spans) each come
with a failure mode that is invisible to CPU-only tests and shows up
only as a production perf/correctness regression: use-after-donation,
silent retraces, host syncs inside the overlap window, unguarded
shared counters, unbalanced spans/gauges, cross-thread races, hidden
request-sized copies, mis-tiled or VMEM-oversubscribed Pallas kernels.
All are *structural* — visible in the syntax tree — so this package
lints for them at review time. Eight rule families:

  TPL1xx  recompilation hazards      TPL5xx  telemetry correctness
  TPL2xx  donation misuse            TPL6xx  whole-program concurrency
  TPL3xx  host sync on the hot path          (deadlock + race model,
  TPL4xx  lock discipline                     analysis/threads.py)
                                     TPL7xx  zero-copy / host path
  TPL8xx  Pallas kernel analysis (tiling/VMEM/DMA + fused-route
          contract; analysis/pallas_model.py)

Entry points: ``python -m triton_client_tpu lint`` (CLI, see
cli/tools.py), :func:`lint_paths` / :func:`lint_source` (library / test
fixtures), docs/LINTING.md (rule catalogue + baseline workflow).

stdlib-only by design: it must run on a bare TPU pod image.
"""

from __future__ import annotations

from triton_client_tpu.analysis.baseline import Baseline
from triton_client_tpu.analysis.engine import (
    Finding,
    Module,
    Package,
    Rule,
    load_package,
    load_source,
    registry,
    render_json,
    render_sarif,
    render_text,
    run_rules,
)


def lint_paths(paths, root=None, codes=None) -> list[Finding]:
    """Parse + analyze ``paths``; returns pragma-filtered findings."""
    return run_rules(load_package(paths, root=root), codes=codes)


def lint_source(source: str, path: str = "<string>", codes=None) -> list[Finding]:
    """Analyze one source snippet (the test-fixture entry point)."""
    return run_rules(load_source(source, path=path), codes=codes)


__all__ = [
    "Baseline",
    "Finding",
    "Module",
    "Package",
    "Rule",
    "lint_paths",
    "lint_source",
    "load_package",
    "load_source",
    "registry",
    "render_json",
    "render_sarif",
    "render_text",
    "run_rules",
]
