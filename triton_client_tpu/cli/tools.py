"""Dataset utility subcommands (the reference's tools/ scripts).

  pc-extract  — PointCloud2 topic of a bag -> numbered .npy point clouds
                (tools/pc_extractor.py:17-45; output feeds the 3D
                NpyPointCloudSource demo path).
  bag-stitch  — copy the first N messages (optionally per-topic) of a
                bag into a new bag: truncated fixture bags for tests
                (tools/bag_stitch.py:1-8).
  bag-info    — topics/types/counts of a bag (rosbag info equivalent,
                handy since TPU hosts have no ROS tooling).
  trace-dump  — pull the request-trace ring buffer off a serving
                process's telemetry port as Chrome-trace JSON
                (open in Perfetto / chrome://tracing).
"""

from __future__ import annotations

import argparse
import os

import numpy as np


def pc_extract(argv=None) -> None:
    p = argparse.ArgumentParser(description="bag -> .npy point clouds")
    p.add_argument("bag_file")
    p.add_argument("--pc-topic", default=None, help="default: first PointCloud2 topic")
    p.add_argument("-o", "--output", default="./extracted_clouds")
    p.add_argument("--limit", type=int, default=0)
    p.add_argument(
        "--intensity-scale",
        type=float,
        default=1.0,
        help="divide intensity by this (pc_extractor.py normalizes /255)",
    )
    args = p.parse_args(argv)

    from triton_client_tpu.io.bag_io import BagPointCloudSource

    os.makedirs(args.output, exist_ok=True)
    src = BagPointCloudSource(args.bag_file, topic=args.pc_topic, limit=args.limit)
    n = 0
    for i, frame in enumerate(src):
        pts = frame.data.copy()
        if args.intensity_scale != 1.0:
            pts[:, 3] /= args.intensity_scale
        np.save(os.path.join(args.output, f"{i:06d}.npy"), pts)
        n += 1
    print(f"extracted {n} point clouds from {src.topic} -> {args.output}")


def bag_stitch(argv=None) -> None:
    p = argparse.ArgumentParser(description="truncate/copy a bag")
    p.add_argument("in_bag")
    p.add_argument("out_bag")
    p.add_argument("-n", "--count", type=int, default=100, help="max messages")
    p.add_argument("--topics", nargs="*", default=None)
    args = p.parse_args(argv)

    from triton_client_tpu.io import rosbag as rb

    n = 0
    with rb.BagReader(args.in_bag) as r, rb.BagWriter(args.out_bag) as w:
        for topic, bm, t in r.read_messages(topics=args.topics, raw=True):
            if n >= args.count:
                break
            w.write(topic, bm, t=t)
            n += 1
    print(f"wrote {n} messages -> {args.out_bag}")


def bag_info(argv=None) -> None:
    p = argparse.ArgumentParser(description="bag topic/type/count summary")
    p.add_argument("bag_file")
    args = p.parse_args(argv)

    from triton_client_tpu.io import rosbag as rb

    counts: dict[str, int] = {}
    t0, t1 = None, None
    with rb.BagReader(args.bag_file) as r:
        for topic, _, t in r.read_messages(raw=True):
            counts[topic] = counts.get(topic, 0) + 1
            t0 = t if t0 is None else min(t0, t)
            t1 = t if t1 is None else max(t1, t)
        types = {c.topic: c.datatype for c in r.connections.values()}
    if t0 is not None:
        print(f"duration: {t1 - t0:.3f}s  messages: {sum(counts.values())}")
    for topic in sorted(counts):
        print(f"  {topic}  {types.get(topic, '?')}  {counts[topic]} msgs")


def trace_dump(argv=None) -> None:
    """Fetch recent request traces from a live server's telemetry port
    and write Chrome-trace JSON — the CLI face of the /traces handler
    (runtime server -> obs.TelemetryServer)."""
    p = argparse.ArgumentParser(
        description="dump recent request traces as Chrome-trace JSON"
    )
    p.add_argument(
        "--url", default="http://127.0.0.1:8002",
        help="telemetry endpoint of the serving process "
        "(serve --metrics-port)",
    )
    p.add_argument(
        "-n", "--count", type=int, default=0,
        help="most recent N traces (0 = everything buffered)",
    )
    p.add_argument(
        "-o", "--output", default="-",
        help="output file ('-' = stdout); load in Perfetto or "
        "chrome://tracing",
    )
    p.add_argument("--timeout", type=float, default=10.0)
    args = p.parse_args(argv)

    import json
    import sys
    import urllib.request

    url = args.url.rstrip("/") + "/traces"
    if args.count:
        url += f"?n={args.count}"
    with urllib.request.urlopen(url, timeout=args.timeout) as resp:
        doc = json.load(resp)
    events = doc.get("traceEvents")
    if events is None:
        raise SystemExit(f"{url} returned no traceEvents (not a trace dump?)")
    body = json.dumps(doc)
    if args.output == "-":
        print(body)
    else:
        with open(args.output, "w") as f:
            f.write(body)
        n_req = sum(
            1 for e in events if e.get("ph") == "X" and e.get("name") == "request"
        )
        print(
            f"wrote {n_req} request traces ({len(events)} events) -> "
            f"{args.output}", file=sys.stderr,
        )


def repo_index(argv=None) -> None:
    """List a model repository: local directory (parsed, not built) or a
    live server's RepositoryIndex over gRPC."""
    p = argparse.ArgumentParser(
        description="list model-repository contents (local dir or grpc:<addr>)"
    )
    p.add_argument("target", help="repository root dir or grpc:<host:port>")
    args = p.parse_args(argv)

    if args.target.startswith("grpc:"):
        from triton_client_tpu.channel.grpc_channel import GRPCChannel

        channel = GRPCChannel(args.target[len("grpc:"):])
        try:
            for name, version, state in channel.repository_index():
                print(f"{name}:{version}  {state}")
        finally:
            channel.close()
        return

    import pathlib

    from triton_client_tpu.dataset_config import load_yaml
    from triton_client_tpu.runtime.disk_repository import (
        find_weights,
        version_dirs,
    )

    root = pathlib.Path(args.target)
    if not root.is_dir():
        raise SystemExit(f"{args.target!r} is not a directory or grpc: address")
    for model_dir in sorted(d for d in root.iterdir() if d.is_dir()):
        cfg = model_dir / "config.yaml"
        if not cfg.exists():
            continue
        doc = load_yaml(str(cfg))
        versions = version_dirs(model_dir)
        if not versions:
            print(f"{model_dir.name}:1  family={doc.get('family')}  (fresh-init)")
        for vdir in versions:
            try:
                artifact = find_weights(vdir).name
            except FileNotFoundError:
                artifact = "MISSING WEIGHTS"
            print(
                f"{model_dir.name}:{vdir.name}  family={doc.get('family')}  "
                f"{artifact}"
            )
