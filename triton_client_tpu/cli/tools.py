"""Dataset utility subcommands (the reference's tools/ scripts).

  pc-extract  — PointCloud2 topic of a bag -> numbered .npy point clouds
                (tools/pc_extractor.py:17-45; output feeds the 3D
                NpyPointCloudSource demo path).
  bag-stitch  — copy the first N messages (optionally per-topic) of a
                bag into a new bag: truncated fixture bags for tests
                (tools/bag_stitch.py:1-8).
  bag-info    — topics/types/counts of a bag (rosbag info equivalent,
                handy since TPU hosts have no ROS tooling).
  trace-dump  — pull the request-trace ring buffer off a serving
                process's telemetry port as Chrome-trace JSON
                (open in Perfetto / chrome://tracing). ``--ops`` turns
                it into a per-op device-time report instead: summarize
                an offline jax.profiler capture (``--ops PATH``) or
                take a live capture through ``/profile`` (bare
                ``--ops``) and rank XLA ops by device time with their
                owning model (obs/opstats.py).
  roofline    — per-model roofline report: measured flops/bytes from
                XLA's cost model (spec.extra, recorded at first
                launch), arithmetic intensity vs the machine knee,
                compute-/bandwidth-bound class, attainable-fps ceiling
                next to the measured rate. Reads a live /snapshot URL
                or a bench.py results JSON.
  trace-join  — merge several Chrome-trace exports (client / router /
                replica trace-dump outputs) onto ONE timeline: each
                source becomes its own pid row, shifted by an explicit
                per-source clock offset or one estimated from a probe
                round-trip against the source's live telemetry port
                (the same NTP-midpoint split obs.trace.graft_span_summary
                applies per response).
  lint        — tpulint: AST hazard analysis of the serving stack
                (recompilation/donation/host-sync/lock/telemetry rules;
                docs/LINTING.md). The CI gate runs this before pytest.
  route       — probe a replica set: liveness/readiness/labels per
                endpoint, the operator view of FrontDoorRouter's
                rotation decision (runtime/router.py).
"""

from __future__ import annotations

import argparse
import os

import numpy as np


def pc_extract(argv=None) -> None:
    p = argparse.ArgumentParser(description="bag -> .npy point clouds")
    p.add_argument("bag_file")
    p.add_argument("--pc-topic", default=None, help="default: first PointCloud2 topic")
    p.add_argument("-o", "--output", default="./extracted_clouds")
    p.add_argument("--limit", type=int, default=0)
    p.add_argument(
        "--intensity-scale",
        type=float,
        default=1.0,
        help="divide intensity by this (pc_extractor.py normalizes /255)",
    )
    args = p.parse_args(argv)

    from triton_client_tpu.io.bag_io import BagPointCloudSource

    os.makedirs(args.output, exist_ok=True)
    src = BagPointCloudSource(args.bag_file, topic=args.pc_topic, limit=args.limit)
    n = 0
    for i, frame in enumerate(src):
        pts = frame.data.copy()
        if args.intensity_scale != 1.0:
            pts[:, 3] /= args.intensity_scale
        np.save(os.path.join(args.output, f"{i:06d}.npy"), pts)
        n += 1
    print(f"extracted {n} point clouds from {src.topic} -> {args.output}")


def bag_stitch(argv=None) -> None:
    p = argparse.ArgumentParser(description="truncate/copy a bag")
    p.add_argument("in_bag")
    p.add_argument("out_bag")
    p.add_argument("-n", "--count", type=int, default=100, help="max messages")
    p.add_argument("--topics", nargs="*", default=None)
    args = p.parse_args(argv)

    from triton_client_tpu.io import rosbag as rb

    n = 0
    with rb.BagReader(args.in_bag) as r, rb.BagWriter(args.out_bag) as w:
        for topic, bm, t in r.read_messages(topics=args.topics, raw=True):
            if n >= args.count:
                break
            w.write(topic, bm, t=t)
            n += 1
    print(f"wrote {n} messages -> {args.out_bag}")


def bag_info(argv=None) -> None:
    p = argparse.ArgumentParser(description="bag topic/type/count summary")
    p.add_argument("bag_file")
    args = p.parse_args(argv)

    from triton_client_tpu.io import rosbag as rb

    counts: dict[str, int] = {}
    t0, t1 = None, None
    with rb.BagReader(args.bag_file) as r:
        for topic, _, t in r.read_messages(raw=True):
            counts[topic] = counts.get(topic, 0) + 1
            t0 = t if t0 is None else min(t0, t)
            t1 = t if t1 is None else max(t1, t)
        types = {c.topic: c.datatype for c in r.connections.values()}
    if t0 is not None:
        print(f"duration: {t1 - t0:.3f}s  messages: {sum(counts.values())}")
    for topic in sorted(counts):
        print(f"  {topic}  {types.get(topic, '?')}  {counts[topic]} msgs")


def trace_dump(argv=None) -> None:
    """Fetch recent request traces from a live server's telemetry port
    and write Chrome-trace JSON — the CLI face of the /traces handler
    (runtime server -> obs.TelemetryServer)."""
    p = argparse.ArgumentParser(
        description="dump recent request traces as Chrome-trace JSON"
    )
    p.add_argument(
        "--url", default="http://127.0.0.1:8002",
        help="telemetry endpoint of the serving process "
        "(serve --metrics-port)",
    )
    p.add_argument(
        "-n", "--count", type=int, default=0,
        help="most recent N traces (0 = everything buffered)",
    )
    p.add_argument(
        "-o", "--output", default="-",
        help="output file ('-' = stdout); load in Perfetto or "
        "chrome://tracing",
    )
    p.add_argument("--timeout", type=float, default=10.0)
    p.add_argument(
        "--ops", nargs="?", const="", default=None, metavar="TRACE",
        help="per-op device-time report instead of a raw trace dump: "
        "with a PATH, summarize that jax.profiler capture (a profile "
        "dir or .trace.json[.gz] file) offline; bare --ops takes a "
        "live capture through <url>/profile first",
    )
    p.add_argument(
        "--seconds", type=float, default=1.0,
        help="live capture window for bare --ops (the /profile knob)",
    )
    p.add_argument(
        "--top-k", type=int, default=20,
        help="op rows to keep in the --ops report",
    )
    args = p.parse_args(argv)

    import json
    import sys
    import urllib.request

    if args.ops is not None:
        from triton_client_tpu.obs import opstats

        if args.ops:
            summary = opstats.summarize_profile_dir(
                args.ops, top_k=args.top_k
            )
        else:
            url = (
                args.url.rstrip("/")
                + f"/profile?seconds={args.seconds}&top_k={args.top_k}"
            )
            with urllib.request.urlopen(url, timeout=args.timeout + args.seconds) as resp:
                doc = json.load(resp)
            if "op_summary" not in doc:
                raise SystemExit(
                    f"{url} returned no op summary "
                    f"({doc.get('op_summary_error', 'unknown failure')})"
                )
            summary = doc["op_summary"]
        total_us = summary.get("total_op_time_us", 0.0) or 0.0
        print(
            f"{summary.get('op_count', 0)} distinct ops, "
            f"{total_us / 1e3:.3f} ms total device op time"
        )
        for model, us in sorted(
            (summary.get("models") or {}).items(), key=lambda kv: -kv[1]
        ):
            print(f"  {model}: {us / 1e3:.3f} ms")
        unattr = summary.get("unattributed_us", 0.0)
        if unattr:
            print(f"  (unattributed: {unattr / 1e3:.3f} ms)")
        hdr = f"{'model':<16} {'kind':<14} {'occ':>5} {'ms':>10} {'share':>7}  op"
        print(hdr)
        print("-" * len(hdr))
        for row in summary.get("ops") or []:
            print(
                f"{(row.get('model') or '-'):<16} "
                f"{row.get('kind', '?'):<14} "
                f"{row.get('occurrences', 0):>5} "
                f"{row.get('time_us', 0.0) / 1e3:>10.3f} "
                f"{row.get('share', 0.0):>6.1%}  "
                f"{row.get('op', '?')}"
            )
        if args.output != "-":
            with open(args.output, "w") as f:
                json.dump(summary, f, indent=2)
            print(f"wrote op summary -> {args.output}", file=sys.stderr)
        return

    url = args.url.rstrip("/") + "/traces"
    if args.count:
        url += f"?n={args.count}"
    with urllib.request.urlopen(url, timeout=args.timeout) as resp:
        doc = json.load(resp)
    events = doc.get("traceEvents")
    if events is None:
        raise SystemExit(f"{url} returned no traceEvents (not a trace dump?)")
    body = json.dumps(doc)
    if args.output == "-":
        print(body)
    else:
        with open(args.output, "w") as f:
            f.write(body)
        n_req = sum(
            1 for e in events if e.get("ph") == "X" and e.get("name") == "request"
        )
        print(
            f"wrote {n_req} request traces ({len(events)} events) -> "
            f"{args.output}", file=sys.stderr,
        )


def trace_join(argv=None) -> None:
    """Merge per-process Chrome-trace exports onto one fleet timeline.

    Each process's chrome_trace export rebases its own earliest trace
    to t=0 on its own perf_counter clock, so client, router and replica
    dumps of the SAME request land at unrelated timestamps. This joins
    them: every input file becomes a distinct pid (Perfetto renders one
    process track per source), with its events shifted by a per-source
    clock offset — explicit (``--offset``), or estimated as half the
    best-of-N probe round-trip against the source's live telemetry
    port (``--probe``), the single-round-trip midpoint estimate NTP
    uses and graft_span_summary applies per response."""
    p = argparse.ArgumentParser(
        description="join client/router/replica Chrome-trace dumps "
        "onto one timeline"
    )
    p.add_argument(
        "inputs", nargs="+", metavar="[NAME=]FILE",
        help="Chrome-trace JSON files (trace-dump output); NAME labels "
        "the source's process track (default: file basename)",
    )
    p.add_argument(
        "--offset", action="append", default=[], metavar="NAME=US",
        help="shift NAME's events by this many microseconds "
        "(repeatable; positive = later on the joined timeline)",
    )
    p.add_argument(
        "--probe", action="append", default=[], metavar="NAME=URL",
        help="estimate NAME's offset as half the best-of-3 HTTP probe "
        "round-trip against its telemetry URL (repeatable)",
    )
    p.add_argument(
        "-o", "--output", default="-",
        help="output file ('-' = stdout); load in Perfetto",
    )
    p.add_argument("--timeout", type=float, default=5.0)
    args = p.parse_args(argv)

    import json
    import sys
    import time as _time
    import urllib.request

    def parse_kv(items, what):
        out = {}
        for item in items:
            name, sep, value = item.partition("=")
            if not sep:
                raise SystemExit(f"--{what} wants NAME=VALUE, got {item!r}")
            out[name] = value
        return out

    offsets = {
        name: float(us) for name, us in parse_kv(args.offset, "offset").items()
    }
    for name, url in parse_kv(args.probe, "probe").items():
        best = None
        for _ in range(3):
            t0 = _time.perf_counter()
            try:
                with urllib.request.urlopen(url, timeout=args.timeout):
                    pass
            except Exception as e:
                raise SystemExit(f"probe against {url} failed: {e}")
            rtt = _time.perf_counter() - t0
            best = rtt if best is None else min(best, rtt)
        offsets[name] = offsets.get(name, 0.0) + best / 2.0 * 1e6
        print(
            f"probe {name}: rtt {best * 1e3:.3f} ms -> offset "
            f"{best / 2.0 * 1e3:.3f} ms", file=sys.stderr,
        )

    events: list[dict] = []
    for i, item in enumerate(args.inputs):
        name, sep, path = item.partition("=")
        if not sep:
            name, path = "", item
        if not name:
            name = os.path.splitext(os.path.basename(path))[0]
        with open(path) as f:
            doc = json.load(f)
        src = doc.get("traceEvents")
        if src is None:
            raise SystemExit(f"{path}: no traceEvents (not a trace dump?)")
        pid = i + 1
        shift = offsets.get(name, 0.0)
        events.append(
            {
                "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                "args": {"name": name},
            }
        )
        n = 0
        for ev in src:
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                continue  # replaced by the source-labelled one above
            ev = dict(ev)
            ev["pid"] = pid
            if "ts" in ev:
                ev["ts"] = round(ev["ts"] + shift, 3)
            events.append(ev)
            n += 1
        print(
            f"{name}: {n} events, offset {shift / 1e3:+.3f} ms",
            file=sys.stderr,
        )

    events.sort(key=lambda e: (e.get("ts", -1.0), e.get("pid", 0)))
    body = json.dumps({"traceEvents": events, "displayTimeUnit": "ms"})
    if args.output == "-":
        print(body)
    else:
        with open(args.output, "w") as f:
            f.write(body)
        print(
            f"wrote {len(events)} joined events -> {args.output}",
            file=sys.stderr,
        )


def roofline(argv=None) -> None:
    """Per-model roofline report: measured flops/bytes (XLA cost model,
    recorded into spec.extra at first launch), arithmetic intensity vs
    the machine knee, the binding ceiling, and the attainable-fps
    ceiling next to the measured rate. Reads a live server's /snapshot
    or a bench.py results JSON."""
    p = argparse.ArgumentParser(
        description="per-model roofline classification "
        "(compute- vs bandwidth-bound, attainable-fps ceiling)"
    )
    p.add_argument(
        "source", nargs="?", default="http://127.0.0.1:8002",
        help="telemetry URL of a serving process (reads /snapshot) or "
        "a bench.py results JSON file",
    )
    p.add_argument("--timeout", type=float, default=10.0)
    p.add_argument(
        "--json", action="store_true", help="machine-readable JSON output"
    )
    args = p.parse_args(argv)

    import json
    import urllib.request

    rows = []
    if os.path.exists(args.source):
        with open(args.source) as f:
            doc = json.load(f)
        # bench.py results: rows carry the roofline columns directly
        for r in doc.get("rows") or doc.get("results") or []:
            if not r.get("roofline_bound"):
                continue
            per_call = r.get("flops_per_call") or (
                (r.get("flops_per_frame") or 0.0) * 1
            )
            rows.append(
                {
                    "model": r.get("metric", "?"),
                    "precision": r.get("precision", "f32"),
                    "flops": per_call,
                    "bytes": r.get("bytes_per_call")
                    or r.get("bytes_per_frame") or 0.0,
                    "intensity": r.get("arithmetic_intensity", 0.0),
                    "bound": r.get("roofline_bound", "unknown"),
                    "attainable_fps": r.get("attainable_fps", 0.0),
                    "measured_fps": r.get("value"),
                    "attained_fraction": r.get("roofline_attained_ratio"),
                }
            )
    else:
        url = args.source.rstrip("/") + "/snapshot"
        with urllib.request.urlopen(url, timeout=args.timeout) as resp:
            snap = json.load(resp)
        for m in snap.get("models") or []:
            roof = m.get("roofline")
            if not roof:
                continue
            rows.append(
                {
                    "model": f"{m['model']}:{m['version']}",
                    "precision": roof.get("precision", "f32"),
                    "flops": roof.get("flops", 0.0),
                    "bytes": roof.get("bytes", 0.0),
                    "intensity": roof.get("intensity", 0.0),
                    "bound": roof.get("bound", "unknown"),
                    "attainable_fps": roof.get("attainable_fps", 0.0),
                    "measured_fps": roof.get("measured_fps"),
                    "attained_fraction": roof.get("attained_fraction"),
                }
            )
    if args.json:
        print(json.dumps({"rows": rows}, indent=2))
        return
    if not rows:
        raise SystemExit(
            "no roofline rows: models record measured flops/bytes at "
            "their first launch (serve a request, then retry), and "
            "bench JSON needs the roofline columns (rerun bench.py)"
        )
    hdr = (
        f"{'model':<40} {'prec':<6} {'GF/call':>9} {'MB/call':>9} "
        f"{'flop/B':>8} {'bound':<10} {'ceiling fps':>12} {'attained':>9}"
    )
    print(hdr)
    print("-" * len(hdr))
    for r in rows:
        attained = (
            f"{r['attained_fraction']:.1%}"
            if r.get("attained_fraction") is not None else "-"
        )
        print(
            f"{r['model']:<40} {r['precision']:<6} "
            f"{r['flops'] / 1e9:>9.2f} {r['bytes'] / 1e6:>9.2f} "
            f"{r['intensity']:>8.1f} {r['bound']:<10} "
            f"{r['attainable_fps']:>12.1f} {attained:>9}"
        )


def lint(argv=None) -> None:
    """tpulint CLI: run the TPL rule families over the package (or the
    given paths), apply the baseline, print text or JSON, and exit
    non-zero on NEW findings. The serving analogue of `ruff check` for
    hazards ruff cannot know about (donation, retraces, hot-path
    syncs)."""
    p = argparse.ArgumentParser(
        prog="tpulint",
        description="AST hazard analysis for the JAX serving stack "
        "(TPL1xx recompilation, TPL2xx donation, TPL3xx host-sync, "
        "TPL4xx locks, TPL5xx telemetry, TPL6xx concurrency, TPL7xx "
        "zero-copy, TPL8xx Pallas kernels; see docs/LINTING.md)",
    )
    p.add_argument(
        "paths", nargs="*",
        help="files/dirs to analyze (default: the triton_client_tpu "
        "package this CLI runs from)",
    )
    p.add_argument(
        "--json", action="store_true", help="machine-readable JSON output"
    )
    p.add_argument(
        "--baseline", default=None,
        help="baseline file of accepted findings (tpulint.baseline.json); "
        "only findings NOT in it fail the run",
    )
    p.add_argument(
        "--write-baseline", default=None, metavar="FILE",
        help="write every current finding to FILE as a baseline and "
        "exit 0; entries surviving from the previous baseline keep "
        "their justifications, stale entries are pruned, new entries "
        "start as TODO and must be edited",
    )
    p.add_argument(
        "--prune-stale", action="store_true",
        help="with --baseline: rewrite the baseline file with stale "
        "entries (fingerprints nothing matches anymore) removed, "
        "keeping every surviving entry and justification untouched",
    )
    p.add_argument(
        "--sarif", default=None, metavar="FILE",
        help="also write findings as SARIF 2.1.0 to FILE ('-' for "
        "stdout) for code-scanning UIs; fingerprints match the "
        "baseline's",
    )
    p.add_argument(
        "--jobs", type=int, default=1, metavar="N",
        help="load/parse files on N threads (CI passes this; default "
        "serial)",
    )
    p.add_argument(
        "--changed", action="store_true",
        help="treat the given paths as CHANGED FILES: analyze the "
        "whole package (interprocedural rules need it) but report "
        "only findings located in those files — the pre-commit fast "
        "path",
    )
    p.add_argument(
        "--rules", default=None,
        help="comma-separated code selection (full codes or family "
        "prefixes: 'TPL3,TPL401')",
    )
    p.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalogue and exit",
    )
    p.add_argument(
        "--no-stale-check", action="store_true",
        help="do not warn about baseline entries nothing matched",
    )
    p.add_argument(
        "--stats", action="store_true",
        help="print a per-rule findings/elapsed-ms table (stderr in "
        "text mode, summary.stats in --json) — keeps the ci.sh gate's "
        "cost visible as rule families grow",
    )
    args = p.parse_args(argv)

    import json as _json
    import sys

    from triton_client_tpu import analysis

    if args.list_rules:
        for code, cls in analysis.registry().items():
            print(f"{code}  {cls.name}")
            doc = " ".join((cls.doc or "").split())
            if doc:
                print(f"       {doc}")
        return

    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if args.changed:
        # fast path: the WHOLE package is analyzed (reachability, lock
        # and thread models are interprocedural — a changed callee can
        # create a finding in an unchanged caller's scope only via its
        # own file, but a changed file's findings need global context),
        # then the report is restricted to the files that changed
        if not args.paths:
            print("tpulint: --changed given but no files; nothing to do",
                  file=sys.stderr)
            return
        paths = [pkg_dir]
    else:
        paths = args.paths or [pkg_dir]
    codes = args.rules.split(",") if args.rules else None
    package = analysis.load_package(paths, jobs=max(1, args.jobs))
    rule_stats: dict = {}
    findings = analysis.run_rules(
        package, codes=codes, stats=rule_stats if args.stats else None
    )
    if args.changed:
        changed = {
            os.path.relpath(os.path.abspath(p)) for p in args.paths
        }
        # the TPL805 fused-route contract spans kernel modules, the
        # routing pipelines, ops/fused.py AND the parity test file —
        # its findings anchor in ops/fused.py, so a plain path filter
        # would hide them exactly when a contract participant changed.
        # Keep them whenever any changed file is a participant.
        contract_changed = any(
            os.path.basename(c).startswith("pallas_")
            or c.replace(os.sep, "/").endswith("ops/fused.py")
            or c.replace(os.sep, "/").endswith("tests/test_fused_parity.py")
            for c in changed
        )
        findings = [
            f for f in findings
            if f.path in changed
            or (contract_changed and f.code == "TPL805")
        ]

    if args.write_baseline:
        prior = None
        for prior_path in (args.write_baseline, args.baseline):
            if prior_path and os.path.exists(prior_path):
                prior = analysis.Baseline.load(prior_path)
                break
        bl = analysis.Baseline.from_findings(findings, prior=prior)
        bl.save(args.write_baseline)
        kept = sum(
            1 for e in bl.entries.values()
            if e.get("justification") not in ("", analysis.baseline.UNJUSTIFIED)
        ) if prior else 0
        todo = len(bl.entries) - kept
        print(
            f"wrote {len(bl.entries)} entr(ies) -> {args.write_baseline} "
            f"({kept} justification(s) preserved, {todo} TODO); edit the "
            "TODOs before committing",
            file=sys.stderr,
        )
        return

    suppressed: list = []
    problems: list[str] = list(package.errors)
    if args.baseline:
        bl = analysis.Baseline.load(args.baseline)
        if args.prune_stale and not args.changed:
            dropped = bl.prune(findings)
            bl.save(args.baseline)
            print(
                f"tpulint: pruned {len(dropped)} stale entr(ies) from "
                f"{args.baseline}",
                file=sys.stderr,
            )
        findings, suppressed = bl.split(findings)
        for fp in bl.unjustified():
            e = bl.entries[fp]
            problems.append(
                f"baseline entry {fp} ({e.get('code')} {e.get('path')}) "
                "has no justification"
            )
        # --changed reports a SUBSET of findings, so "nothing matches
        # this entry" would be meaningless noise there
        if not args.no_stale_check and not args.changed:
            for fp in bl.stale(findings + suppressed):
                e = bl.entries[fp]
                print(
                    f"tpulint: warning: stale baseline entry {fp} "
                    f"({e.get('code')} {e.get('path')}: nothing matches it)",
                    file=sys.stderr,
                )
    if args.sarif:
        body = analysis.render_sarif(findings, errors=problems)
        if args.sarif == "-":
            print(body)
        else:
            with open(args.sarif, "w", encoding="utf-8") as fh:
                fh.write(body + "\n")
            print(f"tpulint: SARIF -> {args.sarif}", file=sys.stderr)

    if args.stats and not args.json:
        # pre-baseline counts: the rule's raw cost, not its residual
        hdr = f"{'rule':<8} {'findings':>8} {'elapsed_ms':>11}"
        print(hdr, file=sys.stderr)
        print("-" * len(hdr), file=sys.stderr)
        for code in sorted(rule_stats):
            row = rule_stats[code]
            print(
                f"{code:<8} {row['findings']:>8} {row['elapsed_ms']:>11.1f}",
                file=sys.stderr,
            )
        total_ms = sum(r["elapsed_ms"] for r in rule_stats.values())
        print(
            f"{'total':<8} {sum(r['findings'] for r in rule_stats.values()):>8} "
            f"{total_ms:>11.1f}",
            file=sys.stderr,
        )

    if args.json:
        doc = _json.loads(
            analysis.render_json(
                findings, suppressed=len(suppressed), errors=problems
            )
        )
        if args.stats:
            doc["summary"]["stats"] = rule_stats
        print(_json.dumps(doc, indent=2, sort_keys=True))
    else:
        analysis.render_text(findings)
        for msg in problems:
            print(f"tpulint: error: {msg}", file=sys.stderr)
        tail = f", {len(suppressed)} baselined" if args.baseline else ""
        print(
            f"tpulint: {len(findings)} new finding(s){tail}",
            file=sys.stderr,
        )
    if findings or problems:
        raise SystemExit(1)


def repo_index(argv=None) -> None:
    """List a model repository: local directory (parsed, not built) or a
    live server's RepositoryIndex over gRPC."""
    p = argparse.ArgumentParser(
        description="list model-repository contents (local dir or grpc:<addr>)"
    )
    p.add_argument("target", help="repository root dir or grpc:<host:port>")
    args = p.parse_args(argv)

    if args.target.startswith("grpc:"):
        from triton_client_tpu.channel.grpc_channel import GRPCChannel

        channel = GRPCChannel(args.target[len("grpc:"):])
        try:
            for name, version, state in channel.repository_index():
                print(f"{name}:{version}  {state}")
        finally:
            channel.close()
        return

    import pathlib

    from triton_client_tpu.dataset_config import load_yaml
    from triton_client_tpu.runtime.disk_repository import (
        find_weights,
        version_dirs,
    )

    root = pathlib.Path(args.target)
    if not root.is_dir():
        raise SystemExit(f"{args.target!r} is not a directory or grpc: address")
    for model_dir in sorted(d for d in root.iterdir() if d.is_dir()):
        cfg = model_dir / "config.yaml"
        if not cfg.exists():
            continue
        doc = load_yaml(str(cfg))
        versions = version_dirs(model_dir)
        if not versions:
            print(f"{model_dir.name}:1  family={doc.get('family')}  (fresh-init)")
        for vdir in versions:
            try:
                artifact = find_weights(vdir).name
            except FileNotFoundError:
                artifact = "MISSING WEIGHTS"
            print(
                f"{model_dir.name}:{vdir.name}  family={doc.get('family')}  "
                f"{artifact}"
            )


def route(argv=None) -> None:
    """Probe a replica set the way the FrontDoorRouter sees it: one
    health pass over every endpoint (ServerLive / ServerReady /
    optional ModelReady), replica labels from ServerMetadata, and —
    with ``--watch`` — a live rotation view, so an operator can answer
    "which replicas would take traffic right now?" without standing up
    a router."""
    p = argparse.ArgumentParser(
        description="probe a replica set (health / readiness / labels)"
    )
    p.add_argument(
        "endpoints", nargs="+", help="replica endpoints (host:port ...)"
    )
    p.add_argument(
        "-m", "--model", action="append", default=[],
        help="also require ModelReady for this model (repeatable)",
    )
    p.add_argument(
        "--timeout", type=float, default=2.0,
        help="per-probe RPC deadline in seconds",
    )
    p.add_argument(
        "--watch", type=float, default=0.0,
        help="re-probe every N seconds until interrupted (0 = once)",
    )
    args = p.parse_args(argv)

    import time as _time

    from triton_client_tpu.channel.grpc_channel import GRPCChannel
    from triton_client_tpu.channel.kserve import pb

    channels = [
        GRPCChannel(ep, timeout_s=args.timeout, retries=0)
        for ep in args.endpoints
    ]

    def label_of(chan) -> str:
        try:
            meta = chan._call(
                chan._stub.ServerMetadata, pb.ServerMetadataRequest(),
                retryable=(), timeout_s=args.timeout,
            )
        except Exception:
            return "-"
        for ext in meta.extensions:
            if ext.startswith("replica_of:"):
                return ext.split(":", 1)[1]
        return "-"

    def pass_once() -> int:
        in_rotation = 0
        for ep, chan in zip(args.endpoints, channels):
            live = chan.server_live(timeout_s=args.timeout)
            ready = live and chan.server_ready(timeout_s=args.timeout)
            models_ok = ready and all(
                chan.model_ready(m, timeout_s=args.timeout)
                for m in args.model
            )
            ok = ready and models_ok
            in_rotation += 1 if ok else 0
            state = (
                "IN-ROTATION" if ok
                else "NOT-READY" if live
                else "DEAD"
            )
            detail = "" if models_ok or not ready else " (model not ready)"
            transport = getattr(chan, "transport", "grpc")
            print(
                f"{ep:<28} {state:<12} transport={transport:<8} "
                f"replica_of={label_of(chan)}{detail}",
                flush=True,
            )
        print(
            f"-- {in_rotation}/{len(args.endpoints)} in rotation",
            flush=True,
        )
        return in_rotation

    try:
        ok = pass_once()
        while args.watch > 0:
            _time.sleep(args.watch)
            print()
            ok = pass_once()
    except KeyboardInterrupt:
        pass
    finally:
        for chan in channels:
            try:
                chan.close()
            except Exception:
                pass
    # scripting-friendly: exit nonzero when NOTHING would take traffic
    if ok == 0:
        raise SystemExit(1)
