"""3D detection entry point (main3d.py / bag3d.py parity).

Runs PointPillars over recorded .npy point clouds (the reference's
tools/pc_extractor.py output format), a synthetic stream, or a live
PointCloud2 topic (``ros:<topic>``, gated).

Usage:
  python -m triton_client_tpu.cli.detect3d -i ./clouds --sink jsonl
  python -m triton_client_tpu.cli.detect3d -i synthetic:16
"""

from __future__ import annotations

import argparse
import dataclasses

import jax

from triton_client_tpu.cli.common import (
    _check_async_flags,
    add_common_flags,
    parse_dtype,
    make_profiler,
    make_sink,
    maybe_device_trace,
    print_report,
)


def parse_args(argv=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(description=__doc__)
    add_common_flags(parser)
    # None sentinels: "not passed" must be distinguishable from the
    # default so YAML --config values aren't silently clobbered.
    parser.add_argument("--score", type=float, default=None, help="default 0.1")
    parser.add_argument(
        "--z-offset",
        type=float,
        default=None,
        help="sensor z correction, default 0 (reference adds 1.5, "
        "ros_inference3d.py:128)",
    )
    parser.add_argument(
        "--config",
        default="",
        help="dataset/model YAML (data/kitti_pointpillars.yaml etc.; the "
        "reference's data/pointpillar.yaml role) — overrides -m",
    )
    parser.add_argument(
        "--sweeps",
        type=int,
        default=None,
        help="aggregate the last N scans with a per-point time-lag "
        "channel before inference (nuScenes 10-sweep semantics, "
        "reference data/nusc_centerpoint_pp_02voxel_two_pfn_10sweep.py); "
        "default: the config's nsweeps (1)",
    )
    parser.add_argument(
        "--show", action="store_true",
        help="open an interactive Open3D window per frame (close it to "
        "advance; the reference's visualize_open3d draw_scenes loop). "
        "Needs open3d installed; --sink keeps working without it",
    )
    parser.add_argument(
        "--poses",
        default="",
        help="ego-pose source for --sweeps > 1: 'odom[:topic]' (read "
        "the input bag's nav_msgs/Odometry topic) or a pose JSONL "
        "({frame_id, pose:[x,y,z,qx,qy,qz,qw]}); older sweeps are then "
        "transformed into the keyframe's sensor frame (ego-motion "
        "compensation). Without it sweeps stack untransformed — exact "
        "only for a stationary platform",
    )
    parser.add_argument(
        "--vfe",
        default=None,
        choices=("auto", "grouped"),
        help="voxel-feature path: 'auto' (sort-free scatter VFE when the "
        "model supports it — the fast path) or 'grouped' (exact OpenPCDet "
        "(V, K) budget semantics: caps at max_voxels/max_points_per_voxel)",
    )
    args = parser.parse_args(argv)
    # keep the raw argv so --repo guards can tell an explicitly passed
    # flag from a parser default (cli/common.flags_given)
    import sys

    args.argv = list(argv) if argv is not None else sys.argv[1:]
    return args


def _check_poses_args(args, nsweeps: int | None = None) -> None:
    """--poses usage guards, cheap and decidable from args (+ the
    resolved nsweeps when known). Called twice: early in main (before
    the expensive model build) and in _run_3d (with real nsweeps)."""
    if not args.poses:
        return
    import os

    if nsweeps is not None:
        too_few = nsweeps <= 1
    else:
        too_few = args.sweeps is not None and args.sweeps <= 1
    if too_few:
        raise SystemExit(
            "--poses only affects multi-sweep aggregation; add --sweeps N"
        )
    if args.poses == "odom" or args.poses.startswith("odom:"):
        if not args.input.endswith(".bag"):
            raise SystemExit(
                "--poses odom[:topic] reads the INPUT bag's odometry "
                "topic; the input must be a .bag"
            )
    elif not os.path.exists(args.poses):
        raise SystemExit(f"--poses: no such pose file {args.poses!r}")


def _build_pose_lookup(args):
    """args.poses (already validated) -> pose_lookup callback."""
    if args.poses == "odom" or args.poses.startswith("odom:"):
        from triton_client_tpu.io.bag_io import bag_pose_lookup

        _, _, topic = args.poses.partition(":")
        return bag_pose_lookup(args.input, topic or None)
    from triton_client_tpu.io.bag_io import pose_lookup_from_jsonl

    return pose_lookup_from_jsonl(args.poses)


def main(argv=None) -> None:
    args = parse_args(argv)
    if args.sink == "images":
        raise SystemExit(
            "--sink images is 2D-only (3D results are box arrays, not "
            "annotated frames); use --sink jsonl"
        )

    if args.async_set:
        _check_async_flags(args)

    _check_poses_args(args)
    if args.show:
        # fail before the expensive model build, not after
        try:
            from triton_client_tpu.io.viz3d import _require_open3d

            _require_open3d()
        except ImportError as e:
            raise SystemExit(str(e))

    from triton_client_tpu.drivers.driver import (
        InferenceDriver,
        channel_infer3d,
        detect3d_infer,
        detect3d_infer_async,
    )
    from triton_client_tpu.pipelines.detect3d import (
        BUILDERS_3D as builders,
        default_detect3d_config,
    )

    if args.channel.startswith("grpc:"):
        if not args.model_name:
            raise SystemExit("--channel grpc:... requires -m/--model-name")
        if args.repo:
            raise SystemExit(
                "--repo is in-process mode; in remote mode the SERVER "
                "loads the repository (serve -r ...)"
            )
        if args.config or args.score is not None or args.vfe is not None:
            # Thresholds/model config are baked into the SERVER's jitted
            # pipeline (the repo entry's config.yaml) — silently
            # accepting them here would mislead.
            raise SystemExit(
                "--config/--score/--vfe are server-side in remote mode: set "
                "them in the model repository entry's config.yaml"
            )
        from triton_client_tpu.channel.grpc_channel import GRPCChannel

        channel = GRPCChannel(
            args.channel[len("grpc:"):],
            use_shared_memory=args.use_shared_memory,
        )
        infer = channel_infer3d(
            channel,
            args.model_name,
            model_version=args.model_version,
            z_offset=args.z_offset,  # None -> served metadata value
            asynchronous=args.async_set,
        )
        _run_3d(args, infer, args.model_name, nsweeps=args.sweeps or 1)
        return

    if args.repo:
        from triton_client_tpu.cli.common import flags_given, load_repo_pipeline

        overrides = {}
        if args.score is not None:
            overrides["score_thresh"] = args.score
        if args.z_offset is not None:
            overrides["z_offset"] = args.z_offset
        if args.vfe is not None:
            overrides["vfe"] = args.vfe
        pipe, spec = load_repo_pipeline(
            args, overrides, "3d",
            conflicts={
                "--config": bool(args.config),
                "--dtype": flags_given(getattr(args, "argv", None), "--dtype"),
            },
        )
        infer = (
            detect3d_infer_async(pipe) if args.async_set else detect3d_infer(pipe)
        )
        _run_3d(
            args, infer, spec.name,
            nsweeps=args.sweeps if args.sweeps is not None
            else pipe.config.nsweeps,
        )
        return

    model_cfg = None
    if args.config:
        from triton_client_tpu.dataset_config import detect3d_from_yaml

        name, model_cfg, cfg = detect3d_from_yaml(args.config)
    else:
        name = args.model_name or "pointpillars"
        cfg = default_detect3d_config(name)
    # explicitly-passed CLI flags win over config-file/default values
    if args.score is not None:
        cfg = dataclasses.replace(cfg, score_thresh=args.score)
    if args.z_offset is not None:
        cfg = dataclasses.replace(cfg, z_offset=args.z_offset)
    if args.vfe is not None:
        cfg = dataclasses.replace(cfg, vfe=args.vfe)
    if name not in builders:
        raise SystemExit(f"unknown 3D model '{name}' (choose from {sorted(builders)})")
    pipe, spec, _ = builders[name](
        jax.random.PRNGKey(0), model_cfg=model_cfg, config=cfg,
        dtype=parse_dtype(args.dtype),
    )
    infer = detect3d_infer_async(pipe) if args.async_set else detect3d_infer(pipe)
    _run_3d(
        args, infer, spec.name,
        nsweeps=args.sweeps if args.sweeps is not None else cfg.nsweeps,
    )


def _run_3d(args, infer, model_name: str, nsweeps: int = 1) -> None:
    """Shared driver tail for local (TPUChannel) and remote (gRPC)
    modes: ROS subscriber or pull-driven file/bag source."""
    if args.input.startswith("ros:"):
        if args.show:
            raise SystemExit(
                "--show is replay-only (the live ROS path publishes box "
                "arrays for rviz instead); drop --show for ros: inputs"
            )
        if nsweeps > 1:
            # live aggregation needs per-message stamps + ego poses the
            # subscribed topics don't carry; replay sources support it
            raise SystemExit(
                "--sweeps > 1 is replay-only (bag/.npy sources); the live "
                "ROS path runs single-sweep"
            )
        from triton_client_tpu.drivers import ros

        node = ros.RosDetect3D(
            infer,
            sub_topic=args.input[len("ros:") :],
            pub_topic="/tpu_detections/boxes3d",
        )
        node.spin()
        return

    from triton_client_tpu.drivers.driver import InferenceDriver
    from triton_client_tpu.io.sources import open_source

    source = open_source(args.input, args.limit, kind="pointcloud")
    _check_poses_args(args, nsweeps)
    if nsweeps > 1:
        from triton_client_tpu.ops.sweeps import sweep_source

        pose_lookup = _build_pose_lookup(args) if args.poses else None
        source = sweep_source(source, nsweeps, pose_lookup)
    evaluator = gt_lookup = None
    if args.gt:
        from triton_client_tpu.eval.detection_map import Detection3DEvaluator
        from triton_client_tpu.io.synthdata import load_gt3d_lookup

        evaluator = Detection3DEvaluator()
        gt_lookup = load_gt3d_lookup(args.gt)
    if args.show:
        from triton_client_tpu.io.viz3d import ShowSink3D

        try:
            sink = ShowSink3D(gt_lookup)
        except ImportError as e:
            raise SystemExit(str(e))
    else:
        sink = make_sink(args)
    profiler = make_profiler(args)
    driver = InferenceDriver(
        infer,
        source,
        sink=sink,
        prefetch=args.prefetch,
        warmup=args.warmup,
        evaluator=evaluator,
        gt_lookup=gt_lookup,
        profiler=profiler,
        inflight=args.inflight if args.async_set else 1,
    )
    with maybe_device_trace(args):
        stats = driver.run(max_frames=args.limit)
    if profiler is not None:
        import sys

        print(profiler.report(), file=sys.stderr)
    summary = evaluator.summary() if evaluator is not None else None
    print_report(stats, summary, {"model": model_name})


if __name__ == "__main__":
    main()
