"""Shared CLI flags and wiring helpers.

Flag parity with the reference argparse surface (main.py:51-113):
``-m`` model, ``-x`` version, ``-b`` batch size, ``-c`` class count,
``-s`` scaling mode, ``-i`` input, ``--async``/``--streaming`` retained
— the reference defines but never exercises them (main.py:59-70); here
both are real (async-futures pipelining / ModelStreamInfer). TPU-first
additions: --variant/--width, --limit, --sink, --gt, --prometheus-port.
"""

from __future__ import annotations

import argparse
import json
from typing import Callable

import numpy as np


def add_common_flags(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("-m", "--model-name", default="", help="served model name")
    parser.add_argument("-x", "--model-version", default="", help="model version")
    parser.add_argument(
        "-u", "--channel", default="tpu", dest="channel",
        help="inference channel: 'tpu' (in-process jit, default), "
        "'grpc:<host:port>' (remote KServe v2 server — the reference's "
        "-u server URL, main.py:51-113), or 'grpc:unix:/path.sock' "
        "(the server's same-host unix socket, printed by serve)",
    )
    parser.add_argument(
        "--shm", action="store_const", const=True, default=None,
        dest="use_shared_memory",
        help="force the POSIX shared-memory tensor transport (Triton "
        "system-shared-memory extension). Default is AUTO: same-host "
        "grpc:/unix: channels negotiate shm on their own and remote "
        "ones stay on the wire; --no-shm pins the wire everywhere",
    )
    parser.add_argument(
        "--no-shm", action="store_const", const=False,
        dest="use_shared_memory",
        help="force the gRPC wire transport even on a same-host channel",
    )
    parser.add_argument(
        "--profile", action="store_true",
        help="print a per-stage latency table (source/infer/sink) after "
        "the run",
    )
    parser.add_argument(
        "--profile-trace", default="",
        help="capture a jax.profiler device trace into this directory "
        "(TensorBoard/Perfetto timeline)",
    )
    parser.add_argument(
        "-r", "--repo", default="",
        help="model repository root: load -m's TRAINED weights from "
        "<repo>/<model>/ (config.yaml + version dirs — the layout serve "
        "-r and train --export use) instead of random init; -x picks "
        "the version (default: latest)",
    )
    parser.add_argument("-b", "--batch-size", type=int, default=1)
    parser.add_argument(
        "-c", "--classes", type=int, default=80, help="number of classes"
    )
    parser.add_argument(
        "-s",
        "--scaling",
        default="yolo",
        choices=("yolo", "none", "inception", "vgg", "coco"),
        help="input scaling mode (reference utils/preprocess.py:147-157)",
    )
    parser.add_argument(
        "-i",
        "--input",
        default="synthetic:32",
        help="source: image dir | video file | rosbag (*.bag) | "
        "synthetic[:N[:HxW]] | npy dir (3D)",
    )
    parser.add_argument("--limit", type=int, default=0, help="max frames")
    parser.add_argument(
        "--sink",
        default="null",
        choices=("null", "images", "jsonl", "bag"),
        help="where detections go (images parity: bag_inference2d.py:136; "
        "bag parity: bag_inference3d.py:182-183)",
    )
    parser.add_argument("-o", "--output", default="./output_data")
    parser.add_argument("--names", default="", help="class-names file")
    parser.add_argument("--gt", default="", help="ground-truth JSONL for eval")
    parser.add_argument("--prometheus-port", type=int, default=0)
    parser.add_argument(
        "--async",
        dest="async_set",
        action="store_true",
        help="pipeline inference with async futures: keep --inflight "
        "requests outstanding so host prep overlaps device/remote "
        "compute (the reference defines this flag but never exercises "
        "it, main.py:59-65)",
    )
    parser.add_argument(
        "--inflight", type=int, default=2,
        help="max outstanding requests with --async (>=2)",
    )
    parser.add_argument("--streaming", action="store_true", help="flag parity")
    parser.add_argument("--prefetch", type=int, default=4)
    parser.add_argument("--warmup", type=int, default=1)
    parser.add_argument(
        "--dtype",
        default="fp32",
        choices=("fp32", "bf16"),
        help="model compute dtype: bf16 runs the backbone on the MXU's "
        "native precision (~15%% faster on v5e; heads/decode/NMS stay "
        "fp32). fp32 is the default pending mAP-parity measurement "
        "with real weights",
    )


def parse_dtype(name: str):
    """--dtype string -> jnp dtype (SystemExit on bad input)."""
    from triton_client_tpu.config import parse_compute_dtype

    try:
        return parse_compute_dtype(name)
    except ValueError as e:
        raise SystemExit(str(e))


def _check_async_flags(args) -> None:
    """--async combination guards shared by the 2D/3D entry points."""
    if getattr(args, "streaming", False):
        raise SystemExit(
            "--async and --streaming both pipeline requests; pick one"
        )
    if getattr(args, "cameras", 1) > 1:
        raise SystemExit(
            "--async does not combine with --cameras (the lockstep "
            "multi-camera driver already batches the device)"
        )
    if args.batch_size > 1:
        raise SystemExit(
            "--async pipelines single-frame dispatches; it does not "
            "combine with -b/--batch-size"
        )
    if args.input.startswith("ros:"):
        raise SystemExit(
            "--async is replay-mode only; the live ROS driver already "
            "overlaps decode and compute through its bounded queue"
        )
    if args.inflight < 2:
        raise SystemExit("--inflight must be >= 2 with --async")


def make_sink(args, class_names: tuple[str, ...] = ()):
    from triton_client_tpu.io.sinks import DetectionLogSink, ImageFileSink, NullSink

    if args.sink == "images":
        return ImageFileSink(args.output, class_names)
    if args.sink == "jsonl":
        import os

        return DetectionLogSink(os.path.join(args.output, "detections.jsonl"))
    if args.sink == "bag":
        import os

        from triton_client_tpu.io.bag_io import OutputBagSink, default_output_bag

        name = (
            default_output_bag(args.input)
            if args.input.endswith(".bag")
            else "output.bag"
        )
        os.makedirs(args.output, exist_ok=True)
        return OutputBagSink(os.path.join(args.output, name))
    return NullSink()


def load_gt_lookup(path: str) -> Callable:
    """GT JSONL: one {"frame_id": int, "boxes": [[x1,y1,x2,y2,cls],...]}
    per line — the replay-mode stand-in for the reference's live GT
    topic (evaluate_inference.py:113-115)."""
    table: dict[int, np.ndarray] = {}
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            row = json.loads(line)
            table[int(row["frame_id"])] = np.asarray(row["boxes"], np.float64).reshape(
                -1, 5
            )

    def lookup(frame):
        return table.get(frame.frame_id)

    return lookup


def flags_given(argv, *names) -> bool:
    """True when any of ``names`` was explicitly passed on the command
    line (exact flag or --flag=value form) — how the --repo guards tell
    'user asked for this' from 'parser default', since an explicitly
    passed default value must conflict just as loudly."""
    if argv is None:
        import sys

        argv = sys.argv[1:]
    return any(a == n or a.startswith(n + "=") for a in argv for n in names)


def load_repo_pipeline(args, overrides: dict, kind: str, conflicts: dict):
    """--repo -> (pipeline, spec) with trained weights, with the loud
    guards both detect CLIs share: -m required, wrong-family entries
    rejected, and explicitly-set model-shape flags (which the entry's
    config.yaml owns) refused rather than silently ignored.
    ``conflicts`` maps flag name -> True when set to a non-default."""
    import os

    from triton_client_tpu.runtime.disk_repository import load_pipeline

    if not args.model_name:
        raise SystemExit("--repo requires -m/--model-name")
    bad = sorted(flag for flag, set_ in conflicts.items() if set_)
    if bad:
        raise SystemExit(
            f"{', '.join(bad)} conflict with --repo: the repo entry's "
            "config.yaml owns the model shape; edit the entry instead"
        )
    try:
        return load_pipeline(
            os.path.join(args.repo, args.model_name),
            args.model_version,
            overrides or None,
            kind=kind,
        )
    except (ValueError, FileNotFoundError, KeyError) as e:
        # KeyError: _Entry's unknown-config.yaml-key guard — the loud
        # failure must still be a clean usage exit, not a traceback
        raise SystemExit(str(e))


def load_names(path: str) -> tuple[str, ...]:
    if not path:
        return ()
    from triton_client_tpu.pipelines.detect2d import load_class_names

    return load_class_names(path)


def print_report(stats, summary=None, extra=None) -> None:
    out = {"driver": stats.to_dict()}
    if summary is not None:
        out["eval"] = summary
    if extra:
        out.update(extra)
    print(json.dumps(out))


def make_profiler(args):
    """--profile -> StageProfiler (None when off)."""
    if not getattr(args, "profile", False):
        return None
    from triton_client_tpu.obs.profiling import StageProfiler

    return StageProfiler()


def maybe_device_trace(args):
    """--profile-trace <dir> -> jax.profiler trace context (else no-op)."""
    import contextlib

    log_dir = getattr(args, "profile_trace", "")
    if not log_dir:
        return contextlib.nullcontext()
    from triton_client_tpu.obs.profiling import device_trace

    return device_trace(log_dir)


def parse_mesh(spec: str):
    """'data=4,model=2' -> MeshConfig (empty string -> None: default
    all-devices data-parallel mesh). Malformed specs exit with a usage
    message, not a traceback."""
    if not spec:
        return None
    from triton_client_tpu.parallel.mesh import MeshConfig

    valid = {"data", "model", "seq", "pipe"}
    kwargs = {}
    for part in spec.split(","):
        key, _, value = part.partition("=")
        key = key.strip()
        if key not in valid:
            raise SystemExit(
                f"--mesh: unknown axis {key!r} (valid: {sorted(valid)})"
            )
        try:
            kwargs[key] = int(value)
        except ValueError:
            raise SystemExit(
                f"--mesh: {part!r} is not <axis>=<int> (e.g. 'data=4')"
            ) from None
    return MeshConfig(**kwargs)
