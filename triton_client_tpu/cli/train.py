"""``train`` entry point: sharded YOLOv5 fine-tuning on the mesh.

The reference is inference-only — weights arrive as server-side
artifacts trained elsewhere (SURVEY.md §5 checkpoint/resume). This
closes the loop TPU-natively: fine-tune (e.g. the crop/weed classes)
with data parallelism over the same mesh that serves, checkpoint with
retention, resume, and export the result straight into a model
repository entry the serve CLI loads.

    python -m triton_client_tpu train -i images/ --gt gt.jsonl -c 2 \
        --steps 500 --checkpoint-dir ckpts --export /opt/model_repo
"""

from __future__ import annotations

import argparse
import functools
import itertools

import numpy as np


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--family", default="yolov5",
                   choices=("yolov5", "pointpillars", "second_iou",
                            "centerpoint"),
                   help="model family: yolov5 (2D, image sources), "
                   "pointpillars / second_iou (3D anchor-head "
                   "detectors, .npy cloud sources + gt3d JSONL), or "
                   "centerpoint (anchor-free center-heatmap 3D; gt3d "
                   "rows may carry optional [vx, vy] velocity columns)")
    p.add_argument("-i", "--input", default="synthetic:64",
                   help="image dir | synthetic[:N[:HxW]] (2D); .npy cloud "
                   "dir (3D)")
    p.add_argument("--gt", default="",
                   help="ground-truth JSONL: {frame_id, boxes:[[x1,y1,x2,y2,"
                   "cls]]} (2D) or [[cx,cy,cz,dx,dy,dz,yaw,cls]] (3D); "
                   "omitted with synthetic 2D input -> random boxes")
    p.add_argument("--points", type=int, default=20000,
                   help="3D: per-scan point budget (static pad)")
    p.add_argument("--config", default="",
                   help="3D: dataset/model yaml (detect3d --config schema); "
                   "copied into the exported entry as its dataset.yaml")
    p.add_argument("--variant", default="n", help="yolov5 variant (n/s/m/l/x)")
    p.add_argument("--mxu-opt", action="store_true",
                   help="yolov5: train the MXU-shaped layout (s2d stem + "
                   "32-channel floor, +16%% serving throughput at b8); "
                   "the exported entry serves it directly")
    p.add_argument("-c", "--classes", type=int, default=2)
    p.add_argument("--input-size", type=int, default=512)
    p.add_argument("-b", "--batch-size", type=int, default=8)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--lr-final", type=float, default=0.0,
                   help="> 0: cosine-decay the lr from --lr to this over "
                   "--steps (0 = constant lr)")
    p.add_argument("--max-boxes", type=int, default=32,
                   help="targets padded per image (static shapes)")
    p.add_argument(
        "--distributed", default="",
        help="join a multi-host jax.distributed cluster before building "
        "the mesh: 'env' (COORDINATOR/NPROC/PROC_ID env vars) or "
        "'<host:port>,<num_processes>,<process_id>'. Run the same "
        "command on every host; the mesh then spans all hosts' chips "
        "(data axis over DCN, model/seq/pipe on intra-host ICI)",
    )
    p.add_argument(
        "--aug3d", default="auto", choices=("auto", "on", "off"),
        help="global rot/flip/scale train augmentation (3D families; "
        "the det3d/OpenPCDet recipe). auto = on for centerpoint — "
        "whose single-cell yaw/velocity regression does not "
        "generalize without it — off for the anchor heads, whose "
        "mod-pi sin-difference loss already does",
    )
    p.add_argument("--mesh", default="",
                   help="e.g. 'data=8' or 'data=4,model=2'")
    p.add_argument("--per-host-source", action="store_true",
                   help="multi-host: --input names THIS host's own "
                        "cameras/bags (each host consumes its stream "
                        "fully) instead of a source shared by all hosts")
    p.add_argument("--checkpoint-dir", default="",
                   help="save TrainState every --save-every steps")
    p.add_argument("--save-every", type=int, default=100)
    p.add_argument("--resume", action="store_true",
                   help="restore the latest step from --checkpoint-dir")
    p.add_argument("--export", default="",
                   help="model-repository root to export final weights into")
    p.add_argument("-m", "--model-name", default="yolov5_trained")
    p.add_argument("--log-every", type=int, default=10)
    return p.parse_args(argv)


def _load_batches(
    args,
    rng: np.random.Generator,
    row0: int = 0,
    rows: int | None = None,
    stride: int | None = None,
):
    """Yield (images (rows, S, S, 3) f32, targets (rows, T, 5) [cls,
    cx, cy, w, h] pixels) forever, cycling the source. ``row0``/``rows``
    window the stream for multi-host runs; ``stride`` is how many frames
    the stream advances per step. Shared source: stride=global batch,
    row0=process_index*per_host — hosts decode disjoint blocks of the
    same stream. Per-host sources (--per-host-source): stride=rows,
    row0=0 — each host consumes its own stream fully (a global stride
    there would silently discard (P-1)/P of every host's frames)."""
    from triton_client_tpu.cli.common import load_gt_lookup
    from triton_client_tpu.io.sources import open_source

    size = args.input_size
    lookup = load_gt_lookup(args.gt) if args.gt else None

    def frame_stream():
        while True:
            source = open_source(args.input, 0)
            empty = True
            for frame in source:
                empty = False
                yield frame
            if empty:
                raise SystemExit(f"no frames in {args.input!r}")

    def to_example(frame):
        img = np.asarray(frame.data, np.float32)
        h, w = img.shape[:2]
        if (h, w) != (size, size):
            import cv2

            img = cv2.resize(img.astype(np.uint8), (size, size)).astype(np.float32)
        # Train on the SERVING input distribution: the fused pipeline
        # normalizes with scaling='yolo' (x/255, ops/preprocess.py), so
        # the train step must see the same 0-1 range or the exported
        # weights (incl. adapted batch_stats) are invalidated at serve
        # time.
        img = img / 255.0
        targets = np.zeros((args.max_boxes, 5), np.float32)
        if lookup is not None:
            gts = lookup(frame)
            if gts is not None and len(gts):
                gts = np.asarray(gts, np.float32)[: args.max_boxes]
                sx, sy = size / w, size / h
                cx = (gts[:, 0] + gts[:, 2]) / 2 * sx
                cy = (gts[:, 1] + gts[:, 3]) / 2 * sy
                bw = (gts[:, 2] - gts[:, 0]) * sx
                bh = (gts[:, 3] - gts[:, 1]) * sy
                targets[: len(gts)] = np.stack(
                    [gts[:, 4], cx, cy, bw, bh], axis=-1
                )
        else:
            # synthetic self-supervision: random plausible boxes
            n = rng.integers(1, 4)
            for t in range(n):
                bw, bh = rng.uniform(size * 0.1, size * 0.4, 2)
                cx = rng.uniform(bw / 2, size - bw / 2)
                cy = rng.uniform(bh / 2, size - bh / 2)
                targets[t] = [rng.integers(0, args.classes), cx, cy, bw, bh]
        return img, targets

    stream = frame_stream()
    rows = args.batch_size if rows is None else rows
    stride = args.batch_size if stride is None else stride
    while True:
        frames = list(itertools.islice(stream, stride))
        examples = [to_example(f) for f in frames[row0 : row0 + rows]]
        yield (
            np.stack([e[0] for e in examples]),
            np.stack([e[1] for e in examples]),
        )


def _load_batches3d(
    args,
    rng: np.random.Generator,
    row0: int = 0,
    rows: int | None = None,
    stride: int | None = None,
    pc_range: tuple | None = None,
    point_cols: int = 4,
    target_cols: int = 8,
):
    """3D sibling of _load_batches: yield (points (rows, P, 4) padded,
    counts (rows,), targets (rows, T, 8) [box7, cls] padded with -1)
    forever. `synthetic[:N]` input generates N labeled scenes in-memory
    (io/synthdata.py) inside ``pc_range`` — the MODEL's grid range, or
    objects would fall outside the voxel grid and train nothing; file
    sources need --gt with the gt3d schema."""
    from triton_client_tpu.io.synthdata import (
        load_gt3d_lookup,
        synth_scene_frame,
    )

    budget, t_max = args.points, args.max_boxes

    if args.input.startswith("synthetic"):
        parts = args.input.split(":")
        n = int(parts[1]) if len(parts) > 1 and parts[1] else 64
        scene_kwargs = {} if pc_range is None else {"pc_range": tuple(pc_range)}

        def pair_stream():
            while True:
                r = np.random.default_rng(0)
                for _ in range(n):
                    yield synth_scene_frame(r, **scene_kwargs)

    else:
        if not args.gt:
            raise SystemExit(
                "--family pointpillars with a file source requires --gt "
                "(gt3d JSONL; generate with io/synthdata.py)"
            )
        from triton_client_tpu.io.sources import open_source

        lookup = load_gt3d_lookup(args.gt)

        def pair_stream():
            while True:
                source = open_source(args.input, 0, kind="pointcloud")
                empty = True
                for frame in source:
                    empty = False
                    gts = lookup(frame)
                    yield (
                        frame.data,
                        gts if gts is not None else np.zeros((0, 8)),
                    )
                if empty:
                    raise SystemExit(f"no clouds in {args.input!r}")

    stream = pair_stream()
    rows = args.batch_size if rows is None else rows
    stride = args.batch_size if stride is None else stride
    while True:
        pairs = list(itertools.islice(stream, stride))[row0 : row0 + rows]
        # both widths are the MODEL's contract, not the data's: clouds
        # narrower than point_cols zero-pad the missing Δt channel
        # (mirroring the serving path, pipelines/detect3d.py infer);
        # sniffing widths from data would mis-lock on an unlucky first
        # window and silently drop velocity labels / crash the VFE
        points = np.zeros((rows, budget, point_cols), np.float32)
        counts = np.zeros((rows,), np.int32)
        targets = np.full((rows, t_max, target_cols), -1.0, np.float32)
        for i, (pts, boxes) in enumerate(pairs):
            m = min(len(pts), budget)
            w = min(pts.shape[1], point_cols)
            points[i, :m, :w] = pts[:m, :w]
            counts[i] = m
            k = min(len(boxes), t_max)
            if k:
                bw = min(boxes.shape[1], target_cols)
                targets[i, :k, :bw] = boxes[:k, :bw]
                if bw < target_cols:
                    targets[i, :k, bw:] = 0.0  # missing vel -> 0
        yield points, counts, targets


def main(argv=None) -> None:
    args = parse_args(argv)

    import jax
    import jax.numpy as jnp
    import optax

    from triton_client_tpu.cli.common import parse_mesh
    from triton_client_tpu.parallel.mesh import make_mesh
    from triton_client_tpu.parallel.train import TrainState

    # cheap usage validation BEFORE paying for model/mesh init
    if args.resume and not args.checkpoint_dir:
        raise SystemExit("--resume requires --checkpoint-dir")
    if args.resume:
        from triton_client_tpu.runtime.checkpoint import CheckpointManager

        if CheckpointManager(args.checkpoint_dir).latest_step() is None:
            raise SystemExit(
                f"--resume: no checkpoint found under {args.checkpoint_dir!r}"
            )

    if args.distributed:
        from triton_client_tpu.parallel.distributed import (
            DistributedConfig,
            global_mesh,
            init_distributed,
            is_coordinator,
        )

        try:
            init_distributed(DistributedConfig.from_spec(args.distributed))
        except ValueError as e:
            raise SystemExit(str(e))
        mesh = global_mesh(parse_mesh(args.mesh))
        singleton = is_coordinator()
    else:
        mesh = make_mesh(parse_mesh(args.mesh))
        singleton = True
    if args.batch_size % mesh.shape["data"]:
        raise SystemExit(
            f"--batch-size {args.batch_size} must divide over the data "
            f"axis ({mesh.shape['data']})"
        )
    if args.distributed and args.batch_size % jax.process_count():
        raise SystemExit(
            f"--batch-size {args.batch_size} must divide across "
            f"{jax.process_count()} processes"
        )

    if args.lr_final > 0:
        schedule = optax.cosine_decay_schedule(
            args.lr, args.steps, alpha=args.lr_final / args.lr
        )
        optimizer = optax.adam(schedule)
    else:
        optimizer = optax.adam(args.lr)
    family3d = args.family in ("pointpillars", "second_iou", "centerpoint")
    if family3d and args.mxu_opt:
        raise SystemExit("--mxu-opt is yolov5-only")
    if not family3d and args.aug3d != "auto":
        raise SystemExit("--aug3d applies to the 3D families only")
    if family3d:
        from triton_client_tpu.parallel.train3d import (
            Augment3DConfig,
            CenterLossConfig,
            Loss3DConfig,
            init_train3d_state,
            make_center3d_step,
            make_train3d_step,
        )

        model_cfg = None
        if args.config:
            from triton_client_tpu.dataset_config import detect3d_from_yaml

            fam, model_cfg, _ = detect3d_from_yaml(args.config)
            if fam != args.family:
                raise SystemExit(
                    f"--config model {fam!r} != --family {args.family!r}"
                )
        if args.family == "second_iou":
            from triton_client_tpu.models.second import init_second

            if model_cfg is not None and model_cfg.middle == "sparse":
                raise SystemExit(
                    "training runs the dense middle encoder; train at a "
                    "dense-capable grid (middle: dense) and serve the "
                    "sparse config after import"
                )
            model, variables = init_second(jax.random.PRNGKey(0), model_cfg)
        elif args.family == "centerpoint":
            from triton_client_tpu.models.centerpoint import init_centerpoint

            model, variables = init_centerpoint(
                jax.random.PRNGKey(0), model_cfg
            )
        else:
            from triton_client_tpu.models.pointpillars import init_pointpillars

            model, variables = init_pointpillars(
                jax.random.PRNGKey(0), model_cfg
            )

        def init_state(vars_):
            return init_train3d_state(model, vars_, optimizer, mesh)

        aug_on = args.aug3d == "on" or (
            args.aug3d == "auto" and args.family == "centerpoint"
        )
        augment = Augment3DConfig() if aug_on else None
        if args.family == "centerpoint":
            step_fn = make_center3d_step(
                model, optimizer, CenterLossConfig(), mesh, augment=augment
            )
        else:
            step_fn = make_train3d_step(
                model, optimizer, Loss3DConfig(), mesh, augment=augment
            )
        loader = functools.partial(
            _load_batches3d,
            pc_range=model.cfg.voxel.point_cloud_range,
            point_cols=model.cfg.voxel.point_features,
            # centerpoint targets carry [vx, vy]; 8-col gt rows pad 0
            target_cols=10 if args.family == "centerpoint" else 8,
        )
        export_doc = {"family": args.family}
        if args.config:
            export_doc["dataset"] = "dataset.yaml"
    else:
        if args.config:
            raise SystemExit(
                "--config is 3D-only; the yolov5 shape comes from "
                "--variant/--input-size/-c"
            )
        from triton_client_tpu.models.yolov5 import DEFAULT_ANCHORS, init_yolov5
        from triton_client_tpu.parallel.train import (
            LossConfig,
            init_train_state,
            make_train_step,
        )

        model, variables = init_yolov5(
            jax.random.PRNGKey(0),
            num_classes=args.classes,
            variant=args.variant,
            input_hw=(args.input_size, args.input_size),
            s2d=args.mxu_opt,
            ch_floor=32 if args.mxu_opt else 0,
        )
        loss_cfg = LossConfig(num_classes=args.classes, anchors=DEFAULT_ANCHORS)

        def init_state(vars_):
            return init_train_state(model, vars_, optimizer, mesh)

        step_fn = make_train_step(model, optimizer, loss_cfg, mesh)
        loader = _load_batches
        export_doc = {
            "family": "yolov5",
            "model": {
                "variant": args.variant,
                "num_classes": args.classes,
                "input_hw": [args.input_size, args.input_size],
            },
        }
        if args.mxu_opt:
            export_doc["model"]["s2d"] = True
            export_doc["model"]["ch_floor"] = 32
    state = init_state(variables)

    manager = None
    if args.checkpoint_dir:
        from triton_client_tpu.runtime.checkpoint import CheckpointManager

        manager = CheckpointManager(args.checkpoint_dir)
        if args.resume:  # existence was validated before model init
            # Restore to host, then re-shard through the same init path
            # (orbax restores leaf placements inconsistently against a
            # mixed replicated/sharded `like` tree).
            host = manager.restore(like=jax.tree.map(np.asarray, state))
            fresh = init_state(jax.tree.map(np.asarray, host.variables))
            # opt_state stays as uncommitted host leaves — the jitted
            # step places them to match the param shardings; committing
            # them to a single device would conflict with the mesh.
            state = TrainState(
                variables=fresh.variables,
                opt_state=jax.tree.map(np.asarray, host.opt_state),
                step=np.asarray(host.step),
            )
            print(f"resumed from step {int(state.step)}")

    rng = np.random.default_rng(0)

    if args.distributed and jax.process_count() > 1:
        # multi-host feed: --batch-size is the GLOBAL batch; the blocks
        # assemble into one global jax.Array — no cross-host gathering.
        # Shared source (default): every host decodes only ITS
        # process_index-th block of rows of the common stream, which
        # advances by the global batch. --per-host-source: each host's
        # --input is its own cameras/bags, so it decodes rows [0,
        # per_host) and advances by per_host only.
        from triton_client_tpu.parallel.distributed import shard_host_batch

        per_host = args.batch_size // jax.process_count()
        if args.per_host_source:
            batches = loader(
                args, rng, row0=0, rows=per_host, stride=per_host
            )
        else:
            batches = loader(
                args, rng, row0=jax.process_index() * per_host, rows=per_host
            )

        def feed(arr):
            return shard_host_batch(arr, mesh)
    else:
        batches = loader(args, rng)
        feed = jnp.asarray

    # checkpoint/log/export are coordinator-only under jax.distributed:
    # DP training replicates params so process 0 holds the full state
    # (model/seq-sharded multi-host checkpointing would need orbax's
    # multihost path — out of scope for the DP train CLI)
    start = int(state.step)
    for step in range(start, args.steps):
        state, metrics = step_fn(state, *(feed(a) for a in next(batches)))
        if singleton and ((step + 1) % args.log_every == 0 or step + 1 == args.steps):
            m = {k: round(float(v), 4) for k, v in metrics.items()}
            print(f"step {step + 1}/{args.steps} {m}")
        if manager is not None and singleton and (step + 1) % args.save_every == 0:
            manager.save(step + 1, state)
    if manager is not None and singleton and int(state.step) > start:
        manager.save(int(state.step), state)
        manager.close()

    if not singleton:
        return
    if args.export:
        from triton_client_tpu.runtime.disk_repository import export_model

        # gather sharded leaves to host before serialization
        host_vars = jax.tree.map(np.asarray, state.variables)
        entry = export_model(
            args.export, args.model_name, export_doc, variables=host_vars
        )
        if family3d and args.config:
            import shutil

            shutil.copy(args.config, entry / "dataset.yaml")
        print(f"exported {entry} (serve with: serve -r {args.export})")


if __name__ == "__main__":
    main()
