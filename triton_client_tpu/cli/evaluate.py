"""Accuracy evaluation entry point (evaluate.py / EvaluateInference
parity, communicator/evaluate_inference.py).

Replays an image source against a ground-truth JSONL, computes COCO
101-pt mAP at IoU 0.5:0.05:0.95 with per-class P/R/F1, and (optionally)
serves the reference's five Prometheus Summaries on --prometheus-port
(default 7658 when enabled; evaluate_inference.py:52-61).

The reference needed a 20 s sleep barrier to sync its image and GT
topics (evaluate_inference.py:117); replay mode joins on frame_id, so
there is nothing to race.
"""

from __future__ import annotations

import argparse
import sys

from triton_client_tpu.cli import detect2d
from triton_client_tpu.cli.common import add_common_flags


def main(argv=None) -> None:
    # evaluate == detect2d with --gt required and eval defaults on. The
    # ORIGINAL argv forwards verbatim (every evaluate flag is a
    # detect2d flag), so detect2d's explicit-flag guards (--repo
    # conflicts) still see exactly what the user typed rather than
    # re-serialized parser defaults.
    parser = argparse.ArgumentParser(description=__doc__)
    add_common_flags(parser)
    parser.add_argument("--input-size", type=int, default=512)
    parser.add_argument("--conf", type=float, default=None)
    parser.add_argument("--iou", type=float, default=None)
    parser.add_argument("--width", type=float, default=1.0)
    args = parser.parse_args(argv)
    if not args.gt:
        parser.error("--gt <file.jsonl> is required for evaluation")

    forwarded = list(argv) if argv is not None else list(sys.argv[1:])
    if args.prometheus_port == 0:
        forwarded += ["--prometheus-port", "7658"]
    detect2d.main(forwarded)


if __name__ == "__main__":
    main()
