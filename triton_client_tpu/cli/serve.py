"""``serve`` entry point: disk model repository -> KServe v2 gRPC server.

The reference's serving process is ``tritonserver
--model-repository=/opt/model_repo`` inside the server containers
(docker/server/Dockerfile:131-135, README.md:66). This is that process
for the TPU runtime: scan the repository layout, jit every model onto
the mesh, serve the KServe v2 protocol so the reference's ROS tooling
(and our GRPCChannel) connects unchanged.
"""

from __future__ import annotations

import argparse
import logging

log = logging.getLogger(__name__)

# one-time deprecation warning for --batch-timeout-us on the continuous
# path (the flag is window-batcher-only; see build_server)
_timeout_warned = False


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="TPU inference server")
    p.add_argument(
        "-r", "--model-repository", required=True,
        help="model repository root (examples/ layout)",
    )
    p.add_argument("-a", "--address", default="0.0.0.0:8001")
    p.add_argument(
        "--uds", default="auto",
        help="unix-domain-socket listener alongside TCP: 'auto' "
        "(default) picks a per-process socket under $TMPDIR, "
        "'unix:/path.sock' or '/path.sock' pins it, 'off' disables. "
        "Same-host clients dialing the unix: target skip the loopback "
        "TCP stack and auto-negotiate shared-memory tensor transport "
        "(docs/OPERATIONS.md 'Host transport')",
    )
    p.add_argument("--max-workers", type=int, default=8)
    p.add_argument(
        "--mesh", default="",
        help="device mesh, e.g. 'data=4' — serve data-parallel over the "
        "mesh (ShardedTPUChannel): params replicated once, request "
        "batches padded and sharded over the data axis; empty = "
        "single-executable TPUChannel",
    )
    p.add_argument(
        "--precision", default="", choices=["", "f32", "bf16", "int8w", "int8"],
        help="serving precision policy applied to EVERY repository entry "
        "(runtime/precision.py), overriding per-model config.yaml "
        "model.precision: bf16 = params+compute+wire in bfloat16, "
        "int8w = int8 weights, int8 = int8 weights+activations with "
        "calibrated scales; empty = per-model config (default f32)",
    )
    p.add_argument(
        "--batching", action="store_true",
        help="micro-batch concurrent requests before dispatch (Triton's "
        "dynamic batcher role; see --batcher for the scheduler)",
    )
    p.add_argument(
        "--batcher", default="continuous", choices=["continuous", "window"],
        help="batch scheduler: 'continuous' (default) admits while device "
        "work is in flight — EDF-ordered ready queue, packed ragged "
        "execution for models registered with a ragged_fn, live "
        "occupancy-driven pad buckets; 'window' is the legacy "
        "admission-window merge (native C++ batcher with python fallback)",
    )
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument(
        "--batch-timeout-us", type=int, default=None,
        help="max time a request waits for batch-mates (window batcher "
        "only, default 2000; DEPRECATED on the continuous scheduler, "
        "which has no admission window — see docs/OPERATIONS.md "
        "'Migration — the window-timeout knob')",
    )
    p.add_argument(
        "--pipeline-depth", type=int, default=2,
        help="formed batches executing concurrently: batch N+1's "
        "host->device transfer overlaps batch N's compute (Triton's "
        "per-instance CUDA-stream role); 1 = strictly serial",
    )
    p.add_argument(
        "--max-merge", type=int, default=None,
        help="frame cap for one device batch formed at dispatch time "
        "(default: --max-batch). Higher values fuse several admission "
        "windows into one device call, amortizing per-dispatch cost "
        "(Triton preferred_batch_size role)",
    )
    p.add_argument(
        "--merge-hold-us", type=int, default=0,
        help="hold a dispatch up to this long when the queue is "
        "shallow, letting a client burst coalesce instead of shipping "
        "a fragment (0 = strictly eager)",
    )
    p.add_argument(
        "--pad-buckets", action="store_true",
        help="pad each device batch to the next power of two so XLA "
        "compiles log2(max-merge)+1 batch shapes instead of every size",
    )
    p.add_argument(
        "--metrics-port", type=int, default=8002,
        help="telemetry endpoint: Prometheus metrics on /metrics (Triton "
        ":8002 parity), Chrome-trace JSON on /traces, raw collector "
        "state on /snapshot (0 disables)",
    )
    p.add_argument(
        "--op-sample-interval", type=float, default=0.0,
        help="continuous op-level sampling: take a short jax.profiler "
        "window every this many seconds and export top-K per-op device "
        "time at tpu_serving_op_device_seconds{model,op,kind} "
        "(obs/sampler.py; capture share of wall time is structurally "
        "capped at 1%%). 0 disables. Requires --metrics-port",
    )
    p.add_argument(
        "--op-sample-window", type=float, default=0.2,
        help="length of one sampler capture window in seconds (clamped "
        "so window/interval never exceeds the 1%% duty-cycle budget)",
    )
    p.add_argument(
        "--history-interval", type=float, default=10.0,
        help="metric-history ring spacing in seconds: per-model×tenant "
        "launch/device-time rates, utilization and MFU snapshots "
        "served at /history (0 disables)",
    )
    p.add_argument(
        "--history-capacity", type=int, default=360,
        help="metric-history ring depth (default 360 x 10s = 1h)",
    )
    p.add_argument(
        "--history-path", default="",
        help="persist the metric-history ring to this JSON file on "
        "drain and restore from it on startup (empty disables)",
    )
    p.add_argument(
        "--canary", action="append", default=[],
        help="arm a quality-gated canary: [primary:]variant=fraction "
        "(e.g. det_int8=0.05 routes 5%% of det traffic — inferred from "
        "the variant name — to det_int8). Promoted to full traffic "
        "after --quality-promote-after consecutive clean shadow-scored "
        "windows, auto-rolled-back to the f32 primary on the first "
        "budget violation. Repeatable; implies the quality plane",
    )
    p.add_argument(
        "--quality-sample", type=float, default=0.0,
        help="continuous quality plane sampling rate in [0,1]: this "
        "fraction of live traffic (deterministic trace-id hash) is "
        "mirrored to the f32 reference and scored online "
        "(tpu_quality_* metric families, /snapshot['quality']). "
        "0 disables unless --canary arms it (then 0.25 is used)",
    )
    p.add_argument(
        "--quality-window", type=int, default=32,
        help="scored frames per quality window: gate verdicts, canary "
        "promotion counting, and the tpu_quality_* gauges all advance "
        "once per window",
    )
    p.add_argument(
        "--quality-promote-after", type=int, default=3,
        help="consecutive clean windows before a canary variant is "
        "promoted to full traffic",
    )
    p.add_argument(
        "--quality-pin-fused-off", action="store_true",
        help="on quality rollback, also export TPU_FUSED_KERNELS=0 so "
        "freshly compiled models take the reference (unfused) path",
    )
    p.add_argument(
        "--trace-capacity", type=int, default=256,
        help="recent request traces kept for /traces export "
        "(`trace-dump`); 0 disables request-scoped spans",
    )
    p.add_argument(
        "--slo-ms", type=float, default=0.0,
        help="per-request latency SLO: requests are deadline-stamped at "
        "admission and scored met/missed per model+priority "
        "(tpu_serving_slo_requests_total); violating traces export at "
        "/traces?slo_violations=1. 0 disables scoring (latency "
        "histograms still export). Requires --metrics-port.",
    )
    p.add_argument(
        "--slo-tail-capacity", type=int, default=64,
        help="bounded ring of SLO-violating / p99+ exemplar traces",
    )
    p.add_argument(
        "--admission", type=int, default=0,
        help="per-model admitted-but-unfinished request cap: beyond it "
        "(or when the estimated queue wait already exceeds a request's "
        "deadline budget) new requests are rejected with "
        "RESOURCE_EXHAUSTED before parse. Enabling admission also arms "
        "deadline shedding in the batcher and staged channels (see "
        "--shed-expired). 0 = no admission control",
    )
    p.add_argument(
        "--admission-concurrency", type=int, default=4,
        help="assumed per-model service concurrency for the "
        "estimated-wait admission math (batcher width x pipeline "
        "depth, roughly)",
    )
    p.add_argument(
        "--shed-expired", action="store_true",
        help="fail requests whose deadline already expired at "
        "batcher-merge and pre-launch with DEADLINE_EXCEEDED instead "
        "of executing them (deadline_expired_launches stays 0 while "
        "tpu_serving_shed_total grows); implied by --admission > 0",
    )
    p.add_argument(
        "--breaker-threshold", type=int, default=5,
        help="consecutive launch/readback failures that open a "
        "model's circuit breaker (fail-fast UNAVAILABLE, launch cache "
        "invalidated; a timed probe half-opens it). 0 disables",
    )
    p.add_argument(
        "--breaker-reset-s", type=float, default=10.0,
        help="seconds an open circuit waits before admitting one "
        "half-open probe request",
    )
    p.add_argument(
        "--drain-timeout", type=float, default=10.0,
        help="graceful-shutdown budget (SIGTERM): health flips "
        "not-ready, new requests get UNAVAILABLE, in-flight work "
        "completes up to this many seconds before teardown",
    )
    p.add_argument(
        "--fault-plan", default="",
        help="JSON fault-injection plan file (runtime/faults.py) "
        "installed process-wide — CHAOS TESTING ONLY: injects "
        "launch/readback/codec failures and latency on a seeded, "
        "deterministic schedule",
    )
    p.add_argument(
        "--hbm-budget", type=float, default=0.0,
        help="HBM paging budget in MB for model params "
        "(runtime/lifecycle.py): models start COLD, page in on first "
        "request, and evict LRU-within-priority under pressure — "
        "register more models than fit at once. 0 = every model stays "
        "resident (legacy behavior)",
    )
    p.add_argument(
        "--tenants", default="",
        help="tenants.yaml path mapping models to tenants with HBM "
        "quotas, fair-share weights, and in-flight caps (see "
        "docs/OPERATIONS.md 'Multi-tenant serving')",
    )
    p.add_argument(
        "--max-sessions", type=int, default=64,
        help="streaming-session slot pool size (runtime/sessions.py): "
        "requests carrying a sequence_id parameter get device-resident "
        "per-stream tracker state in one of this many slots; ended and "
        "TTL-expired slots are reclaimed, a full unreclaimable pool "
        "sheds with RESOURCE_EXHAUSTED. 0 disables sessions (sequence "
        "params pass through untracked)",
    )
    p.add_argument(
        "--session-ttl-s", type=float, default=60.0,
        help="idle seconds before a streaming session's slot is "
        "reclaimable (streams that vanish without sequence_end)",
    )
    p.add_argument(
        "--session-id-namespace", type=int, default=0,
        help="track-id namespace (0-15) stamped into this replica's "
        "track ids — give each replica of a fleet a distinct value so "
        "ids stay globally unique across session re-homing",
    )
    p.add_argument(
        "--temporal-reuse", default="off",
        choices=("auto", "on", "off"),
        help="temporal compute reuse for streaming sessions "
        "(runtime/temporal.py): full detection every K frames with "
        "tracker-coast between, ROI-tile partial recompute on "
        "tile-capable models. 'auto' adapts K per stream from the "
        "Kalman innovation; 'on' runs a fixed K=--temporal-k-max; "
        "'off' (default) disables the plane. Per-model "
        "spec.extra['temporal_reuse'] overrides. Quality-gated: the "
        "plane auto-disables per stream on ID churn, and the quality "
        "plane's window violations disable it per model",
    )
    p.add_argument(
        "--temporal-k-max", type=int, default=8,
        help="keyframe-interval ceiling: at most K-1 consecutive "
        "coast/partial frames between full detections",
    )
    p.add_argument(
        "--temporal-tile", type=int, default=8,
        help="ROI recompute tile edge (pixels) for tile-capable models",
    )
    p.add_argument(
        "--temporal-forced-k", type=int, default=0,
        help="pin K to this value, no adaptation (cadence tests and "
        "over-aggressive-K drives; 0 = adaptive)",
    )
    p.add_argument(
        "--replica-of", default="",
        help="replica-set label: this server is one replica of the named "
        "fleet. Advertised via ServerMetadata extensions (the `route` "
        "tool reads it back) and keys the replica_down fault point so a "
        "chaos plan can kill one labeled replica",
    )
    p.add_argument(
        "--warmup", action="store_true",
        help="compile every registered model before accepting requests",
    )
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.DEBUG if args.verbose else logging.INFO)
    server = build_server(args)
    server.start()
    # flush=True: supervisors/drives parse this line through a pipe,
    # where block buffering would hold it until exit.
    print(f"KServe v2 gRPC server listening on port {server.port}", flush=True)
    if getattr(server, "uds_address", None):
        print(f"unix socket: {server.uds_address}", flush=True)
    if server.metrics_enabled:
        print(
            f"telemetry on :{server.metrics_port} "
            "(/metrics /traces /snapshot /profile /history)", flush=True,
        )

    import signal

    def _sigterm(signum, frame):
        # orchestrator shutdown: drain instead of dropping in-flight
        # work on the floor. The handler interrupts wait() on the main
        # thread; drain() flips not-ready, waits out the building, and
        # stops the transport — wait() then returns and main exits.
        print(
            f"SIGTERM: draining (timeout {args.drain_timeout:.1f}s)",
            flush=True,
        )
        drained = server.drain(timeout_s=args.drain_timeout)
        print(
            "drain complete" if drained
            else "drain timeout: stragglers cancelled",
            flush=True,
        )

    signal.signal(signal.SIGTERM, _sigterm)
    try:
        server.wait()
    except KeyboardInterrupt:
        server.stop()


def build_server(args):
    """Repository scan + channel stack + InferenceServer (not started)
    from parsed ``main`` args — split out so tests and embedders can
    stand the server up on a loopback port without blocking in wait()."""
    from triton_client_tpu.channel.sharded_channel import ShardedTPUChannel
    from triton_client_tpu.channel.tpu_channel import TPUChannel
    from triton_client_tpu.cli.common import parse_mesh
    from triton_client_tpu.runtime.disk_repository import scan_disk
    from triton_client_tpu.runtime.server import InferenceServer

    repo = scan_disk(
        args.model_repository,
        precision=getattr(args, "precision", "") or None,
    )
    for name, version in repo.list_models():
        model = repo.get(name, version)
        policy = model.spec.extra.get("precision", "f32")
        print(
            f"loaded {name}:{version} ({model.spec.platform}, "
            f"precision={policy})"
        )
        if args.warmup and model.warmup is not None:
            model.warmup()

    if getattr(args, "fault_plan", ""):
        # CHAOS TESTING ONLY: a seeded, deterministic fault timeline
        # installed process-wide before the channel stack is built
        from triton_client_tpu.runtime.faults import (
            FaultPlan,
            install_fault_plan,
        )

        with open(args.fault_plan) as fh:
            plan = FaultPlan.from_json(fh.read())
        install_fault_plan(plan)
        print(
            f"FAULT PLAN ACTIVE (seed {plan.seed}, "
            f"{len(plan.rules)} rule(s)) — chaos testing only",
            flush=True,
        )

    # admission implies deadline shedding: an overload plane that
    # rejects at the door but still executes expired work would shed
    # the wrong requests
    shed = bool(getattr(args, "shed_expired", False)) or (
        getattr(args, "admission", 0) > 0
    )
    chan_kw = dict(
        shed_expired=shed,
        breaker_threshold=getattr(args, "breaker_threshold", 5),
        breaker_reset_s=getattr(args, "breaker_reset_s", 10.0),
    )
    mesh_config = parse_mesh(args.mesh)
    if args.mesh:
        # explicit --mesh: serve the whole mesh data-parallel — params
        # replicated, request batches sharded over the data axis
        channel = ShardedTPUChannel(repo, mesh_config=mesh_config, **chan_kw)
        print(
            f"mesh serving: {channel.stats()['mesh_devices']} devices, "
            f"data axis {channel.batch_multiple} "
            f"(batches shard over 'data'; params replicated)", flush=True,
        )
    else:
        channel = TPUChannel(repo, mesh_config=mesh_config, **chan_kw)
    base_channel = channel

    # multi-tenant model lifecycle: HBM-budgeted paging + tenant policy
    tenants = None
    tenants_path = getattr(args, "tenants", "") or ""
    if tenants_path:
        from triton_client_tpu.runtime.lifecycle import load_tenants

        tenants = load_tenants(tenants_path)
    lifecycle = None
    budget_mb = float(getattr(args, "hbm_budget", 0.0) or 0.0)
    if budget_mb > 0 or tenants is not None:
        from triton_client_tpu.runtime.lifecycle import ModelLifecycleManager

        lifecycle = ModelLifecycleManager(
            repo,
            budget_bytes=int(budget_mb * (1 << 20)),
            tenants=tenants,
        )
        base_channel.attach_lifecycle(lifecycle)
        print(
            f"model lifecycle: hbm_budget="
            f"{f'{budget_mb:g}MB' if budget_mb > 0 else 'unlimited'} "
            f"tenants={len(tenants.tenants()) if tenants else 0} "
            "(models page in on demand, evict LRU-within-priority)",
            flush=True,
        )
    # streaming sessions: device-resident per-stream tracker state keyed
    # by the KServe sequence_id parameter (runtime/sessions.py)
    max_sessions = int(getattr(args, "max_sessions", 64) or 0)
    sessions = None
    if max_sessions > 0 and hasattr(base_channel, "attach_sessions"):
        from triton_client_tpu.runtime.sessions import SessionManager

        sessions = SessionManager(
            max_sessions=max_sessions,
            ttl_s=float(getattr(args, "session_ttl_s", 60.0)),
            id_namespace=int(getattr(args, "session_id_namespace", 0)),
        )
        base_channel.attach_sessions(sessions)
        print(
            f"streaming sessions: max_sessions={max_sessions} "
            f"ttl={float(getattr(args, 'session_ttl_s', 60.0)):g}s "
            f"id_namespace={int(getattr(args, 'session_id_namespace', 0))} "
            "(device-resident tracking keyed by sequence_id)",
            flush=True,
        )
    if args.batching:
        from triton_client_tpu.runtime.batching import BatchingChannel
        from triton_client_tpu.runtime.continuous import (
            ContinuousBatchingChannel,
        )

        # getattr: embedders build the args Namespace by hand
        # (tests/test_serve_cli.py) and may predate these knobs
        batcher = getattr(args, "batcher", "continuous")
        cls = (
            ContinuousBatchingChannel if batcher == "continuous"
            else BatchingChannel
        )
        # --batch-timeout-us: None means "not given" (window default
        # 2000us). An EXPLICIT value on the continuous path used to be
        # silently ignored; warn once instead, pointing at the doc
        timeout_us = getattr(args, "batch_timeout_us", None)
        if timeout_us is not None and batcher == "continuous":
            global _timeout_warned
            if not _timeout_warned:
                _timeout_warned = True
                log.warning(
                    "--batch-timeout-us is deprecated with the "
                    "continuous scheduler and has no effect (there is "
                    "no admission window); see docs/OPERATIONS.md "
                    "section 'Migration — the window-timeout knob'"
                )
        channel = cls(
            channel,
            max_batch=args.max_batch,
            timeout_us=timeout_us if timeout_us is not None else 2000,
            pipeline_depth=args.pipeline_depth,
            max_merge=getattr(args, "max_merge", None),
            # continuous always bucket-pads its dense fallback — the
            # buckets come from the live occupancy table, so the pad
            # tax is bounded without the static pow2 ladder
            pad_to_buckets=(
                batcher == "continuous"
                or getattr(args, "pad_buckets", False)
            ),
            merge_hold_us=getattr(args, "merge_hold_us", 0),
            shed_expired=shed,
        )
        if tenants is not None and batcher == "continuous":
            # deficit-round-robin fair share folded into the EDF ready
            # ordering, weighted by each tenant's share
            channel.attach_tenants(tenants)
        timeout_note = (
            "windowless" if batcher == "continuous"
            else f"timeout={timeout_us if timeout_us is not None else 2000}us"
        )
        print(
            f"micro-batching[{batcher}]: max_batch={args.max_batch} "
            f"{timeout_note} "
            f"pipeline_depth={args.pipeline_depth} "
            # default merge cap scales with the inner channel's data
            # axis: max_batch frames per device
            f"max_merge={getattr(args, 'max_merge', None) or args.max_batch * getattr(channel.inner, 'batch_multiple', 1)} "
            # the EFFECTIVE value: continuous always bucket-pads its
            # dense fallback regardless of the flag
            f"pad_buckets={batcher == 'continuous' or getattr(args, 'pad_buckets', False)}",
            flush=True,
        )
    # continuous quality plane: shadow-scored online accuracy + canary
    # routing. Armed by --quality-sample > 0 or any --canary spec; the
    # mirror dispatches through the server's own channel stack (wired
    # inside InferenceServer), so shadow work queues behind live work.
    quality = None
    canary_specs = list(getattr(args, "canary", []) or [])
    sample_rate = float(getattr(args, "quality_sample", 0.0) or 0.0)
    if canary_specs and sample_rate <= 0.0:
        # a canary without samples would never score a window — arm a
        # rate high enough that promotion happens in human time
        sample_rate = 0.25
    if sample_rate > 0.0:
        from triton_client_tpu.eval.quality_plane import (
            QualityPlane,
            infer_primary,
            parse_canary_spec,
            precision_of_name,
        )

        def _precision_of(variant):
            # the repo's own precision tag wins over name sniffing
            try:
                return repo.get(variant, "").spec.extra.get(
                    "precision"
                ) or precision_of_name(variant)
            except Exception:
                return precision_of_name(variant)

        quality = QualityPlane(
            sample_rate=sample_rate,
            window_frames=getattr(args, "quality_window", 32),
            promote_after=getattr(args, "quality_promote_after", 3),
            precision_of=_precision_of,
            pin_fused_off=bool(
                getattr(args, "quality_pin_fused_off", False)
            ),
        )
        names = [name for name, _ in repo.list_models()]
        for spec in canary_specs:
            primary, variant, fraction = parse_canary_spec(spec)
            if primary is None:
                primary = infer_primary(variant, names)
            if primary is None:
                raise SystemExit(
                    f"--canary {spec}: cannot infer the primary model "
                    f"from {variant!r}; use the primary:variant=fraction "
                    "form"
                )
            quality.set_canary(primary, variant, fraction)
            print(
                f"canary armed: {primary} -> {variant} at "
                f"{fraction * 100:g}% of traffic "
                f"(promote after {getattr(args, 'quality_promote_after', 3)}"
                " clean windows, auto-rollback on budget violation)",
                flush=True,
            )
        print(
            f"quality plane: sample_rate={sample_rate:g} "
            f"window_frames={getattr(args, 'quality_window', 32)} "
            "(shadow-scored online mAP/velocity/ID-switch vs the f32 "
            "reference; tpu_quality_* families)",
            flush=True,
        )
    # temporal compute reuse: per-stream keyframe scheduling + ROI
    # partial recompute, riding the session plane (ISSUE 19). The plane
    # dispatches tile sub-requests at the TOP of the channel stack so
    # the continuous batcher can pack them across streams.
    temporal = None
    t_mode = getattr(args, "temporal_reuse", "off") or "off"
    if t_mode != "off" and sessions is not None:
        from triton_client_tpu.runtime.temporal import (
            TemporalReuseConfig,
            TemporalReusePlane,
        )

        def _extra_of(name):
            try:
                return repo.get(name, "").spec.extra
            except Exception:
                return None

        t_cfg = TemporalReuseConfig(
            mode=t_mode,
            k_max=max(1, int(getattr(args, "temporal_k_max", 8))),
            tile=max(1, int(getattr(args, "temporal_tile", 8))),
            forced_k=max(0, int(getattr(args, "temporal_forced_k", 0))),
        )
        temporal = TemporalReusePlane(
            sessions, config=t_cfg, channel=channel,
            spec_extra_fn=_extra_of,
        )
        print(
            f"temporal reuse: mode={t_cfg.mode} "
            f"k=[{t_cfg.k_min},{t_cfg.k_max}] tile={t_cfg.tile} "
            + (f"forced_k={t_cfg.forced_k} " if t_cfg.forced_k else "")
            + "(keyframe scheduling + ROI partial recompute; coast "
            "frames skip the detector, charged per-stream in the "
            "device-time ledger)",
            flush=True,
        )
    elif t_mode != "off":
        print(
            "temporal reuse requested but sessions are disabled "
            "(--max-sessions 0); ignoring --temporal-reuse",
            flush=True,
        )
    uds = getattr(args, "uds", "auto") or "off"
    return InferenceServer(
        repo,
        channel,
        address=args.address,
        uds_address=None if uds == "off" else uds,
        max_workers=args.max_workers,
        metrics_port=args.metrics_port,
        trace_capacity=getattr(args, "trace_capacity", 256),
        slo_ms=getattr(args, "slo_ms", 0.0),
        slo_tail_capacity=getattr(args, "slo_tail_capacity", 64),
        admission_max_queue=getattr(args, "admission", 0),
        admission_concurrency=getattr(args, "admission_concurrency", 4),
        lifecycle=lifecycle,
        tenants=tenants,
        replica_of=getattr(args, "replica_of", "") or None,
        op_sample_interval_s=getattr(args, "op_sample_interval", 0.0),
        op_sample_window_s=getattr(args, "op_sample_window", 0.2),
        history_interval_s=getattr(args, "history_interval", 10.0),
        history_capacity=getattr(args, "history_capacity", 360),
        history_path=getattr(args, "history_path", "") or None,
        quality=quality,
        temporal=temporal,
    )


if __name__ == "__main__":
    main()
