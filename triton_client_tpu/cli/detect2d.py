"""2D detection entry point.

The composition is the reference's main.py:116-139 triple — client
(model pipeline) + channel + inference driver — with the remote Triton
hop replaced by the in-process TPU channel. ``--input ros:<topic>``
selects the live ROS adapter when rospy is available; anything else is
pull-driven replay (bag2d.py semantics).

Usage:
  python -m triton_client_tpu.cli.detect2d -m yolov5n -i ./frames --sink images
  python -m triton_client_tpu.cli.detect2d -m yolov4 -i synthetic:64 --gt gt.jsonl
"""

from __future__ import annotations

import argparse
import dataclasses

import jax

from triton_client_tpu.cli.common import (
    _check_async_flags,
    add_common_flags,
    parse_dtype,
    load_gt_lookup,
    load_names,
    make_profiler,
    make_sink,
    maybe_device_trace,
    parse_mesh,
    print_report,
)


def _run_streaming(args, channel, spec, class_names) -> None:
    """Pump every source frame through ONE bidirectional
    ModelStreamInfer stream and sink responses as they arrive — requests
    pipeline instead of blocking one round-trip per frame."""
    import time

    import numpy as np

    from triton_client_tpu.channel.base import InferRequest
    from triton_client_tpu.drivers.driver import latency_stats
    from triton_client_tpu.io.sources import open_source

    if args.input.startswith("ros:"):
        raise SystemExit("--streaming is replay-mode only; drop it for ros:")

    source = open_source(args.input, args.limit)
    frames = iter(source)
    first = next(frames, None)
    if first is None:
        raise SystemExit("input source is empty")
    # Warmup through the unary path so the server-side jit compile
    # (minutes cold on TPU) never lands in the streamed latency stats —
    # matching the InferenceDriver/MultiCameraDriver methodology.
    for _ in range(args.warmup):
        channel.do_inference(
            InferRequest(
                model_name=args.model_name,
                model_version=args.model_version,
                inputs={"images": np.asarray(first.data)[None]},
            )
        )

    in_flight = {}
    sent = {}

    def req_iter():
        import itertools

        for i, frame in enumerate(itertools.chain([first], frames)):
            if args.limit and i >= args.limit:
                break
            rid = str(i)
            in_flight[rid] = frame
            sent[rid] = time.perf_counter()
            yield InferRequest(
                model_name=args.model_name,
                model_version=args.model_version,
                inputs={"images": np.asarray(frame.data)[None]},
                request_id=rid,
            )

    sink = make_sink(args, class_names)
    latencies = []
    n = 0
    t0 = time.perf_counter()
    stream_timeout = args.stream_timeout_s if args.stream_timeout_s > 0 else None
    try:
        for resp in channel.infer_stream(
            req_iter(), stream_timeout_s=stream_timeout
        ):
            latencies.append(time.perf_counter() - sent.pop(resp.request_id))
            frame = in_flight.pop(resp.request_id)
            out = {
                k: (v[0] if np.ndim(v) > 0 and np.shape(v)[0] == 1 else v)
                for k, v in resp.outputs.items()
            }
            sink.write(frame, out)
            n += 1
    finally:
        sink.close()
    wall = time.perf_counter() - t0
    print_report(
        latency_stats(latencies, frames=n, wall_s=wall, ticks=n),
        None,
        {"model": spec.name, "streaming": True},
    )


def _run_multicam(args, channel, spec, class_names) -> None:
    """Lockstep N-camera batch serving over the mesh data axis."""
    import copy
    import os

    from triton_client_tpu.channel.base import InferRequest
    from triton_client_tpu.drivers.multicam import MultiCameraDriver
    from triton_client_tpu.io.sources import open_source

    if args.gt:
        raise SystemExit(
            "--gt is single-stream only; run the evaluation pass without "
            "--cameras (accuracy is camera-independent)"
        )
    if args.input.startswith("ros:"):
        raise SystemExit(
            "--cameras is replay/synthetic-only for now; live multi-topic "
            "ROS batching needs one subscriber per topic (run one "
            "detect2d per topic, or drop --cameras)"
        )

    sources = [
        open_source(args.input, args.limit) for _ in range(args.cameras)
    ]
    profiler = make_profiler(args)

    def infer(inputs):
        resp = channel.do_inference(
            InferRequest(
                model_name=args.model_name or spec.name,
                model_version=args.model_version,
                inputs=inputs,
            )
        )
        return resp.outputs

    if profiler is not None:
        infer = profiler.wrap("infer_batch", infer)

    # One sink per camera rooted at <output>/cam<i>/ so per-camera
    # outputs never collide on shared frame-numbered filenames.
    sinks = []
    for ci in range(args.cameras):
        cam_args = copy.copy(args)
        cam_args.output = os.path.join(args.output, f"cam{ci}")
        sinks.append(make_sink(cam_args, class_names))

    def cam_sink(ci, frame, result):
        sinks[ci].write(frame, result)

    driver = MultiCameraDriver(infer, sources, sink=cam_sink, warmup=args.warmup)
    try:
        with maybe_device_trace(args):
            stats = driver.run(max_ticks=args.limit)
    finally:
        # flush buffered sinks even when infer raises mid-run (the
        # single-stream driver closes its sink in a finally too)
        for sink in sinks:
            sink.close()
    if profiler is not None:
        import sys

        print(profiler.report(), file=sys.stderr)
    print_report(stats, None, {"model": spec.name, "cameras": args.cameras})


def parse_args(argv=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(description=__doc__)
    add_common_flags(parser)
    parser.add_argument(
        "--mesh", default="",
        help="device mesh for the in-process channel, e.g. 'data=4' "
        "(multi-camera DP serving) or 'data=4,model=2'",
    )
    parser.add_argument(
        "--cameras", type=int, default=1,
        help="replicate the input source N times and run the lockstep "
        "multi-camera driver: one (N, H, W, 3) batch per tick, sharded "
        "over the mesh data axis (the reference's 'ensemble "
        "multi-camera' serving, README.md:119)",
    )
    parser.add_argument(
        "--stream-timeout-s", type=float, default=3600.0,
        help="whole-stream deadline for --streaming (0 = unbounded for "
        "long-lived live sessions)",
    )
    parser.add_argument(
        "--input-size", type=int, default=512, help="model input H=W (reference 512)"
    )
    # None -> per-model reference defaults (yolov5: 0.3/0.45
    # ros_inference.py:148; yolov4: 0.4/0.6 tools/utils.py post_processing)
    parser.add_argument("--conf", type=float, default=None)
    parser.add_argument("--iou", type=float, default=None)
    parser.add_argument(
        "--width", type=float, default=1.0, help="YOLOv4 width multiple"
    )
    parser.add_argument(
        "--mxu-opt", action="store_true",
        help="yolov5 only: space-to-depth stem + 32-channel floor — the "
        "MXU-shaped layout (+16%% at b8 on a v5e chip, measured). Same "
        "detection function; upstream weights import losslessly",
    )
    args = parser.parse_args(argv)
    # keep the raw argv so --repo guards can tell an explicitly passed
    # flag from a parser default (cli/common.flags_given)
    import sys

    args.argv = list(argv) if argv is not None else sys.argv[1:]
    return args


def build(args):
    """Model name -> (pipeline, spec). yolov5{n,s,m,l,x}, yolov4,
    retinanet[_<depth>] or fcos[_<depth>] (depth: tiny|resnet18|34|50).
    With --repo, the model is instead loaded from the repository entry
    (trained weights + its config.yaml; --conf/--iou still override)."""
    if args.repo:
        from triton_client_tpu.cli.common import flags_given, load_repo_pipeline

        overrides = {}
        if args.conf is not None:
            overrides["conf_thresh"] = args.conf
        if args.iou is not None:
            overrides["iou_thresh"] = args.iou
        argv = getattr(args, "argv", None)
        return load_repo_pipeline(
            args, overrides, "2d",
            conflicts={
                "--input-size": flags_given(argv, "--input-size"),
                "--classes": flags_given(argv, "-c", "--classes"),
                "--width": flags_given(argv, "--width"),
                "--scaling": flags_given(argv, "-s", "--scaling"),
                "--dtype": flags_given(argv, "--dtype"),
                "--mxu-opt": args.mxu_opt,
            },
        )
    from triton_client_tpu.pipelines.detect2d import (
        Detect2DConfig,
        build_fcos_pipeline,
        build_retinanet_pipeline,
        build_yolov4_pipeline,
        build_yolov5_pipeline,
    )

    name = args.model_name or "yolov5n"
    hw = (args.input_size, args.input_size)
    is_v4 = name == "yolov4"
    cfg = Detect2DConfig(
        model_name=name,
        input_hw=hw,
        num_classes=args.classes,
        conf_thresh=args.conf if args.conf is not None else (0.4 if is_v4 else 0.3),
        iou_thresh=args.iou if args.iou is not None else (0.6 if is_v4 else 0.45),
        scaling=args.scaling,
    )
    if name.startswith("yolov5"):
        variant = name[len("yolov5") :] or "n"
        pipe, spec, _ = build_yolov5_pipeline(
            jax.random.PRNGKey(0),
            variant=variant,
            num_classes=args.classes,
            input_hw=hw,
            config=cfg,
            dtype=parse_dtype(args.dtype),
            s2d=args.mxu_opt,
            ch_floor=32 if args.mxu_opt else 0,
        )
    elif args.mxu_opt:
        raise SystemExit("--mxu-opt is yolov5-only")
    elif name == "yolov4":
        pipe, spec, _ = build_yolov4_pipeline(
            jax.random.PRNGKey(0),
            num_classes=args.classes,
            width=args.width,
            input_hw=hw,
            config=cfg,
            dtype=parse_dtype(args.dtype),
        )
    elif name.partition("_")[0] in ("retinanet", "fcos"):
        from triton_client_tpu.models.retinanet import RESNET_DEPTHS

        base, _, depth = name.partition("_")
        depth = depth or "resnet50"
        if depth not in RESNET_DEPTHS:
            raise SystemExit(
                f"unknown backbone depth '{depth}' (choose from {sorted(RESNET_DEPTHS)})"
            )
        builder = build_retinanet_pipeline if base == "retinanet" else build_fcos_pipeline
        # Detectron family: no /255 scaling, detectron2 test thresholds,
        # reference input 640x480 (RetinaNet_detectron/config.pbtxt:3-8).
        cfg = dataclasses.replace(
            cfg,
            conf_thresh=args.conf if args.conf is not None else 0.05,
            # Per-model detectron2 test-time NMS: 0.5 retinanet, 0.6 fcos.
            iou_thresh=args.iou
            if args.iou is not None
            else (0.5 if base == "retinanet" else 0.6),
            max_det=100,
            scaling="none",
            multi_label=True,
            head_style="scored",
        )
        pipe, spec, _ = builder(
            jax.random.PRNGKey(0),
            num_classes=args.classes,
            depth=depth,
            input_hw=hw,
            config=cfg,
            dtype=parse_dtype(args.dtype),
        )
    else:
        raise SystemExit(
            f"unknown 2D model '{name}' "
            "(yolov5[nsmlx] | yolov4 | retinanet[_depth] | fcos[_depth])"
        )
    return pipe, spec


def main(argv=None) -> None:
    args = parse_args(argv)
    if args.sink == "bag":
        raise SystemExit(
            "--sink bag is 3D-only (the output bag carries point clouds + "
            "jsk box arrays, bag_inference3d.py:182-183); use --sink "
            "images or jsonl"
        )
    if args.async_set:
        _check_async_flags(args)
    from triton_client_tpu.drivers.driver import InferenceDriver, channel_infer

    if args.channel.startswith("grpc:"):
        # Remote mode: the reference's actual topology — model runs in
        # the serving process, this client only decodes/draws/publishes.
        if not args.model_name:
            raise SystemExit("--channel grpc:... requires -m/--model-name")
        if args.repo:
            raise SystemExit(
                "--repo is in-process mode; in remote mode the SERVER "
                "loads the repository (serve -r ...)"
            )
        if args.conf is not None or args.iou is not None:
            # Thresholds are baked into the SERVER's jitted pipeline
            # (repo entry config.yaml) — same guard as detect3d's.
            raise SystemExit(
                "--conf/--iou are server-side in remote mode: set them in "
                "the model repository entry's config.yaml"
            )
        if args.mesh:
            raise SystemExit(
                "--mesh is server-side in remote mode: pass it to "
                "'serve --mesh ...' instead"
            )
        from triton_client_tpu.channel.grpc_channel import GRPCChannel

        channel = GRPCChannel(
            args.channel[len("grpc:"):],
            use_shared_memory=args.use_shared_memory,
        )
        spec = channel.get_metadata(args.model_name, args.model_version)
        class_names = load_names(args.names) or tuple(
            spec.extra.get("class_names", ())
        )
        if args.streaming:
            # the reference defines --streaming but never exercises it
            # (main.py:66-70); here it is the pipelined ModelStreamInfer
            # path: requests flow while earlier responses are in flight.
            if args.gt:
                raise SystemExit(
                    "--gt is unary-mode only; drop --streaming to evaluate"
                )
            if args.cameras > 1:
                raise SystemExit(
                    "--cameras batches locally; it does not combine with "
                    "--streaming"
                )
            if args.profile or args.profile_trace:
                raise SystemExit(
                    "--profile/--profile-trace are not wired for the "
                    "streaming path yet; per-request latency is already "
                    "in the report"
                )
            _run_streaming(args, channel, spec, class_names)
            return
        infer = channel_infer(
            channel,
            args.model_name,
            model_version=args.model_version,
            asynchronous=args.async_set,
        )
    else:
        if args.streaming:
            raise SystemExit(
                "--streaming is the remote ModelStreamInfer path; use "
                "-u grpc:<host:port> (in-process inference has no wire "
                "to stream over)"
            )
        pipe, spec = build(args)
        # --names wins; a --repo entry's own class vocabulary (its
        # config.yaml class_names_file) labels sinks like the grpc
        # path's served metadata does
        class_names = load_names(args.names) or tuple(
            spec.extra.get("class_names", ())
        )

        from triton_client_tpu.channel.tpu_channel import TPUChannel
        from triton_client_tpu.runtime.repository import ModelRepository

        repo = ModelRepository()
        repo.register(spec, pipe.infer_fn())
        channel = TPUChannel(repo, mesh_config=parse_mesh(args.mesh))
        infer = channel_infer(channel, spec.name, asynchronous=args.async_set)

    if args.cameras > 1:
        _run_multicam(args, channel, spec, class_names)
        return

    if args.input.startswith("ros:"):
        from triton_client_tpu.drivers import ros

        node = ros.RosDetect2D(
            infer,
            sub_topic=args.input[len("ros:") :],
            pub_topic="/tpu_detections/image",
            class_names=class_names,
        )
        node.spin()
        return

    from triton_client_tpu.io.sources import open_source

    source = open_source(args.input, args.limit)
    evaluator = gt_lookup = None
    if args.gt:
        from triton_client_tpu.eval import DetectionEvaluator

        evaluator = DetectionEvaluator()
        gt_lookup = load_gt_lookup(args.gt)

    profiler = make_profiler(args)
    driver = InferenceDriver(
        infer,
        source,
        sink=make_sink(args, class_names),
        prefetch=max(args.prefetch, args.batch_size),
        warmup=args.warmup,
        evaluator=evaluator,
        gt_lookup=gt_lookup,
        profiler=profiler,
        batch_size=args.batch_size,
        inflight=args.inflight if args.async_set else 1,
    )
    with maybe_device_trace(args):
        stats = driver.run(max_frames=args.limit)
    if profiler is not None:
        import sys

        print(profiler.report(), file=sys.stderr)
    summary = evaluator.summary() if evaluator is not None else None
    print_report(stats, summary, {"model": spec.name})
    if summary is not None and args.prometheus_port > 0:
        # Keep the process (and the metrics HTTP server) alive so a
        # Prometheus scrape can actually happen — the reference exporter
        # lives inside a long-running ROS node (evaluate_inference.py:52).
        import sys
        import time as _time

        from triton_client_tpu.eval.prometheus_export import EvalPrometheusExporter

        exporter = EvalPrometheusExporter(args.prometheus_port)
        for frame_stats in evaluator.per_frame_summaries():
            exporter.observe(*frame_stats)
        print(
            f"serving eval metrics on :{args.prometheus_port}; Ctrl-C to exit",
            file=sys.stderr,
        )
        try:
            while True:
                _time.sleep(3600)
        except KeyboardInterrupt:
            pass


if __name__ == "__main__":
    main()
