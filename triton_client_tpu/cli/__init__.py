"""CLI entry points (L5): ``python -m triton_client_tpu.cli.detect2d``
etc., mirroring the reference's six entry scripts (main.py, main3d.py,
bag2d.py, bag3d.py, evaluate.py, yolo_onnx_test.py — SURVEY.md section 2
#1-3). One flag set serves live/replay: the input source string picks
the mode (directory, video, synthetic, or ros:<topic>)."""
