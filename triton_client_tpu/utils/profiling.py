"""Deprecated shim: the timing substrate moved to ``obs/profiling.py``.

ISSUE 11 folded the legacy stage timers into the ``obs`` observability
package so there is one timing substrate next to the request tracer
(obs/trace.py) and the device-time ledger (obs/device_time.py). This
module re-exports the public names so existing imports keep working;
new code should import from ``triton_client_tpu.obs.profiling``.
"""

from __future__ import annotations

import warnings

from triton_client_tpu.obs.profiling import (  # noqa: F401
    _BUCKETS,
    PrometheusStageExporter,
    StageProfiler,
    annotate,
    device_trace,
)

warnings.warn(
    "triton_client_tpu.utils.profiling moved to "
    "triton_client_tpu.obs.profiling; this shim will be removed",
    DeprecationWarning,
    stacklevel=2,
)
