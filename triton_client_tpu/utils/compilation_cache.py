"""Persistent XLA compilation cache shared by bench/perf/entry paths.

The remote-chip tunnel charges 40-250 s per fresh compile (BENCH_r03:
b64 warmup alone was 243 s and the full warmup bill ~902 s — more than
the driver's whole bench budget). jax's persistent cache keys serialized
executables by HLO + backend, so a second process on the same rig pays
only deserialization (measured here: an 8.1 s first-call drops to
1.8 s). Every entry point that compiles the flagship pipelines calls
:func:`enable_persistent_cache` first so one process's compile bill is
every later process's warm start.

Reference analogue: Triton caches TensorRT engines next to the model
repository for the same reason (first-load autotuning is minutes).
"""

from __future__ import annotations

import os
import pathlib

_DEFAULT_DIR = pathlib.Path(__file__).resolve().parents[2] / ".jax_cache"


def _accelerator_plugin_present() -> bool:
    """True when a non-CPU jax backend could load: libtpu on the path
    or any PJRT plugin advertised via the 'jax_plugins' entry-point
    group / namespace package. Never imports or initializes a backend."""
    import importlib.metadata
    import importlib.util

    try:
        if importlib.util.find_spec("libtpu") is not None:
            return True
        if importlib.util.find_spec("jax_plugins") is not None:
            return True
        return bool(list(importlib.metadata.entry_points(group="jax_plugins")))
    except Exception:
        return False


def enable_persistent_cache(cache_dir: str | os.PathLike | None = None) -> str:
    """Point jax at the repo-local persistent compilation cache.

    Safe to call more than once and before or after backend init;
    honors an explicit ``JAX_COMPILATION_CACHE_DIR`` from the
    environment over the repo default. Returns the directory used —
    or ``""`` when skipped: on CPU-selected platforms the default
    cache is NOT enabled (compiles are seconds there, and XLA:CPU AOT
    reloading is picky about machine-feature flags — observed
    'prefer-no-gather not supported ... could lead to SIGILL'
    warnings reloading this same box's own artifacts). An explicit
    ``cache_dir`` argument or env var is an opt-in and wins anyway.
    """
    import jax

    explicit = cache_dir or os.environ.get("JAX_COMPILATION_CACHE_DIR")
    # platform read WITHOUT initializing the backend (default_backend()
    # would commit the platform choice and break callers that select
    # cpu after this returns)
    selected = (
        getattr(jax.config, "jax_platforms", None)
        or os.environ.get("JAX_PLATFORMS")
        or ""
    )
    if not explicit:
        if selected.split(",")[0] == "cpu":
            return ""
        # No platform selected at all: a host with no accelerator
        # plugin will default to CPU too — same SIGILL hazard, so the
        # same gate applies (plugin presence checked without importing
        # or initializing anything backend-side).
        if not selected and not _accelerator_plugin_present():
            return ""

    path = str(explicit or _DEFAULT_DIR)
    pathlib.Path(path).mkdir(parents=True, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # default thresholds skip sub-second / small entries; over the
    # tunnel even those compiles cost a round trip, so cache everything
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return path
