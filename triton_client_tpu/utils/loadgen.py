"""Closed- and open-loop gRPC load generators for serving benchmarks.

The role Triton's ``perf_analyzer`` plays in the reference's ecosystem
(its README benchmarks the server with concurrent closed-loop clients):
N threads, each with its own channel, issuing one synchronous
ModelInfer after another against a KServe v2 endpoint, with a
warm-before-measure barrier so neither thread ramp nor first-request
compiles bias the measured window. Used by ``bench.measure_serving``
and ``perf/profile_serving.py`` so both measure the SAME protocol.

Client lifecycle per thread:
  1. staggered connect + one warm request (staggering avoids N
     simultaneous payload uploads blowing deadlines on a small host);
  2. barrier — every thread arrives, warmed or failed;
  3. closed loop until ``stop`` is set, per-request latency recorded;
  4. channel closed (unregisters any shared-memory regions), counts
     merged under a lock.

``run_pool`` returns after EVERY client thread has fully exited — a
straggler blocked on a slow request is waited out (bounded by the
request deadline), never left running into a subsequent measurement.

Open-loop mode (round 11, the MLPerf-Inference "server scenario"
discipline): ``run_pool``'s closed loop is the wrong instrument for
capacity questions — each client waits for its response before sending
the next request, so when the server slows down the offered load
politely slows down with it and queueing collapse is invisible
(coordinated omission). ``run_open_loop`` issues requests on a SEEDED
Poisson schedule that does not care how the server is doing: arrivals
are pre-generated (``poisson_schedule``), the dispatcher never blocks
on a response, and every latency is measured from the request's
SCHEDULED arrival time — a request issued late because the dispatcher
fell behind still charges the server for the wait. Unanswered or
failed requests score as +Inf in the percentile math
(``co_percentile``), so saturation reads as a blown p99, never as a
quietly shrunk sample set. ``slo_capacity_search`` binary-searches the
offered rate for the MLPerf headline number: max qps at p99 <= SLO.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


@dataclass
class PoolResult:
    served_frames: int
    wall_s: float
    latencies_ms: list = field(default_factory=list)
    errors: list = field(default_factory=list)

    @property
    def fps(self) -> float:
        return self.served_frames / self.wall_s if self.wall_s > 0 else 0.0


def run_pool(
    address: str,
    model_name: str,
    inputs: dict,
    clients: int,
    duration_s: float,
    deadline_s: float = 300.0,
    use_shared_memory: bool | None = None,
    stagger_s: float = 0.25,
    on_window_start=None,
    mode: str = "unary",
    inflight: int = 1,
    stream_group: int = 1,
) -> PoolResult:
    """Drive ``clients`` closed-loop threads for ``duration_s`` and
    return counts/latencies. ``on_window_start`` fires after the warm
    barrier, immediately before the timed window — the hook for
    clearing server-side accounting (batcher stats, occupancy taps).

    ``mode`` selects the client protocol (round 5 — puts numbers on
    the reference's dead --streaming/--async flags, main.py:59-70):
      * 'unary'  — one synchronous ModelInfer per iteration (default);
      * 'stream' — ONE long-lived ModelStreamInfer session per client,
        ``inflight`` requests pipelined inside it (latency = send ->
        matching response; responses preserve order on a stream);
      * 'async'  — ModelInfer call-futures with ``inflight`` in the
        air per client (the --async --inflight N path).

    ``use_shared_memory=None`` (default) lets each channel
    auto-negotiate its transport from the endpoint — shm on loopback /
    unix: targets, plain wire otherwise; pass True/False to pin it.

    ``stream_group`` (stream mode only) packs that many frames into one
    ModelStreamInfer message (the multi-frame group protocol); it is
    clamped to ``inflight`` because a closed-loop client can never have
    more than ``inflight`` frames buffered toward a group.
    """
    from triton_client_tpu.channel.base import InferRequest
    from triton_client_tpu.channel.grpc_channel import GRPCChannel

    if mode not in ("unary", "stream", "async"):
        raise ValueError(f"unknown pool mode {mode!r}")
    inflight = max(1, int(inflight))
    # a group can only fill from frames the closed loop has in flight
    stream_group = max(1, min(int(stream_group), inflight))

    served: list = []
    latencies: list = []
    errors: list = []
    lock = threading.Lock()
    stop = threading.Event()
    ready = threading.Barrier(clients + 1)
    # the warm phase is bounded by one request deadline plus the
    # connect stagger: a hard-coded barrier timeout shorter than
    # deadline_s (bench sizes that from measured device time — 320 s+
    # on a ~1 s/dispatch rig) broke the barrier while a slow warm was
    # still legitimate, and the pool leaked running clients into the
    # next transport's measurement
    barrier_timeout_s = deadline_s + stagger_s * clients + 60.0

    def client_loop(idx: int):
        n, mine = 0, []  # n counts only completions INSIDE the window
        chan = req = None
        try:
            time.sleep(stagger_s * (idx % 4))
            chan = GRPCChannel(
                address,
                timeout_s=deadline_s,
                use_shared_memory=use_shared_memory,
            )
            req = InferRequest(model_name=model_name, inputs=inputs)
            chan.do_inference(req)  # connection + server path warm
        except Exception as e:
            with lock:
                errors.append(repr(e))
            chan = None
        try:
            # EVERY thread reaches the barrier, warm or not — a failed
            # warm must not strand the caller's wait
            ready.wait(timeout=barrier_timeout_s)
        except threading.BrokenBarrierError:
            pass
        try:
            if chan is not None and mode == "unary":
                while not stop.is_set():
                    t0 = time.perf_counter()
                    chan.do_inference(req)
                    mine.append((time.perf_counter() - t0) * 1e3)
                    # a completion racing the window close (the final
                    # in-flight request) is drained but NOT counted —
                    # fps must be completions-in-window / window, not
                    # diluted by the post-stop drain time
                    if not stop.is_set():
                        n += 1
            elif chan is not None and mode == "stream":
                import queue as _q

                sent: _q.Queue = _q.Queue(maxsize=inflight)

                def gen():
                    # closed-loop through the stream: the bounded queue
                    # caps in-flight requests; put blocks until a
                    # response frees a slot. The timestamp is taken
                    # AFTER the slot is granted, immediately before the
                    # request goes to gRPC — timing the backpressure
                    # wait would double-count the previous in-flight
                    # request's latency
                    while not stop.is_set():
                        cell = [0.0]
                        sent.put(cell)
                        cell[0] = time.perf_counter()
                        yield req

                for _resp in chan.infer_stream(
                    gen(),
                    stream_timeout_s=deadline_s,
                    group_size=stream_group,
                ):
                    t0 = sent.get()[0]
                    mine.append((time.perf_counter() - t0) * 1e3)
                    if not stop.is_set():
                        n += 1
            elif chan is not None:  # async futures, inflight in the air
                from collections import deque

                air: deque = deque()
                while not stop.is_set():
                    while len(air) < inflight and not stop.is_set():
                        air.append(
                            (time.perf_counter(), chan.do_inference_async(req))
                        )
                    if not air:  # stop raced the fill loop
                        break
                    t0, fut = air.popleft()
                    fut.result()
                    mine.append((time.perf_counter() - t0) * 1e3)
                    if not stop.is_set():
                        n += 1
                while air:  # drain, uncounted
                    air.popleft()[1].result()
        except Exception as e:  # a dying client must still report
            with lock:
                errors.append(repr(e))
        finally:
            if chan is not None:
                try:
                    chan.close()
                except Exception:
                    pass
            with lock:
                served.append(n)
                latencies.extend(mine)

    threads = [
        threading.Thread(target=client_loop, args=(i,)) for i in range(clients)
    ]
    for t in threads:
        t.start()
    wall = 0.0
    try:
        try:
            ready.wait(timeout=barrier_timeout_s)
        except threading.BrokenBarrierError as e:
            # a broken barrier aborts the window but must NOT skip the
            # stop/join in the finally — clients swallow
            # BrokenBarrierError and enter their request loop, so
            # without stop.set() they would keep issuing requests into
            # the caller's next measurement until server teardown
            with lock:
                errors.append(f"warm barrier broke: {e!r}")
        else:
            if on_window_start is not None:
                on_window_start()
            t_start = time.perf_counter()
            time.sleep(duration_s)
            # the measured window closes HERE: stragglers are drained
            # in the finally so nothing survives into the caller's
            # next measurement, but their drain time must not dilute
            # the reported rate
            wall = time.perf_counter() - t_start
    finally:
        stop.set()
        # wait stragglers OUT: an in-flight request is bounded by the
        # gRPC deadline, so this join always terminates
        for t in threads:
            t.join(timeout=deadline_s + 60.0)
        alive = [t for t in threads if t.is_alive()]
        if alive:
            errors.append(
                f"{len(alive)} client threads still alive after join"
            )
    return PoolResult(
        served_frames=sum(served),
        wall_s=wall,
        latencies_ms=latencies,
        errors=errors,
    )


# -- open-loop (MLPerf server-scenario) driver --------------------------------


def poisson_schedule(
    rate_qps: float,
    duration_s: float,
    seed: int = 0,
    weights=None,
):
    """Seeded Poisson arrival plan: ``(offsets_s, scenario_idx)``.

    ``offsets_s`` are arrival times relative to window start
    (exponential inter-arrival gaps at ``rate_qps``); ``scenario_idx``
    picks a traffic-mix entry per arrival, proportional to ``weights``
    (all zeros when no mix). Pure function of its arguments — the same
    seed replays the identical request timeline, which is what makes an
    open-loop capacity number reproducible and the determinism test
    possible."""
    import numpy as np

    rate = float(rate_qps)
    if rate <= 0 or duration_s <= 0:
        empty = np.zeros(0)
        return empty, np.zeros(0, dtype=int)
    rng = np.random.default_rng(int(seed))
    offsets = np.zeros(0)
    draw = max(16, int(rate * duration_s * 1.5) + 32)
    last = 0.0
    while last < duration_s:
        gaps = rng.exponential(1.0 / rate, size=draw)
        offsets = np.concatenate([offsets, last + np.cumsum(gaps)])
        last = float(offsets[-1])
    offsets = offsets[offsets < duration_s]
    if weights is not None and len(weights) > 1:
        w = np.asarray(weights, dtype=float)
        picks = rng.choice(len(w), size=len(offsets), p=w / w.sum())
    else:
        picks = np.zeros(len(offsets), dtype=int)
    return offsets, picks


@dataclass
class OpenLoopResult:
    offered_qps: float
    scheduled: int
    completed: int
    wall_s: float
    # completion - SCHEDULED arrival (not actual send): a request the
    # dispatcher issued late still charges the server for the backlog
    latencies_ms: list = field(default_factory=list)
    errors: list = field(default_factory=list)

    @property
    def achieved_qps(self) -> float:
        return self.completed / self.wall_s if self.wall_s > 0 else 0.0

    def percentile(self, q: float) -> float:
        """Coordinated-omission-safe percentile over the SCHEDULED
        population: requests that never completed (errors, drops) rank
        as +Inf, so a saturated probe cannot launder its tail by
        shedding samples."""
        return co_percentile(self.latencies_ms, self.scheduled, q)

    def attainment(self, slo_ms: float) -> float:
        """Fraction of SCHEDULED requests that completed within
        ``slo_ms``."""
        if self.scheduled <= 0:
            return 1.0
        ok = sum(1 for v in self.latencies_ms if v <= slo_ms)
        return ok / self.scheduled

    def goodput_qps(self, slo_ms: float) -> float:
        """SLO-met completions per second of the scheduled window —
        the capacity number that matters under shedding: offered load
        the server *served within budget*, not load it survived."""
        if self.wall_s <= 0:
            return 0.0
        return sum(1 for v in self.latencies_ms if v <= slo_ms) / self.wall_s

    @property
    def shed_count(self) -> int:
        """Requests the server deliberately rejected with
        RESOURCE_EXHAUSTED (admission door / bounded queue) — distinct
        from transport faults in the same ``errors`` list."""
        return sum(
            1 for e in self.errors if "RESOURCE_EXHAUSTED" in str(e)
        )

    @property
    def shed_rate(self) -> float:
        """Shed fraction of the SCHEDULED population."""
        if self.scheduled <= 0:
            return 0.0
        return self.shed_count / self.scheduled


def co_percentile(latencies_ms, scheduled: int, q: float) -> float:
    """Percentile ``q`` (0..100) of ``latencies_ms`` ranked within a
    population of ``scheduled`` requests; the missing tail is +Inf."""
    n = max(int(scheduled), len(latencies_ms))
    if n <= 0:
        return 0.0
    import math

    rank = min(n, max(1, math.ceil(q / 100.0 * n)))
    lats = sorted(latencies_ms)
    return lats[rank - 1] if rank <= len(lats) else float("inf")


def _dial(target, deadline_s: float):
    """Resolve a loadgen target into ``(channel, owned)``.

    Three target shapes, so capacity numbers can be fleet numbers:
      * ``"host:port"`` — one endpoint, a fresh ``GRPCChannel``
        (owned: closed by the caller when the window ends);
      * ``["host:port", ...]`` — a replica set: a fresh
        ``FrontDoorRouter`` over the endpoints (owned);
      * a channel-shaped object (anything with ``do_inference_async``)
        — used as-is and NOT closed, so a caller-configured router
        (custom hedge/budget knobs, warm latency histogram) can be
        driven across several windows."""
    if isinstance(target, str):
        from triton_client_tpu.channel.grpc_channel import GRPCChannel

        return GRPCChannel(target, timeout_s=deadline_s), True
    if isinstance(target, (list, tuple)):
        from triton_client_tpu.runtime.router import FrontDoorRouter

        return FrontDoorRouter(list(target), timeout_s=deadline_s), True
    if hasattr(target, "do_inference_async"):
        return target, False
    raise TypeError(
        f"loadgen target must be an address, a list of addresses, or a "
        f"channel, not {type(target).__name__}"
    )


def run_open_loop(
    address,
    scenarios,
    rate_qps: float,
    duration_s: float,
    seed: int = 0,
    deadline_s: float = 60.0,
    warm: bool = True,
    resolvers: int = 16,
    request_factory=None,
) -> OpenLoopResult:
    """Drive one open-loop window against a KServe v2 endpoint — or a
    replica fleet.

    ``address`` is a ``_dial`` target: one endpoint string, a list of
    endpoint strings (routed through a ``FrontDoorRouter``), or an
    already-built channel/router instance (driven, not closed).

    ``scenarios``: the traffic mix — a list of ``(model_name, inputs)``
    or ``(model_name, inputs, weight)`` tuples; arrivals pick a
    scenario proportionally to weight (seeded, like the schedule).

    Dispatch discipline: ONE thread walks the pre-generated schedule,
    sleeping to each arrival and issuing via the non-blocking gRPC call
    future — it never waits for a response, so the offered rate is
    independent of server health. A bounded pool of resolver threads
    drains completions and records latency from the scheduled arrival.
    At heavy overload the pool itself queues, which can only OVERSTATE
    tail latency — the conservative direction for a capacity search.
    Completions after the window still count (with their true
    latency); ``wall_s`` is the scheduled window.

    ``request_factory``: optional per-arrival hook
    ``(base_request, arrival_index) -> InferRequest`` replacing the
    default reuse of one InferRequest per scenario. Quality-plane
    drives use it to stamp a deterministic per-arrival identity
    (request_id / traceparent) so hash-sampled canary slices are
    reproducible across runs; any exception falls back to the shared
    base request."""
    import queue as _q

    from triton_client_tpu.channel.base import InferRequest

    scenarios = [
        (s[0], s[1], float(s[2]) if len(s) > 2 else 1.0) for s in scenarios
    ]
    if not scenarios:
        raise ValueError("run_open_loop needs at least one scenario")
    offsets, picks = poisson_schedule(
        rate_qps, duration_s, seed=seed, weights=[s[2] for s in scenarios]
    )
    latencies: list = []
    errors: list = []
    completed = [0]
    lock = threading.Lock()
    pending: _q.Queue = _q.Queue()

    def resolve_loop() -> None:
        while True:
            item = pending.get()
            if item is None:
                return
            t_sched, fut = item
            try:
                fut.result()
            except Exception as e:
                with lock:
                    errors.append(repr(e))
                continue
            lat_ms = (time.perf_counter() - t_sched) * 1e3
            with lock:
                latencies.append(lat_ms)
                completed[0] += 1

    chan, owned = _dial(address, deadline_s)
    try:
        requests = [
            InferRequest(model_name=m, inputs=inputs)
            for m, inputs, _w in scenarios
        ]
        if warm:
            for req in requests:
                chan.do_inference(req)
        workers = [
            threading.Thread(
                target=resolve_loop, daemon=True, name=f"openloop-res-{i}"
            )
            for i in range(max(1, int(resolvers)))
        ]
        for w in workers:
            w.start()
        t_base = time.perf_counter()
        for i, (off, pick) in enumerate(zip(offsets, picks)):
            target = t_base + float(off)
            delay = target - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            # behind schedule: issue immediately, latency still counts
            # from `target` — the CO-safe accounting
            req = requests[pick]
            if request_factory is not None:
                try:
                    req = request_factory(req, i)
                except Exception:
                    req = requests[pick]
            pending.put((target, chan.do_inference_async(req)))
        for _ in workers:
            pending.put(None)
        for w in workers:
            # a straggler is bounded by the gRPC deadline
            w.join(timeout=deadline_s + 30.0)
        alive = [w for w in workers if w.is_alive()]
        if alive:
            errors.append(f"{len(alive)} resolver threads still alive")
    finally:
        if owned:
            try:
                chan.close()
            except Exception:
                pass
    return OpenLoopResult(
        offered_qps=float(rate_qps),
        scheduled=len(offsets),
        completed=completed[0],
        wall_s=float(duration_s),
        latencies_ms=latencies,
        errors=errors,
    )


def slo_capacity_search(
    address,
    scenarios,
    slo_ms: float,
    duration_s: float = 5.0,
    seed: int = 0,
    qps_lo: float = 1.0,
    qps_hi: float = 512.0,
    iters: int = 5,
    percentile: float = 99.0,
    deadline_s: float | None = None,
) -> dict:
    """Max offered qps with ``percentile`` latency <= ``slo_ms``.

    The MLPerf-Inference server-scenario headline: exponential growth
    from ``qps_lo`` brackets the knee, then a geometric bisection
    (``iters`` probes, or until hi/lo < 1.15) narrows it. Every probe
    is one seeded open-loop window; probe seeds differ so schedules
    are independent but the WHOLE search replays from ``seed``.
    Returns the capacity plus the p50/p99/p999 measured AT capacity
    and the full probe log.

    ``address`` takes the same target shapes as ``run_open_loop``; a
    list of endpoints dials ONE router shared across every probe, so
    its rolling hedge quantile and health state carry over — the fleet
    capacity number measures the steady-state front door, not a cold
    one per probe."""
    if deadline_s is None:
        # the gRPC deadline must comfortably exceed the SLO so a miss
        # is measured, not truncated into an error
        deadline_s = max(30.0, slo_ms / 1e3 * 20.0)
    chan, owned = _dial(address, deadline_s)
    probes: list[dict] = []
    best: OpenLoopResult | None = None

    def probe(qps: float):
        res = run_open_loop(
            chan, scenarios, rate_qps=qps, duration_s=duration_s,
            seed=seed + len(probes) + 1, deadline_s=deadline_s,
            warm=len(probes) == 0,  # first probe warms the path
        )
        p = res.percentile(percentile)
        probes.append(
            {
                "offered_qps": round(qps, 3),
                "p_ms": round(p, 3) if p != float("inf") else None,
                "scheduled": res.scheduled,
                "completed": res.completed,
                "errors": len(res.errors),
            }
        )
        return p <= slo_ms, res

    try:
        ok, res = probe(qps_lo)
        if not ok:
            return {
                "slo_ms": slo_ms,
                "percentile": percentile,
                "slo_capacity_qps": 0.0,
                "goodput_qps": round(res.goodput_qps(slo_ms), 3),
                "shed_rate": round(res.shed_rate, 4),
                "p50_ms": res.percentile(50.0),
                "p99_ms": res.percentile(99.0),
                "p999_ms": res.percentile(99.9),
                "probes": probes,
            }
        lo, hi, best = qps_lo, None, res
        q = qps_lo
        while q < qps_hi:
            q = min(qps_hi, q * 2.0)
            ok, res = probe(q)
            if ok:
                lo, best = q, res
            else:
                hi = q
                break
        if hi is not None:
            for _ in range(max(0, int(iters))):
                if hi / lo < 1.15:
                    break
                mid = (lo * hi) ** 0.5
                ok, res = probe(mid)
                if ok:
                    lo, best = mid, res
                else:
                    hi = mid
        p50 = best.percentile(50.0)
        p99 = best.percentile(99.0)
        p999 = best.percentile(99.9)
        return {
            "slo_ms": slo_ms,
            "percentile": percentile,
            "slo_capacity_qps": round(lo, 3),
            "goodput_qps": round(best.goodput_qps(slo_ms), 3),
            "shed_rate": round(best.shed_rate, 4),
            "achieved_qps": round(best.achieved_qps, 3),
            "p50_ms": round(p50, 3) if p50 != float("inf") else None,
            "p99_ms": round(p99, 3) if p99 != float("inf") else None,
            "p999_ms": round(p999, 3) if p999 != float("inf") else None,
            "probes": probes,
        }
    finally:
        if owned:
            try:
                chan.close()
            except Exception:
                pass


# -- streaming replay (round: streaming perception sessions) ------------------


@dataclass
class StreamStats:
    """One replayed stream's ledger.

    Latencies are measured from each frame's SCHEDULED send time (the
    recorded timestamp replayed against the stream's epoch), so a frame
    issued late because the previous one stalled still charges the
    server — the same coordinated-omission discipline as
    ``run_open_loop``. ``inter_frame_ms`` is completion-to-completion:
    the cadence the downstream consumer of this stream actually sees."""

    stream_id: str
    frames_sent: int = 0
    frames_ok: int = 0
    # temporal-reuse split (ISSUE 19): how each OK frame was served,
    # read from the response's ``reuse_mode`` output (0 full detector,
    # 1 tracker-coast, 2 ROI-tile partial). Coasted frames carry no
    # per-detection assignment, so they are scored separately:
    # ``coast_track_drops`` counts bound ground-truth tracks whose id
    # vanished from a coast frame's live track set — the coast-path
    # quality failure an ID-switch counter (detection frames only)
    # cannot see.
    frames_detected: int = 0
    frames_coasted: int = 0
    frames_partial: int = 0
    coast_track_drops: int = 0
    wall_s: float = 0.0
    latencies_ms: list = field(default_factory=list)
    inter_frame_ms: list = field(default_factory=list)
    id_switches: int = 0
    fragmentation: int = 0
    # track id -> the ground-truth object it was first bound to, and
    # the count of REBINDS (a track id later seen on a different
    # object: the id-alias failure the epoch layout must prevent)
    track_map: dict = field(default_factory=dict)
    aliases: int = 0
    errors: list = field(default_factory=list)

    @property
    def sustained_fps(self) -> float:
        return self.frames_ok / self.wall_s if self.wall_s > 0 else 0.0

    def inter_frame_p99(self) -> float:
        if not self.inter_frame_ms:
            return 0.0
        return co_percentile(
            self.inter_frame_ms, len(self.inter_frame_ms), 99.0
        )


@dataclass
class StreamsResult:
    streams: list = field(default_factory=list)
    wall_s: float = 0.0

    @property
    def frames_sent(self) -> int:
        return sum(s.frames_sent for s in self.streams)

    @property
    def frames_ok(self) -> int:
        return sum(s.frames_ok for s in self.streams)

    @property
    def goodput(self) -> float:
        """Fraction of replayed frames that came back OK."""
        sent = self.frames_sent
        return self.frames_ok / sent if sent else 0.0

    @property
    def id_switches(self) -> int:
        return sum(s.id_switches for s in self.streams)

    @property
    def fragmentation(self) -> int:
        return sum(s.fragmentation for s in self.streams)

    @property
    def aliases(self) -> int:
        return sum(s.aliases for s in self.streams)

    @property
    def frames_coasted(self) -> int:
        return sum(s.frames_coasted for s in self.streams)

    @property
    def frames_partial(self) -> int:
        return sum(s.frames_partial for s in self.streams)

    @property
    def coast_track_drops(self) -> int:
        return sum(s.coast_track_drops for s in self.streams)

    def summary(self) -> dict:
        per99 = [s.inter_frame_p99() for s in self.streams]
        fps = [s.sustained_fps for s in self.streams]
        return {
            "streams": len(self.streams),
            "frames_sent": self.frames_sent,
            "frames_ok": self.frames_ok,
            "goodput": round(self.goodput, 4),
            "frames_detected": sum(s.frames_detected for s in self.streams),
            "frames_coasted": self.frames_coasted,
            "frames_partial": self.frames_partial,
            "coast_track_drops": self.coast_track_drops,
            "id_switches": self.id_switches,
            "fragmentation": self.fragmentation,
            "track_id_aliases": self.aliases,
            "min_sustained_fps": round(min(fps), 3) if fps else 0.0,
            "worst_inter_frame_p99_ms": (
                round(max(per99), 3) if per99 else 0.0
            ),
            "wall_s": round(self.wall_s, 3),
        }


def synthetic_stream(
    n_frames: int,
    fps: float = 10.0,
    n_objects: int = 4,
    det_dim: int = 11,
    seed: int = 0,
    speed: float = 1.0,
    clutter: int = 2,
    dynamics: str | None = None,
    phase_frames: int = 12,
):
    """Generate a synthetic timestamped detection stream for replay:
    ``n_objects`` constant-velocity movers plus ``clutter`` low-score
    distractors per frame. Yields ``(offset_s, inputs, gt_ids)`` frames
    in the shape ``run_streams`` replays: ``inputs`` carries
    ``detections (N, det_dim) f32`` rows
    ``[x y z dx dy dz heading vx vy ... score label]`` and a ``valid``
    bool mask; ``gt_ids`` aligns ground-truth object ids with rows
    (clutter rows are ``-1``, never scored for ID switches).

    ``dynamics`` (ISSUE 19) shapes the scene motion so temporal-reuse
    drives can exercise the adaptive keyframe scheduler's whole range:
      * ``None``    — legacy constant-velocity movers;
      * ``"static"`` — objects hold position (innovation -> 0, K opens
        wide, coast dominates);
      * ``"pan"``   — every object shares one coherent drift (a panning
        rig: large pixel motion, perfectly predictable — the case the
        Kalman coast should absorb);
      * ``"burst"`` — static with sudden re-drawn high-speed velocities
        every ``phase_frames`` frames (innovation spikes, K must
        collapse to 1 at each burst edge);
      * ``"mixed"`` — cycles static -> pan -> burst phases of
        ``phase_frames`` each."""
    import numpy as np

    if det_dim < 11:
        raise ValueError("synthetic_stream needs det_dim >= 11")
    if dynamics not in (None, "static", "pan", "burst", "mixed"):
        raise ValueError(
            f"dynamics must be None/static/pan/burst/mixed, not {dynamics!r}"
        )
    phase_frames = max(1, int(phase_frames))
    rng = np.random.default_rng(seed)
    pos = rng.uniform(-20.0, 20.0, size=(n_objects, 2))
    base_vel = rng.uniform(-1.0, 1.0, size=(n_objects, 2)) * speed
    pan_vel = rng.uniform(-1.0, 1.0, size=(1, 2)) * speed * 2.0
    dt = 1.0 / fps
    n_rows = n_objects + clutter
    vel = base_vel
    for k in range(n_frames):
        if dynamics is not None:
            phase = dynamics
            if dynamics == "mixed":
                phase = ("static", "pan", "burst")[
                    (k // phase_frames) % 3
                ]
            if phase == "static":
                vel = np.zeros_like(base_vel)
            elif phase == "pan":
                vel = np.broadcast_to(pan_vel, base_vel.shape)
            elif phase == "burst":
                # burst edge: re-draw high-speed velocities at each
                # phase boundary, hold them through the phase
                if k % phase_frames == 0:
                    vel = (
                        rng.uniform(-1.0, 1.0, base_vel.shape)
                        * speed
                        * 4.0
                    )
        det = np.zeros((n_rows, det_dim), dtype=np.float32)
        det[:n_objects, 0:2] = pos + rng.normal(0.0, 0.05, pos.shape)
        det[:n_objects, 3:6] = (4.0, 2.0, 1.5)
        det[:n_objects, 7:9] = vel
        det[:n_objects, -2] = 0.9
        if clutter:
            det[n_objects:, 0:2] = rng.uniform(-30.0, 30.0, (clutter, 2))
            det[n_objects:, -2] = 0.05
        gt = np.concatenate(
            [
                np.arange(n_objects, dtype=np.int64),
                np.full((clutter,), -1, dtype=np.int64),
            ]
        )
        inputs = {
            "detections": det,
            "valid": np.ones((n_rows,), dtype=np.bool_),
        }
        yield (k * dt, inputs, gt)
        pos = pos + vel * dt


def _score_tracking(stats, det_tids, gt_ids, gt_to_tid, tids_per_gt):
    """Fold one frame's track assignment into the stream's ID-switch
    counter and per-object track-id sets. ``det_tids`` is the server's
    per-detection track id output; ``gt_ids`` the replayer's aligned
    ground truth (``-1`` rows are clutter and never scored)."""
    import numpy as np

    tids = np.asarray(det_tids).reshape(-1)
    gts = np.asarray(gt_ids).reshape(-1)
    if tids.shape[0] != gts.shape[0]:
        return
    for g, tid in zip(gts.tolist(), tids.tolist()):
        if g < 0 or tid < 0:
            continue
        prev = gt_to_tid.get(g)
        if prev is not None and prev != tid:
            stats.id_switches += 1
        gt_to_tid[g] = tid
        tids_per_gt.setdefault(g, set()).add(tid)
        bound = stats.track_map.setdefault(tid, g)
        if bound != g:
            stats.aliases += 1


def _score_coast(stats, outputs, gt_to_tid) -> None:
    """Score one coasted frame (ISSUE 19): no per-detection assignment
    exists, so the only checkable claim is track PERSISTENCE — every
    ground-truth object's bound track id must still be live in the
    coast frame's ``track_ids``/``tracks_valid``. Each vanished binding
    counts one ``coast_track_drops``."""
    import numpy as np

    tids = outputs.get("track_ids")
    if tids is None or not gt_to_tid:
        return
    live = np.asarray(tids).reshape(-1)
    valid = outputs.get("tracks_valid")
    if valid is not None:
        mask = np.asarray(valid, bool).reshape(-1)
        if mask.shape == live.shape:
            live = live[mask]
    live_set = {int(t) for t in live.tolist() if t > 0}
    stats.coast_track_drops += sum(
        1 for tid in gt_to_tid.values() if tid not in live_set
    )


def run_streams(
    target,
    model_name: str,
    n_streams: int,
    source,
    deadline_s: float = 60.0,
    stream_id_prefix: str = "stream",
    track_output: str = "det_track_ids",
    realtime: bool = True,
) -> StreamsResult:
    """Replay ``n_streams`` timestamped sequences at recorded pace —
    the streaming-session answer to ``run_pool``'s stateless closed
    loop.

    ``target`` is a ``_dial`` shape (endpoint, endpoint list — routed
    with session affinity through a ``FrontDoorRouter`` — or a built
    channel/router). ``source(stream_idx)`` returns an iterable of
    ``(offset_s, inputs)`` or ``(offset_s, inputs, gt_ids)`` frames;
    see :func:`synthetic_stream`. Every stream gets its own thread and
    ``sequence_id``; the first frame carries ``sequence_start``, the
    last ``sequence_end``, so server-side session slots open and close
    with the replay.

    Pacing: frame ``i`` is sent no earlier than ``epoch + offset_i``
    and never before frame ``i-1`` resolved (sessions are ordered —
    in-flight pipelining inside one stream would reorder state). With
    ``realtime=False`` the recorded offsets are ignored and each stream
    replays as fast as its round-trips allow (back-to-back mode for
    parity drives). Per-frame latency is charged from the SCHEDULED
    time; a late frame never hides server stall.

    ID switches / fragmentation need ground truth: frames that carry
    ``gt_ids`` are scored against the ``track_output`` tensor in each
    response (id switch = a ground-truth object's track id changed
    between consecutive sightings; fragmentation = extra distinct track
    ids per object beyond the first)."""
    from triton_client_tpu.channel.base import InferRequest

    if n_streams < 1:
        raise ValueError("run_streams needs n_streams >= 1")
    chan, owned = _dial(target, deadline_s)
    results = [
        StreamStats(f"{stream_id_prefix}-{i}") for i in range(n_streams)
    ]
    ready = threading.Barrier(n_streams + 1)

    def stream_loop(idx: int) -> None:
        stats = results[idx]
        frames = []
        for f in source(idx):
            off, inputs = f[0], f[1]
            gt = f[2] if len(f) > 2 else None
            frames.append((float(off), inputs, gt))
        gt_to_tid: dict = {}
        tids_per_gt: dict = {}
        try:
            ready.wait(timeout=deadline_s)
        except threading.BrokenBarrierError:
            return
        t0 = time.perf_counter()
        last_done = None
        for k, (off, inputs, gt) in enumerate(frames):
            sched = t0 + off if realtime else time.perf_counter()
            delay = sched - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            req = InferRequest(
                model_name=model_name,
                inputs=inputs,
                sequence_id=stats.stream_id,
                sequence_start=(k == 0),
                sequence_end=(k == len(frames) - 1),
            )
            stats.frames_sent += 1
            try:
                resp = chan.do_inference(req)
            except Exception as e:  # the stream outlives one lost frame
                stats.errors.append(e)
                continue
            now = time.perf_counter()
            stats.frames_ok += 1
            stats.latencies_ms.append((now - sched) * 1e3)
            if last_done is not None:
                stats.inter_frame_ms.append((now - last_done) * 1e3)
            last_done = now
            mode = resp.outputs.get("reuse_mode")
            if mode is not None:
                import numpy as _np

                mode = int(_np.asarray(mode).reshape(-1)[0])
            else:
                mode = 0
            if mode == 1:
                stats.frames_coasted += 1
            elif mode == 2:
                stats.frames_partial += 1
            else:
                stats.frames_detected += 1
            if gt is not None:
                if mode == 1:
                    # coasted: no per-detection assignment came back —
                    # score track persistence instead of ID switches
                    _score_coast(stats, resp.outputs, gt_to_tid)
                else:
                    tids = resp.outputs.get(track_output)
                    if tids is not None:
                        _score_tracking(
                            stats, tids, gt, gt_to_tid, tids_per_gt
                        )
        stats.wall_s = time.perf_counter() - t0
        stats.fragmentation = sum(len(s) - 1 for s in tids_per_gt.values())

    threads = [
        threading.Thread(
            target=stream_loop, args=(i,), name=f"stream-{i}", daemon=True
        )
        for i in range(n_streams)
    ]
    t_start = time.perf_counter()
    try:
        for t in threads:
            t.start()
        ready.wait(timeout=deadline_s)
        for t in threads:
            t.join()
    finally:
        if owned:
            try:
                chan.close()
            except Exception:
                pass
    return StreamsResult(streams=results, wall_s=time.perf_counter() - t_start)
