"""Closed-loop gRPC load generator for serving benchmarks.

The role Triton's ``perf_analyzer`` plays in the reference's ecosystem
(its README benchmarks the server with concurrent closed-loop clients):
N threads, each with its own channel, issuing one synchronous
ModelInfer after another against a KServe v2 endpoint, with a
warm-before-measure barrier so neither thread ramp nor first-request
compiles bias the measured window. Used by ``bench.measure_serving``
and ``perf/profile_serving.py`` so both measure the SAME protocol.

Client lifecycle per thread:
  1. staggered connect + one warm request (staggering avoids N
     simultaneous payload uploads blowing deadlines on a small host);
  2. barrier — every thread arrives, warmed or failed;
  3. closed loop until ``stop`` is set, per-request latency recorded;
  4. channel closed (unregisters any shared-memory regions), counts
     merged under a lock.

``run_pool`` returns after EVERY client thread has fully exited — a
straggler blocked on a slow request is waited out (bounded by the
request deadline), never left running into a subsequent measurement.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


@dataclass
class PoolResult:
    served_frames: int
    wall_s: float
    latencies_ms: list = field(default_factory=list)
    errors: list = field(default_factory=list)

    @property
    def fps(self) -> float:
        return self.served_frames / self.wall_s if self.wall_s > 0 else 0.0


def run_pool(
    address: str,
    model_name: str,
    inputs: dict,
    clients: int,
    duration_s: float,
    deadline_s: float = 300.0,
    use_shared_memory: bool = False,
    stagger_s: float = 0.25,
    on_window_start=None,
    mode: str = "unary",
    inflight: int = 1,
) -> PoolResult:
    """Drive ``clients`` closed-loop threads for ``duration_s`` and
    return counts/latencies. ``on_window_start`` fires after the warm
    barrier, immediately before the timed window — the hook for
    clearing server-side accounting (batcher stats, occupancy taps).

    ``mode`` selects the client protocol (round 5 — puts numbers on
    the reference's dead --streaming/--async flags, main.py:59-70):
      * 'unary'  — one synchronous ModelInfer per iteration (default);
      * 'stream' — ONE long-lived ModelStreamInfer session per client,
        ``inflight`` requests pipelined inside it (latency = send ->
        matching response; responses preserve order on a stream);
      * 'async'  — ModelInfer call-futures with ``inflight`` in the
        air per client (the --async --inflight N path).
    """
    from triton_client_tpu.channel.base import InferRequest
    from triton_client_tpu.channel.grpc_channel import GRPCChannel

    if mode not in ("unary", "stream", "async"):
        raise ValueError(f"unknown pool mode {mode!r}")
    inflight = max(1, int(inflight))

    served: list = []
    latencies: list = []
    errors: list = []
    lock = threading.Lock()
    stop = threading.Event()
    ready = threading.Barrier(clients + 1)
    # the warm phase is bounded by one request deadline plus the
    # connect stagger: a hard-coded barrier timeout shorter than
    # deadline_s (bench sizes that from measured device time — 320 s+
    # on a ~1 s/dispatch rig) broke the barrier while a slow warm was
    # still legitimate, and the pool leaked running clients into the
    # next transport's measurement
    barrier_timeout_s = deadline_s + stagger_s * clients + 60.0

    def client_loop(idx: int):
        n, mine = 0, []  # n counts only completions INSIDE the window
        chan = req = None
        try:
            time.sleep(stagger_s * (idx % 4))
            chan = GRPCChannel(
                address,
                timeout_s=deadline_s,
                use_shared_memory=use_shared_memory,
            )
            req = InferRequest(model_name=model_name, inputs=inputs)
            chan.do_inference(req)  # connection + server path warm
        except Exception as e:
            with lock:
                errors.append(repr(e))
            chan = None
        try:
            # EVERY thread reaches the barrier, warm or not — a failed
            # warm must not strand the caller's wait
            ready.wait(timeout=barrier_timeout_s)
        except threading.BrokenBarrierError:
            pass
        try:
            if chan is not None and mode == "unary":
                while not stop.is_set():
                    t0 = time.perf_counter()
                    chan.do_inference(req)
                    mine.append((time.perf_counter() - t0) * 1e3)
                    # a completion racing the window close (the final
                    # in-flight request) is drained but NOT counted —
                    # fps must be completions-in-window / window, not
                    # diluted by the post-stop drain time
                    if not stop.is_set():
                        n += 1
            elif chan is not None and mode == "stream":
                import queue as _q

                sent: _q.Queue = _q.Queue(maxsize=inflight)

                def gen():
                    # closed-loop through the stream: the bounded queue
                    # caps in-flight requests; put blocks until a
                    # response frees a slot. The timestamp is taken
                    # AFTER the slot is granted, immediately before the
                    # request goes to gRPC — timing the backpressure
                    # wait would double-count the previous in-flight
                    # request's latency
                    while not stop.is_set():
                        cell = [0.0]
                        sent.put(cell)
                        cell[0] = time.perf_counter()
                        yield req

                for _resp in chan.infer_stream(gen(), stream_timeout_s=deadline_s):
                    t0 = sent.get()[0]
                    mine.append((time.perf_counter() - t0) * 1e3)
                    if not stop.is_set():
                        n += 1
            elif chan is not None:  # async futures, inflight in the air
                from collections import deque

                air: deque = deque()
                while not stop.is_set():
                    while len(air) < inflight and not stop.is_set():
                        air.append(
                            (time.perf_counter(), chan.do_inference_async(req))
                        )
                    if not air:  # stop raced the fill loop
                        break
                    t0, fut = air.popleft()
                    fut.result()
                    mine.append((time.perf_counter() - t0) * 1e3)
                    if not stop.is_set():
                        n += 1
                while air:  # drain, uncounted
                    air.popleft()[1].result()
        except Exception as e:  # a dying client must still report
            with lock:
                errors.append(repr(e))
        finally:
            if chan is not None:
                try:
                    chan.close()
                except Exception:
                    pass
            with lock:
                served.append(n)
                latencies.extend(mine)

    threads = [
        threading.Thread(target=client_loop, args=(i,)) for i in range(clients)
    ]
    for t in threads:
        t.start()
    wall = 0.0
    try:
        try:
            ready.wait(timeout=barrier_timeout_s)
        except threading.BrokenBarrierError as e:
            # a broken barrier aborts the window but must NOT skip the
            # stop/join in the finally — clients swallow
            # BrokenBarrierError and enter their request loop, so
            # without stop.set() they would keep issuing requests into
            # the caller's next measurement until server teardown
            with lock:
                errors.append(f"warm barrier broke: {e!r}")
        else:
            if on_window_start is not None:
                on_window_start()
            t_start = time.perf_counter()
            time.sleep(duration_s)
            # the measured window closes HERE: stragglers are drained
            # in the finally so nothing survives into the caller's
            # next measurement, but their drain time must not dilute
            # the reported rate
            wall = time.perf_counter() - t_start
    finally:
        stop.set()
        # wait stragglers OUT: an in-flight request is bounded by the
        # gRPC deadline, so this join always terminates
        for t in threads:
            t.join(timeout=deadline_s + 60.0)
        alive = [t for t in threads if t.is_alive()]
        if alive:
            errors.append(
                f"{len(alive)} client threads still alive after join"
            )
    return PoolResult(
        served_frames=sum(served),
        wall_s=wall,
        latencies_ms=latencies,
        errors=errors,
    )
