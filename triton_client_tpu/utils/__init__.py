"""Cross-cutting utilities (profiling/tracing, observability)."""
