"""Rotated 3D/BEV box geometry: corners, IoU, NMS.

The reference gets rotated-box NMS from OpenPCDet's iou3d_nms_cuda
(SURVEY.md section 2.9) compiled CUDA. TPU re-design: the intersection
of two convex rectangles is computed vectorized with fixed shapes —
candidate vertices are (a) corners of A inside B, (b) corners of B
inside A, (c) all 16 edge-pair intersection points; the valid ones are
angle-sorted around their centroid (the intersection of convex sets is
convex) and the area comes from the shoelace formula with masked slots
collapsed onto the first valid vertex (degenerate edges contribute zero
area). No loops, no dynamic shapes — one vmap'd expression, fused by XLA.

Box parameterization follows the 3D wire contract
(clients/postprocess/detector_3d_postprocess.py pred_boxes (N, 7)):
[x, y, z, dx, dy, dz, heading]; BEV uses [x, y, dx, dy, heading].
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def bev_corners(boxes: jnp.ndarray) -> jnp.ndarray:
    """(..., 5) [cx, cy, dx, dy, heading] -> (..., 4, 2) corners CCW."""
    cx, cy, dx, dy, h = (boxes[..., i] for i in range(5))
    cos, sin = jnp.cos(h), jnp.sin(h)
    # local corner offsets, CCW
    lx = jnp.stack([dx, -dx, -dx, dx], axis=-1) * 0.5
    ly = jnp.stack([dy, dy, -dy, -dy], axis=-1) * 0.5
    wx = cx[..., None] + lx * cos[..., None] - ly * sin[..., None]
    wy = cy[..., None] + lx * sin[..., None] + ly * cos[..., None]
    return jnp.stack([wx, wy], axis=-1)


def _point_in_rect(pts: jnp.ndarray, rect: jnp.ndarray, eps: float) -> jnp.ndarray:
    """pts (P, 2) inside rotated rect (5,) -> (P,) bool."""
    cos, sin = jnp.cos(rect[4]), jnp.sin(rect[4])
    rel = pts - rect[:2]
    local_x = rel[:, 0] * cos + rel[:, 1] * sin
    local_y = -rel[:, 0] * sin + rel[:, 1] * cos
    return (jnp.abs(local_x) <= rect[2] * 0.5 + eps) & (
        jnp.abs(local_y) <= rect[3] * 0.5 + eps
    )


def _seg_intersections(ca: jnp.ndarray, cb: jnp.ndarray, eps: float):
    """All 16 edge-pair intersection points between two 4-gons.

    ca, cb: (4, 2) corners. Returns (16, 2) points + (16,) valid."""
    a1 = ca  # (4, 2) edge starts
    a2 = jnp.roll(ca, -1, axis=0)
    b1 = cb
    b2 = jnp.roll(cb, -1, axis=0)
    # broadcast to (4, 4, 2): A edges x B edges
    p, r = a1[:, None], (a2 - a1)[:, None]
    q, s = b1[None, :], (b2 - b1)[None, :]
    rxs = r[..., 0] * s[..., 1] - r[..., 1] * s[..., 0]  # (4, 4)
    qp = q - p
    t = (qp[..., 0] * s[..., 1] - qp[..., 1] * s[..., 0]) / jnp.where(
        jnp.abs(rxs) < eps, 1.0, rxs
    )
    u = (qp[..., 0] * r[..., 1] - qp[..., 1] * r[..., 0]) / jnp.where(
        jnp.abs(rxs) < eps, 1.0, rxs
    )
    valid = (
        (jnp.abs(rxs) >= eps)
        & (t >= -eps) & (t <= 1 + eps)
        & (u >= -eps) & (u <= 1 + eps)
    )
    pts = p + t[..., None] * r
    return pts.reshape(16, 2), valid.reshape(16)


def _pair_intersection_area(box_a: jnp.ndarray, box_b: jnp.ndarray, eps: float = 1e-6):
    """Intersection area of two (5,) BEV rects."""
    ca, cb = bev_corners(box_a), bev_corners(box_b)
    pts_e, val_e = _seg_intersections(ca, cb, eps)
    val_a = _point_in_rect(ca, box_b, eps)
    val_b = _point_in_rect(cb, box_a, eps)
    pts = jnp.concatenate([ca, cb, pts_e], axis=0)  # (24, 2)
    valid = jnp.concatenate([val_a, val_b, val_e])  # (24,)

    n_valid = valid.sum()
    any_valid = n_valid >= 3  # fewer than 3 vertices -> zero area
    centroid = jnp.where(valid[:, None], pts, 0.0).sum(0) / jnp.maximum(n_valid, 1)
    ang = jnp.arctan2(pts[:, 1] - centroid[1], pts[:, 0] - centroid[0])
    ang = jnp.where(valid, ang, jnp.inf)  # invalid sort last
    order = jnp.argsort(ang)
    pts_s = pts[order]
    valid_s = valid[order]
    # collapse invalid tail onto the first (valid) vertex: duplicate
    # vertices add zero to the shoelace sum
    first = pts_s[0]
    pts_s = jnp.where(valid_s[:, None], pts_s, first)
    nxt = jnp.roll(pts_s, -1, axis=0)
    cross = pts_s[:, 0] * nxt[:, 1] - nxt[:, 0] * pts_s[:, 1]
    area = 0.5 * jnp.abs(cross.sum())
    return jnp.where(any_valid, area, 0.0)


@jax.jit
def rotated_iou_bev(boxes1: jnp.ndarray, boxes2: jnp.ndarray) -> jnp.ndarray:
    """Pairwise rotated IoU between (N, 5) and (M, 5) BEV boxes -> (N, M)."""
    inter = jax.vmap(
        lambda a: jax.vmap(lambda b: _pair_intersection_area(a, b))(boxes2)
    )(boxes1)
    area1 = boxes1[:, 2] * boxes1[:, 3]
    area2 = boxes2[:, 2] * boxes2[:, 3]
    union = area1[:, None] + area2[None, :] - inter
    return inter / jnp.maximum(union, 1e-9)


def boxes7_to_bev(boxes: jnp.ndarray) -> jnp.ndarray:
    """(..., 7) [x, y, z, dx, dy, dz, heading] -> (..., 5) BEV."""
    return jnp.concatenate(
        [boxes[..., 0:2], boxes[..., 3:5], boxes[..., 6:7]], axis=-1
    )


@functools.partial(jax.jit, static_argnames=("max_det",))
def nms_bev(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    iou_thresh: float = 0.01,
    max_det: int = 128,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Greedy rotated-BEV NMS over (N, 7) boxes. Same fixed-iteration
    design as ops.nms.nms; scores of -inf mark padding. Returns
    ((max_det,) indices, (max_det,) valid)."""
    bev = boxes7_to_bev(boxes)
    n = bev.shape[0]
    neg_inf = jnp.asarray(-jnp.inf, scores.dtype)

    def body(i, state):
        live, indices, valid = state
        best = jnp.argmax(live)
        is_valid = live[best] > neg_inf
        indices = indices.at[i].set(best.astype(jnp.int32))
        valid = valid.at[i].set(is_valid)
        ious = jax.vmap(lambda b: _pair_intersection_area(bev[best], b))(bev)
        area_b = bev[best, 2] * bev[best, 3]
        areas = bev[:, 2] * bev[:, 3]
        ious = ious / jnp.maximum(area_b + areas - ious, 1e-9)
        suppress = (ious > iou_thresh) | (jnp.arange(n) == best)
        live = jnp.where(suppress & is_valid, neg_inf, live)
        return live, indices, valid

    indices = jnp.zeros((max_det,), jnp.int32)
    valid = jnp.zeros((max_det,), bool)
    _, indices, valid = jax.lax.fori_loop(0, max_det, body, (scores, indices, valid))
    return indices, valid
