"""Rotated 3D/BEV box geometry: corners, IoU, NMS.

The reference gets rotated-box NMS from OpenPCDet's iou3d_nms_cuda
(SURVEY.md section 2.9) compiled CUDA. TPU re-design: the intersection
of two convex rectangles is computed vectorized with fixed shapes —
candidate vertices are (a) corners of A inside B, (b) corners of B
inside A, (c) all 16 edge-pair intersection points; the valid ones are
angle-sorted around their centroid (the intersection of convex sets is
convex) and the area comes from the shoelace formula with masked slots
collapsed onto the first valid vertex (degenerate edges contribute zero
area). No loops, no dynamic shapes — one vmap'd expression, fused by XLA.

Box parameterization follows the 3D wire contract
(clients/postprocess/detector_3d_postprocess.py pred_boxes (N, 7)):
[x, y, z, dx, dy, dz, heading]; BEV uses [x, y, dx, dy, heading].
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def bev_corners(boxes: jnp.ndarray) -> jnp.ndarray:
    """(..., 5) [cx, cy, dx, dy, heading] -> (..., 4, 2) corners CCW."""
    cx, cy, dx, dy, h = (boxes[..., i] for i in range(5))
    cos, sin = jnp.cos(h), jnp.sin(h)
    # local corner offsets, CCW
    lx = jnp.stack([dx, -dx, -dx, dx], axis=-1) * 0.5
    ly = jnp.stack([dy, dy, -dy, -dy], axis=-1) * 0.5
    wx = cx[..., None] + lx * cos[..., None] - ly * sin[..., None]
    wy = cy[..., None] + lx * sin[..., None] + ly * cos[..., None]
    return jnp.stack([wx, wy], axis=-1)


def _corners_soa(boxes: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(P, 5) rects -> CCW corner coordinates as (4, P) x / (4, P) y.

    Struct-of-arrays with the PAIR axis last: every downstream op is
    (k, P)-shaped with P riding the 128-wide vector lanes. The previous
    AoS formulation carried a minor dim of 2 (xy pairs), wasting 126 of
    128 lanes on every VPU op — the dominant cost of rotated NMS."""
    cx, cy, dx, dy, h = (boxes[:, i] for i in range(5))
    cos, sin = jnp.cos(h), jnp.sin(h)
    lx = jnp.stack([dx, -dx, -dx, dx], axis=0) * 0.5  # (4, P)
    ly = jnp.stack([dy, dy, -dy, -dy], axis=0) * 0.5
    return cx + lx * cos - ly * sin, cy + lx * sin + ly * cos


def _in_rect_soa(px, py, rect: jnp.ndarray, eps: float) -> jnp.ndarray:
    """(k, P) points inside (P, 5) rects -> (k, P) bool."""
    cos, sin = jnp.cos(rect[:, 4]), jnp.sin(rect[:, 4])
    relx, rely = px - rect[:, 0], py - rect[:, 1]
    lx = relx * cos + rely * sin
    ly = -relx * sin + rely * cos
    return (jnp.abs(lx) <= rect[:, 2] * 0.5 + eps) & (
        jnp.abs(ly) <= rect[:, 3] * 0.5 + eps
    )


def intersection_areas(
    boxes_a: jnp.ndarray, boxes_b: jnp.ndarray, eps: float = 1e-6
) -> jnp.ndarray:
    """Elementwise intersection area of (P, 5) vs (P, 5) BEV rects -> (P,).

    Exact convex-polygon clip, fully lane-parallel: 16 edge-pair
    intersections + 8 contained-corner tests give <=24 candidate
    vertices per pair; vertices are angle-ordered around the centroid
    with ONE multi-operand lax.sort (co-sorting x/y/valid with the angle
    key — no per-pair gather), then shoelace-summed."""
    ax, ay = _corners_soa(boxes_a)
    bx, by = _corners_soa(boxes_b)
    p = boxes_a.shape[0]

    # edge vectors; (4, 1, P) x (1, 4, P) -> (4, 4, P)
    rx, ry = (jnp.roll(ax, -1, 0) - ax)[:, None], (jnp.roll(ay, -1, 0) - ay)[:, None]
    sx, sy = (jnp.roll(bx, -1, 0) - bx)[None], (jnp.roll(by, -1, 0) - by)[None]
    px, py = ax[:, None], ay[:, None]
    qx, qy = bx[None], by[None]
    rxs = rx * sy - ry * sx
    qpx, qpy = qx - px, qy - py
    denom = jnp.where(jnp.abs(rxs) < eps, 1.0, rxs)
    t = (qpx * sy - qpy * sx) / denom
    u = (qpx * ry - qpy * rx) / denom
    val_e = (
        (jnp.abs(rxs) >= eps)
        & (t >= -eps) & (t <= 1 + eps)
        & (u >= -eps) & (u <= 1 + eps)
    )
    ix, iy = px + t * rx, py + t * ry

    val_a = _in_rect_soa(ax, ay, boxes_b, eps)
    val_b = _in_rect_soa(bx, by, boxes_a, eps)
    xs = jnp.concatenate([ax, bx, ix.reshape(16, p)], axis=0)  # (24, P)
    ys = jnp.concatenate([ay, by, iy.reshape(16, p)], axis=0)
    valid = jnp.concatenate([val_a, val_b, val_e.reshape(16, p)], axis=0)

    n_valid = valid.sum(axis=0)
    any_valid = n_valid >= 3  # fewer than 3 vertices -> zero area
    vf = valid.astype(xs.dtype)
    cx = (xs * vf).sum(0) / jnp.maximum(n_valid, 1)
    cy = (ys * vf).sum(0) / jnp.maximum(n_valid, 1)
    ang = jnp.where(valid, jnp.arctan2(ys - cy, xs - cx), jnp.inf)
    _, xs_s, ys_s, vf_s = jax.lax.sort((ang, xs, ys, vf), dimension=0, num_keys=1)
    # collapse the invalid tail onto the first (valid) vertex: duplicate
    # vertices add zero to the shoelace sum
    valid_s = vf_s > 0.5
    xs_s = jnp.where(valid_s, xs_s, xs_s[0])
    ys_s = jnp.where(valid_s, ys_s, ys_s[0])
    cross = xs_s * jnp.roll(ys_s, -1, 0) - jnp.roll(xs_s, -1, 0) * ys_s
    area = 0.5 * jnp.abs(cross.sum(0))
    return jnp.where(any_valid, area, 0.0)


@jax.jit
def rotated_iou_bev(boxes1: jnp.ndarray, boxes2: jnp.ndarray) -> jnp.ndarray:
    """Pairwise rotated IoU between (N, 5) and (M, 5) BEV boxes -> (N, M)."""
    n, m = boxes1.shape[0], boxes2.shape[0]
    a = jnp.repeat(boxes1, m, axis=0)  # (N*M, 5)
    b = jnp.tile(boxes2, (n, 1))
    inter = intersection_areas(a, b).reshape(n, m)
    area1 = boxes1[:, 2] * boxes1[:, 3]
    area2 = boxes2[:, 2] * boxes2[:, 3]
    union = area1[:, None] + area2[None, :] - inter
    return inter / jnp.maximum(union, 1e-9)


def boxes7_to_bev(boxes: jnp.ndarray) -> jnp.ndarray:
    """(..., 7) [x, y, z, dx, dy, dz, heading] -> (..., 5) BEV."""
    return jnp.concatenate(
        [boxes[..., 0:2], boxes[..., 3:5], boxes[..., 6:7]], axis=-1
    )


@functools.partial(jax.jit, static_argnames=("max_det",))
def nms_bev(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    iou_thresh: float = 0.01,
    max_det: int = 128,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Greedy rotated-BEV NMS over (N, 7) boxes. Same fixed-iteration
    design as ops.nms.nms; scores of -inf mark padding. Returns
    ((max_det,) indices, (max_det,) valid).

    The full N x N rotated IoU matrix is computed ONCE up front on
    SCORE-SORTED candidates (fully parallel polygon clipping —
    VPU-friendly), then suppression resolves as the shared greedy
    fixpoint (ops.nms.fixpoint_keep_sorted): sequential-step count =
    suppression-chain depth (single digits), not max_det. Round-1
    history: in-loop polygon clipping -> precomputed matrix + max_det
    argmax steps (~5x) -> fixpoint (removes the max_det serial steps
    too)."""
    from triton_client_tpu.ops.nms import fixpoint_keep_sorted

    neg_inf = jnp.asarray(-jnp.inf, scores.dtype)
    order = jnp.argsort(-scores, stable=True).astype(jnp.int32)
    bev = boxes7_to_bev(boxes)[order]
    valid0 = scores[order] > neg_inf
    iou = rotated_iou_bev(bev, bev)  # (N, N), once
    return fixpoint_keep_sorted(iou, valid0, order, iou_thresh, max_det)
