"""YOLO anchor-grid decode (v5 and v4 conventions), vectorized for XLA.

Parity target: tools/yolo_layer.py:148-288 (yolo_forward_dynamic), which
decodes raw feature maps with per-scalar python/torch indexing on host.
Here the decode is a closed-form jnp expression over the whole grid so
it fuses into the model's jit and runs on the VPU.

Conventions (b = batch, a = anchors-per-scale, h/w = grid, nc = classes):
  v5: xy = (2*sig(t_xy) - 0.5 + grid) * stride
      wh = (2*sig(t_wh))**2 * anchor_px
      obj/cls = sig(t)
  v4: xy = (sig(t_xy) + grid) * stride      (normalized variant: /input_size)
      wh = exp(t_wh) * anchor_px
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _grid(h: int, w: int, dtype) -> jnp.ndarray:
    """(h, w, 2) grid of (x, y) cell offsets."""
    ys = jnp.arange(h, dtype=dtype)
    xs = jnp.arange(w, dtype=dtype)
    gx, gy = jnp.meshgrid(xs, ys)
    return jnp.stack([gx, gy], axis=-1)


def decode_yolo_grid(
    raw: jnp.ndarray,
    anchors: jnp.ndarray,
    stride: int,
    variant: str = "v5",
    normalize_hw: tuple[int, int] | None = None,
) -> jnp.ndarray:
    """Decode one scale's raw head output.

    Args:
      raw: (b, h, w, a, 5 + nc) raw logits for one scale.
      anchors: (a, 2) anchor sizes in input pixels.
      stride: input-pixels per grid cell for this scale.
      variant: "v5" or "v4" box parameterization.
      normalize_hw: if set, divide boxes into [0, 1] by (H, W) — the
        reference's YOLOv4 path emits normalized boxes
        (tools/yolo_layer.py:281-287).

    Returns:
      (b, h*w*a, 5 + nc) decoded [cx, cy, w, h, obj, cls...] in input
      pixels (or [0, 1] if normalize_hw).
    """
    b, h, w, a, no = raw.shape
    # Decode in f32 always: grid offsets and pixel boxes are not
    # representable in bf16 past ~128 cells (spacing 1 at [128, 256)),
    # which would snap centers to cell edges on large inputs.
    raw = raw.astype(jnp.float32)
    dtype = jnp.float32
    grid = _grid(h, w, dtype)[None, :, :, None, :]  # (1, h, w, 1, 2)
    anchors = jnp.asarray(anchors, dtype).reshape(1, 1, 1, a, 2)

    txy, twh, trest = raw[..., :2], raw[..., 2:4], raw[..., 4:]
    if variant == "v5":
        xy = (jax.nn.sigmoid(txy) * 2.0 - 0.5 + grid) * stride
        wh = (jax.nn.sigmoid(twh) * 2.0) ** 2 * anchors
    elif variant == "v4":
        xy = (jax.nn.sigmoid(txy) + grid) * stride
        wh = jnp.exp(twh) * anchors
    else:
        raise ValueError(f"unknown decode variant: {variant}")
    rest = jax.nn.sigmoid(trest)

    out = jnp.concatenate([xy, wh, rest], axis=-1)
    if normalize_hw is not None:
        nh, nw = normalize_hw
        scale = jnp.asarray([nw, nh, nw, nh] + [1.0] * (no - 4), dtype)
        out = out / scale
    return out.reshape(b, h * w * a, no)
