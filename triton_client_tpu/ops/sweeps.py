"""Multi-sweep point-cloud aggregation (nuScenes 10-sweep semantics).

The reference's CenterPoint path is explicitly the 10-sweep config
(data/nusc_centerpoint_pp_02voxel_two_pfn_10sweep.py; its client zero-
pads a time column onto single sweeps, clients/preprocess/voxelize.py:
38-40 — the degenerate 1-sweep case of this module). Upstream det3d
stacks the keyframe with up to 9 prior sweeps, each transformed into
the keyframe's sensor frame, and appends a per-point time-lag channel
Δt = t_key - t_sweep so the network can infer motion (the velocity
head's input signal).

Host-side numpy: aggregation is stream prep (like JPEG decode on the
2D path), the padded result feeds the jitted pipeline whose VFE takes
``VoxelConfig.point_features = 5`` columns.
"""

from __future__ import annotations

import collections
from typing import Sequence

import numpy as np


def aggregate_sweeps(
    sweeps: Sequence[np.ndarray],
    times: Sequence[float] | None = None,
    transforms: Sequence[np.ndarray] | None = None,
) -> np.ndarray:
    """Stack sweeps (keyframe FIRST) into one (N, 5) cloud
    [x, y, z, intensity, Δt].

    Args:
      sweeps: per-sweep (M_i, >=3) arrays, newest (keyframe) first; a
        missing intensity column is zero-filled.
      times: per-sweep timestamps (seconds). Δt_i = times[0] - times[i]
        (keyframe lag 0; older sweeps positive). None -> all zeros (the
        reference's single-sweep zero-pad).
      transforms: optional per-sweep (4, 4) homogeneous transforms
        mapping sweep i's sensor frame into the KEYFRAME's frame (ego
        motion compensation; identity for the keyframe). None -> static
        platform assumed.
    """
    if not sweeps:
        raise ValueError("aggregate_sweeps needs at least one sweep")
    if times is not None and len(times) != len(sweeps):
        raise ValueError(f"{len(times)} times for {len(sweeps)} sweeps")
    if transforms is not None and len(transforms) != len(sweeps):
        raise ValueError(f"{len(transforms)} transforms for {len(sweeps)} sweeps")

    parts = []
    t0 = times[0] if times is not None else 0.0
    for i, sweep in enumerate(sweeps):
        pts = np.asarray(sweep, np.float32)
        if pts.ndim != 2 or pts.shape[1] < 3:
            raise ValueError(f"sweep {i}: expected (M, >=3), got {pts.shape}")
        xyz = pts[:, :3]
        if transforms is not None:
            tf = np.asarray(transforms[i], np.float32)
            xyz = xyz @ tf[:3, :3].T + tf[:3, 3]
        inten = (
            pts[:, 3:4]
            if pts.shape[1] >= 4
            else np.zeros((len(pts), 1), np.float32)
        )
        dt = np.full(
            (len(pts), 1),
            (t0 - times[i]) if times is not None else 0.0,
            np.float32,
        )
        parts.append(np.concatenate([xyz, inten, dt], axis=1))
    return np.concatenate(parts, axis=0)


def pose_to_matrix(
    translation: Sequence[float], quaternion: Sequence[float]
) -> np.ndarray:
    """(x, y, z) + (qx, qy, qz, qw) -> (4, 4) homogeneous world_T_sensor
    (the ROS nav_msgs/Odometry pose convention)."""
    x, y, z, w = (float(v) for v in quaternion)
    n = np.sqrt(x * x + y * y + z * z + w * w)
    if n < 1e-12:
        raise ValueError("zero-norm quaternion")
    x, y, z, w = x / n, y / n, z / n, w / n
    tf = np.eye(4, dtype=np.float64)
    tf[:3, :3] = [
        [1 - 2 * (y * y + z * z), 2 * (x * y - z * w), 2 * (x * z + y * w)],
        [2 * (x * y + z * w), 1 - 2 * (x * x + z * z), 2 * (y * z - x * w)],
        [2 * (x * z - y * w), 2 * (y * z + x * w), 1 - 2 * (x * x + y * y)],
    ]
    tf[:3, 3] = translation
    return tf


def _rigid_inverse(tf: np.ndarray) -> np.ndarray:
    out = np.eye(4, dtype=np.float64)
    r = tf[:3, :3].T
    out[:3, :3] = r
    out[:3, 3] = -r @ tf[:3, 3]
    return out


def relative_transforms(poses: Sequence[np.ndarray]) -> list[np.ndarray]:
    """Per-sweep world_T_sensor poses (keyframe FIRST) -> transforms
    mapping each sweep's sensor frame into the KEYFRAME's frame:
    T_i = inv(pose_key) @ pose_i (identity for the keyframe) — the
    det3d ego-motion compensation the reference applies from dataset
    sweep records (clients/preprocess/voxelize.py:13-24)."""
    inv_key = _rigid_inverse(np.asarray(poses[0], np.float64))
    return [inv_key @ np.asarray(p, np.float64) for p in poses]


class SweepBuffer:
    """Rolling window of the last ``nsweeps`` scans for a live/replay
    stream: push the newest scan (+ timestamp and, on a moving
    platform, its world_T_sensor pose), get the aggregated (N, 5)
    cloud with the newest scan as keyframe.

    With poses, older sweeps are transformed into the keyframe's
    sensor frame before stacking (ego-motion compensation — without it
    a moving vehicle smears static structure across sweeps and
    corrupts the velocity head's input). Without poses the platform is
    assumed static — exact for a stationary sensor and an explicit,
    documented approximation otherwise. Mixing posed and poseless
    pushes in one window is refused loudly."""

    def __init__(self, nsweeps: int = 10):
        if nsweeps < 1:
            raise ValueError(f"nsweeps must be >= 1, got {nsweeps}")
        self.nsweeps = nsweeps
        self._window: collections.deque = collections.deque(maxlen=nsweeps)

    def push(
        self,
        points: np.ndarray,
        timestamp: float,
        pose: np.ndarray | None = None,
    ) -> np.ndarray:
        """Add the newest scan; returns the aggregated cloud (newest
        first, Δt relative to it)."""
        # validate BEFORE appending: a rejected push must not poison
        # the window for the following (correct) pushes
        window_posed = [q is not None for _, _, q in self._window]
        if window_posed and (pose is not None) != window_posed[0]:
            raise ValueError(
                "SweepBuffer window mixes posed and poseless scans; "
                "supply a pose for every push or none"
            )
        self._window.appendleft(
            (
                np.asarray(points, np.float32),
                float(timestamp),
                None if pose is None else np.asarray(pose, np.float64),
            )
        )
        sweeps = [p for p, _, _ in self._window]
        times = [t for _, t, _ in self._window]
        poses = [q for _, _, q in self._window]
        have = [q is not None for q in poses]
        transforms = relative_transforms(poses) if all(have) and poses else None
        return aggregate_sweeps(sweeps, times, transforms)

    def __len__(self) -> int:
        return len(self._window)


def sweep_source(source, nsweeps: int, pose_lookup=None):
    """Wrap a pull-driven FrameSource so each yielded frame's data is
    the aggregation of the last ``nsweeps`` scans (Δt from the frames'
    own timestamps). ``pose_lookup(frame) -> (4, 4) world_T_sensor or
    None`` supplies ego poses (io/bag_io.bag_pose_lookup for a bag's
    odometry topic, or any callback). Identity when nsweeps == 1 —
    single sweeps still gain their zero Δt column from the pipeline's
    column pad."""
    import dataclasses

    if nsweeps <= 1:
        yield from source
        return
    buf = SweepBuffer(nsweeps)
    for frame in source:
        pose = None
        if pose_lookup is not None:
            pose = pose_lookup(frame)
            if pose is None:
                # a total key mismatch would otherwise degrade to the
                # very uncompensated stacking --poses exists to fix
                raise ValueError(
                    f"pose source has no pose for frame_id "
                    f"{frame.frame_id} (t={frame.timestamp}); check the "
                    "pose file's frame_id keying / odometry coverage"
                )
        agg = buf.push(np.asarray(frame.data), frame.timestamp, pose)
        yield dataclasses.replace(frame, data=agg)
