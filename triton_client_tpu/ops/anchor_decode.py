"""Anchor generation + box-delta decoding for the detectron family.

The reference serves RetinaNet/FCOS behind Triton with decoding and NMS
already applied server-side (clients/detectron_client.py:4-21 consumes
finished boxes/classes/scores). In this framework that server side is
in-tree, so the decode must exist here — implemented as fixed-shape
jnp ops that fuse into the model's jit:

  * dense per-level anchor grids (RetinaNet: 3 scales x 3 ratios per
    cell, strides 8..128 for FPN P3-P7);
  * Faster-RCNN delta decode (dx,dy,dw,dh vs anchor, clamped dw/dh);
  * FCOS location + ltrb distance decode (anchor-free).

Everything is computed from static shapes at trace time — anchors are
constants folded into the compiled program, not a host-side table.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax.numpy as jnp
import numpy as np

# Detectron2 RetinaNet defaults: sizes 32..512 on P3..P7, 3 octave
# scales, aspect ratios 1:2 / 1:1 / 2:1.
RETINA_STRIDES = (8, 16, 32, 64, 128)
RETINA_SIZES = (32, 64, 128, 256, 512)
RETINA_RATIOS = (0.5, 1.0, 2.0)
RETINA_OCTAVES = (1.0, 2 ** (1 / 3), 2 ** (2 / 3))

# Delta clamp: log(max scale factor), detectron2's SCALE_CLAMP.
_SCALE_CLAMP = math.log(1000.0 / 16)


def cell_anchors(
    size: float,
    ratios: Sequence[float] = RETINA_RATIOS,
    octaves: Sequence[float] = RETINA_OCTAVES,
) -> np.ndarray:
    """(A, 4) xyxy anchors centered at the origin for one level."""
    out = []
    for octave in octaves:
        area = (size * octave) ** 2
        for ratio in ratios:
            w = math.sqrt(area / ratio)
            h = w * ratio
            out.append([-w / 2, -h / 2, w / 2, h / 2])
    return np.asarray(out, np.float32)


def level_anchors(
    feat_hw: tuple[int, int], stride: int, base: np.ndarray
) -> np.ndarray:
    """(H*W*A, 4) anchors for one pyramid level (host-side constant)."""
    h, w = feat_hw
    shift_x = (np.arange(w, dtype=np.float32) + 0.5) * stride
    shift_y = (np.arange(h, dtype=np.float32) + 0.5) * stride
    sx, sy = np.meshgrid(shift_x, shift_y)
    shifts = np.stack([sx, sy, sx, sy], axis=-1).reshape(-1, 1, 4)
    return (shifts + base[None]).reshape(-1, 4)


def pyramid_anchors(
    input_hw: tuple[int, int],
    strides: Sequence[int] = RETINA_STRIDES,
    sizes: Sequence[float] = RETINA_SIZES,
    ratios: Sequence[float] = RETINA_RATIOS,
    octaves: Sequence[float] = RETINA_OCTAVES,
) -> np.ndarray:
    """All-level (N, 4) anchor table for an input resolution. Feature
    sizes follow ceil-division like the conv stack's SAME padding."""
    out = []
    for stride, size in zip(strides, sizes):
        feat_hw = (
            -(-input_hw[0] // stride),
            -(-input_hw[1] // stride),
        )
        out.append(level_anchors(feat_hw, stride, cell_anchors(size, ratios, octaves)))
    return np.concatenate(out, axis=0)


def decode_deltas(anchors: jnp.ndarray, deltas: jnp.ndarray) -> jnp.ndarray:
    """Faster-RCNN parameterization: anchors (N, 4) xyxy + deltas
    (..., N, 4) [dx, dy, dw, dh] -> (..., N, 4) xyxy boxes."""
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = anchors[:, 0] + 0.5 * aw
    ay = anchors[:, 1] + 0.5 * ah

    dx, dy, dw, dh = (deltas[..., i] for i in range(4))
    dw = jnp.clip(dw, None, _SCALE_CLAMP)
    dh = jnp.clip(dh, None, _SCALE_CLAMP)

    cx = dx * aw + ax
    cy = dy * ah + ay
    w = jnp.exp(dw) * aw
    h = jnp.exp(dh) * ah
    return jnp.stack(
        [cx - 0.5 * w, cy - 0.5 * h, cx + 0.5 * w, cy + 0.5 * h], axis=-1
    )


def fcos_locations(
    input_hw: tuple[int, int], strides: Sequence[int] = RETINA_STRIDES
) -> np.ndarray:
    """(N, 2) FCOS per-cell center locations across the pyramid."""
    out = []
    for stride in strides:
        h = -(-input_hw[0] // stride)
        w = -(-input_hw[1] // stride)
        xs = (np.arange(w, dtype=np.float32) + 0.5) * stride
        ys = (np.arange(h, dtype=np.float32) + 0.5) * stride
        gx, gy = np.meshgrid(xs, ys)
        out.append(np.stack([gx, gy], axis=-1).reshape(-1, 2))
    return np.concatenate(out, axis=0)


def fcos_decode(locations: jnp.ndarray, ltrb: jnp.ndarray) -> jnp.ndarray:
    """locations (N, 2) + ltrb distances (..., N, 4) -> xyxy boxes."""
    x, y = locations[:, 0], locations[:, 1]
    return jnp.stack(
        [
            x - ltrb[..., 0],
            y - ltrb[..., 1],
            x + ltrb[..., 2],
            y + ltrb[..., 3],
        ],
        axis=-1,
    )
