"""Fused-kernel routing: the ONE place that decides whether a pipeline
stage runs its hand-written Pallas fusion or the XLA reference path.

Mirrors the trace-time routing idiom of ``ops/nms._nms_mode``: shapes
and backends are static under jit, so the decision is baked into the
executable and can never flip mid-serve. Three layers of control, most
specific wins:

  * ``TPU_FUSED_KERNELS`` env — ``0``/``off`` disables every fusion;
    ``1``/``on``/``auto`` enables routing; a comma list
    (``voxelize_scatter,decode_nms``) enables ONLY the named stages.
    Read per trace, like ``TRITON_CLIENT_TPU_NMS``.
  * per-pipeline config knob (``Detect2DConfig.fused`` /
    ``Detect3DConfig.fused``: ``auto``/``on``/``off``) — the spec-extra
    opt-out: the resolved stage list is published as
    ``spec.extra["fused_stages"]`` so remote clients and bench rows can
    see exactly which fusions a served model runs.
  * backend — ``auto`` routes fused only on a real TPU backend (XLA is
    faster than interpret mode on CPU); ``on`` forces the fusion
    everywhere, running the SAME kernels under the Pallas interpreter
    (how the tier-1 parity matrix pins kernel numerics on CPU).

Stage names are the shared vocabulary between pipelines, bench rows,
``obs/opstats`` per-stage attribution and ``perf/profile_fused``:

  * ``voxelize_scatter`` — ops/pallas_voxel.fused_mean_volume
  * ``decode_nms``       — ops/pallas_decode (2D decode+NMS+pack /
                           3D residual decode + suppress+pack)
"""

from __future__ import annotations

import os

FUSED_STAGES = ("voxelize_scatter", "decode_nms")

_OFF = ("0", "off", "false", "none", "")
_ON = ("1", "on", "true", "all", "auto")


def _env_stages() -> tuple[str, ...] | None:
    """Stage allowlist from ``TPU_FUSED_KERNELS``; ``None`` = everything
    off. Unknown stage names in a comma list are ignored (an operator
    typo should degrade to the reference path, not crash a server)."""
    raw = os.environ.get("TPU_FUSED_KERNELS", "auto").strip().lower()
    if raw in _OFF:
        return None
    if raw in _ON:
        return FUSED_STAGES
    names = tuple(s.strip() for s in raw.split(",") if s.strip())
    return tuple(s for s in names if s in FUSED_STAGES) or None


def fused_interpret() -> bool:
    """Whether fused kernels must run under the Pallas interpreter
    (everywhere but a real TPU backend — same rule as ops.nms)."""
    import jax

    return jax.default_backend() != "tpu"


def fused_stage_enabled(stage: str, mode: str = "auto") -> bool:
    """Resolve one stage against the env knob, the pipeline ``mode``
    knob and the backend. ``mode='on'`` forces the fusion even off-TPU
    (interpret mode — tests); ``'off'`` is the spec-level opt-out;
    ``'auto'`` fuses only where it wins (TPU + env not disabled)."""
    if stage not in FUSED_STAGES:
        raise ValueError(f"unknown fused stage {stage!r} (of {FUSED_STAGES})")
    if mode == "off":
        return False
    allowed = _env_stages()
    if allowed is None or stage not in allowed:
        return False
    if mode == "on":
        return True
    if mode != "auto":
        raise ValueError(f"fused mode must be auto|on|off, got {mode!r}")
    return not fused_interpret()


def resolve_fused_stages(mode: str, candidates: tuple[str, ...]) -> tuple[str, ...]:
    """The pipeline-facing form: which of this pipeline's candidate
    stages actually route fused. Published as
    ``spec.extra['fused_stages']`` and keyed into bench rows."""
    return tuple(s for s in candidates if fused_stage_enabled(s, mode))
