"""Axis-aligned 2D box utilities (jittable, fixed-shape).

Behavioral parity targets (semantics only, all-new implementation):
reference utils/postprocess.py:12-103 and
clients/postprocess/base_postprocess.py:39-110 (xywh2xyxy / box_iou /
greedy NMS). The reference computes these per-frame on host CPU with
numpy/torch; here they are jnp functions designed to live inside the
jit-compiled postprocess so boxes never leave the device.
"""

from __future__ import annotations

import jax.numpy as jnp


def xywh2xyxy(boxes: jnp.ndarray) -> jnp.ndarray:
    """[cx, cy, w, h] -> [x1, y1, x2, y2]; boxes is (..., 4)."""
    cx, cy, w, h = jnp.split(boxes, 4, axis=-1)
    return jnp.concatenate(
        [cx - w * 0.5, cy - h * 0.5, cx + w * 0.5, cy + h * 0.5], axis=-1
    )


def xyxy2xywh(boxes: jnp.ndarray) -> jnp.ndarray:
    """[x1, y1, x2, y2] -> [cx, cy, w, h]; boxes is (..., 4)."""
    x1, y1, x2, y2 = jnp.split(boxes, 4, axis=-1)
    return jnp.concatenate(
        [(x1 + x2) * 0.5, (y1 + y2) * 0.5, x2 - x1, y2 - y1], axis=-1
    )


def box_area(boxes: jnp.ndarray) -> jnp.ndarray:
    """Area of (..., 4) xyxy boxes -> (...)."""
    w = jnp.clip(boxes[..., 2] - boxes[..., 0], 0.0, None)
    h = jnp.clip(boxes[..., 3] - boxes[..., 1], 0.0, None)
    return w * h


def box_iou(boxes1: jnp.ndarray, boxes2: jnp.ndarray) -> jnp.ndarray:
    """Pairwise IoU matrix between (N, 4) and (M, 4) xyxy boxes -> (N, M)."""
    lt = jnp.maximum(boxes1[:, None, :2], boxes2[None, :, :2])
    rb = jnp.minimum(boxes1[:, None, 2:], boxes2[None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0, None)
    inter = wh[..., 0] * wh[..., 1]
    union = box_area(boxes1)[:, None] + box_area(boxes2)[None, :] - inter
    return inter / jnp.maximum(union, 1e-9)


def scale_boxes(
    boxes: jnp.ndarray,
    model_hw: tuple[int, int],
    orig_hw: tuple[int, int],
    letterbox_meta: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Rescale xyxy boxes from model input resolution back to the original image.

    Parity: communicator/ros_inference.py:100-115 (_scale_boxes) which
    multiplies by (orig/model) per-axis after a plain cv2.resize. When
    ``letterbox_meta`` ([gain, pad_x, pad_y], as returned by
    ``ops.preprocess.letterbox``) is given, undoes that exact
    pad+scale instead — consuming the meta avoids recomputing the
    rounded geometry and drifting by a pixel.
    """
    if letterbox_meta is None:
        mh, mw = model_hw
        oh, ow = orig_hw
        sx = ow / mw
        sy = oh / mh
        return boxes * jnp.asarray([sx, sy, sx, sy], dtype=boxes.dtype)
    gain, pad_x, pad_y = letterbox_meta[0], letterbox_meta[1], letterbox_meta[2]
    pads = jnp.stack([pad_x, pad_y, pad_x, pad_y]).astype(boxes.dtype)
    return (boxes - pads) / gain
