"""Fused voxelize->scatter Pallas kernel (sorted-segment mean volume).

The XLA scatter that dominates ``second_iou`` device time is
``models/second._scatter_mean_volume``: a 131k-row scatter-ADD with
duplicate indices into the (n_cells+1, f+1) accumulator — XLA lowers
duplicate-index adds to a serialized update chain (~5 ms/scan measured,
BASELINE.md). This module replaces the whole voxelize->scatter stage
with the ragged-TPU formulation (*Ragged Paged Attention*, PAPERS.md):

  1. XLA prologue (cheap, fully parallel): cell assignment + one
     ``lax.sort`` by linearized cell id — the same sort the grouped
     voxelizer already pays — then segment ranks give every point a
     dense voxel SLOT in [0, max_voxels). Sorted order means a block of
     consecutive points touches a *contiguous* slot range.
  2. ONE Pallas kernel streams point blocks HBM->VMEM and reduces each
     block against only its 128-aligned local slot window — a
     (block, window) one-hot x (8, block) values matmul on the MXU, no
     gather, no scatter, no serialization. The per-slot feature sums,
     counts AND the mean division all happen in-kernel; the dense (8,
     v_out) accumulator never leaves VMEM (~1.3 MB at the 40k-voxel
     KITTI budget, vs the 34 MB dense cell accumulator the XLA path
     round-trips through HBM).
  3. XLA epilogue: one unique-index ``.set`` scatter places the V
     per-voxel means into the dense (nz, ny, nx, f) volume — V rows
     with NO duplicate indices (3x fewer rows than the reference
     scatter, and set-scatters don't serialize the way duplicate adds
     do).

Double buffering (fusion 3): the default path lets the Pallas grid
pipeline double-buffer the HBM->VMEM block loads (BlockSpec prefetch —
loads of block i+1 overlap compute of block i, the ``emit_pipeline``
pattern); ``TPU_FUSED_PIPELINE=manual`` routes an explicit 2-slot
``make_async_copy`` variant of the same kernel for rigs where the
hand-rolled schedule measures better (perf/profile_fused compares).

Numerics contract (documented tolerance, not bitwise): per-voxel means
reduce the SAME point set as ``_scatter_mean_volume`` but in sorted
row order through an MXU contraction, so sums may reassociate —
parity tests pin ``rtol=1e-5``. Budget caveat: slots saturate at
``max_voxels`` (the OpenPCDet grouped-path budget); scenes with more
occupied cells than the budget drop the overflow exactly like
``ops/voxelize.voxelize`` does, where the reference scatter path keeps
them (the same semantics gap Detect3DPipeline already logs for
scatter-vs-grouped routing).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_client_tpu.ops.voxelize import VoxelConfig, assign_cells, linearize_zyx
from triton_client_tpu.parallel.ragged_kernels import kernel_block_rows

_LANES = 128
_SUBLANES = 8
# Points per grid step. Must be a power of two >= _LANES so it divides
# every ragged row bucket at or above it (kernel_block_rows asserts).
POINT_BLOCK = 1024
# Slot window one block can touch: sorted slots advance by < POINT_BLOCK
# within a block, plus up to _LANES-1 slack from 128-aligning the base.
_WINDOW = POINT_BLOCK + _LANES


def pipeline_mode() -> str:
    """grid (BlockSpec auto double-buffering, default) | manual
    (explicit 2-slot make_async_copy schedule). Trace-time, like
    TRITON_CLIENT_TPU_NMS."""
    mode = os.environ.get("TPU_FUSED_PIPELINE", "grid").strip().lower()
    return mode if mode in ("grid", "manual") else "grid"


def _accum_block(out_ref, valsT, slots_row, base, *, window):
    """Shared reduce step: one (8, block) values block x its one-hot
    slot selector into the VMEM accumulator's 128-aligned window.
    ``slots_row``: (1, block) int32 sorted slots — lane-major, so the
    block tiles VMEM exactly (a (block, 1) column would pad 128x,
    TPL801); ``base``: scalar 128-aligned window start. Slots outside
    the window (the dump slot of a mixed real/pad block) compare false
    everywhere and vanish — their value rows are pre-zeroed by the
    validity weight anyway."""
    block = slots_row.shape[1]
    local = slots_row - base
    col = jax.lax.broadcasted_iota(jnp.int32, (window, block), 0)
    onehotT = (col == local).astype(jnp.float32)  # (window, block)
    contrib = jax.lax.dot_general(
        valsT,
        onehotT,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (8, window): same contraction over the block dim as the old
    # (block, window) one-hot, elementwise-identical operands — bitwise
    cur = out_ref[:, pl.ds(base, window)]
    out_ref[:, pl.ds(base, window)] = cur + contrib


def _finalize_means(out_ref, *, count_row):
    """In-kernel mean epilogue: divide every sum row by the count row
    (empty slots divide by 1 and stay 0; rows past the feature width
    are zero and stay zero)."""
    sums = out_ref[:]
    cnt = jnp.maximum(sums[count_row : count_row + 1, :], 1.0)
    out_ref[:] = sums / cnt


def _segment_mean_grid_kernel(
    bases_ref, valsT_ref, slots_ref, out_ref, *, n_blocks, window, count_row
):
    """Grid-pipelined form: one point block per grid step; the Pallas
    BlockSpec pipeline prefetches block i+1's HBM->VMEM copies while
    block i computes (the emit_pipeline-style double buffer)."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    _accum_block(
        out_ref, valsT_ref[:], slots_ref[:], bases_ref[i], window=window
    )

    @pl.when(i == n_blocks - 1)
    def _():
        _finalize_means(out_ref, count_row=count_row)


def _segment_mean_manual_kernel(
    bases_ref, valsT_hbm, slots_hbm, out_ref, *, n_blocks, block, window, count_row
):
    """Explicit double-buffered form: inputs stay in HBM/ANY; a 2-slot
    VMEM scratch + DMA-semaphore pair per stream overlaps the copy of
    block i+1 with the compute of block i (the pallas guide's
    run_scoped double-buffer pattern, hand-scheduled)."""

    def body(vals_vmem, slots_vmem, vsem, ssem):
        def copies(slot, bi):
            return (
                pltpu.make_async_copy(
                    valsT_hbm.at[:, pl.ds(bi * block, block)],
                    vals_vmem.at[slot],
                    vsem.at[slot],
                ),
                pltpu.make_async_copy(
                    slots_hbm.at[:, pl.ds(bi * block, block)],
                    slots_vmem.at[slot],
                    ssem.at[slot],
                ),
            )

        out_ref[:] = jnp.zeros_like(out_ref)
        for c in copies(0, 0):
            c.start()

        def step(bi, _):
            slot = jax.lax.rem(bi, 2)
            nxt = jax.lax.rem(bi + 1, 2)

            @pl.when(bi + 1 < n_blocks)
            def _():  # start the next block's DMAs before waiting
                for c in copies(nxt, bi + 1):
                    c.start()

            for c in copies(slot, bi):
                c.wait()
            _accum_block(
                out_ref,
                vals_vmem[slot],
                slots_vmem[slot],
                bases_ref[bi],
                window=window,
            )
            return 0

        jax.lax.fori_loop(0, n_blocks, step, 0)
        _finalize_means(out_ref, count_row=count_row)

    pl.run_scoped(
        body,
        vals_vmem=pltpu.VMEM((2, _SUBLANES, block), jnp.float32),
        slots_vmem=pltpu.VMEM((2, 1, block), jnp.int32),
        vsem=pltpu.SemaphoreType.DMA((2,)),
        ssem=pltpu.SemaphoreType.DMA((2,)),
    )


@functools.partial(
    jax.jit, static_argnames=("num_slots", "interpret", "pipeline")
)
def sorted_segment_mean_pallas(
    valsT: jnp.ndarray,
    slots: jnp.ndarray,
    num_slots: int,
    interpret: bool = False,
    pipeline: str = "grid",
) -> jnp.ndarray:
    """Per-slot mean of SORTED rows: ``valsT`` (8, N) f32 value rows
    (weight/count row included by the caller), ``slots`` (N,) int32
    non-decreasing slot ids with ``num_slots`` as the dump id. N must
    be a POINT_BLOCK multiple (kernel_block_rows). Returns (8, v_out)
    f32 per-slot means — callers slice ``[:, :num_slots]``.

    The count row is fixed at row ``_SUBLANES - 1`` by convention so
    the kernel's mean epilogue never depends on the caller's feature
    width."""
    n = valsT.shape[1]
    if valsT.shape[0] != _SUBLANES or n % POINT_BLOCK:
        raise ValueError(f"valsT must be (8, k*{POINT_BLOCK}), got {valsT.shape}")
    n_blocks = n // POINT_BLOCK
    v_out = ((num_slots + 1 + _WINDOW + _LANES - 1) // _LANES) * _LANES
    count_row = _SUBLANES - 1

    # 128-aligned window base per block, from each block's first (lowest)
    # slot — scalar-prefetched so both kernel forms read it from SMEM.
    bases = (slots[::POINT_BLOCK] // _LANES) * _LANES
    slots_row = slots.reshape(1, n)

    if pipeline == "manual":
        kernel = functools.partial(
            _segment_mean_manual_kernel,
            n_blocks=n_blocks,
            block=POINT_BLOCK,
            window=_WINDOW,
            count_row=count_row,
        )
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.ANY),
                pl.BlockSpec(memory_space=pltpu.ANY),
            ],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        )
    else:
        kernel = functools.partial(
            _segment_mean_grid_kernel,
            n_blocks=n_blocks,
            window=_WINDOW,
            count_row=count_row,
        )
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n_blocks,),
            in_specs=[
                pl.BlockSpec((_SUBLANES, POINT_BLOCK), lambda i, bases: (0, i)),
                pl.BlockSpec((1, POINT_BLOCK), lambda i, bases: (0, i)),
            ],
            out_specs=pl.BlockSpec((_SUBLANES, v_out), lambda i, bases: (0, 0)),
        )
    with jax.named_scope("fused:voxelize_scatter"):
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((_SUBLANES, v_out), jnp.float32),
            interpret=interpret,
        )(bases.astype(jnp.int32), valsT, slots_row)


def fused_mean_volume(
    points: jnp.ndarray,
    count: jnp.ndarray,
    voxel: VoxelConfig,
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused replacement for ``models/second._scatter_mean_volume``:
    (N, F) padded cloud -> dense (nz, ny, nx, F) per-cell mean volume.
    Same cell assignment/linearization sources as every other voxel
    path (ops/voxelize), so the two routes can only differ by fp
    reassociation and the max_voxels budget (module docstring)."""
    nx, ny, nz = voxel.grid_size
    n, f = points.shape
    if f > _SUBLANES - 1:
        raise ValueError(
            f"fused_mean_volume supports <= {_SUBLANES - 1} point "
            f"features (count row rides row {_SUBLANES - 1}), got {f}"
        )
    v_cap = voxel.max_voxels

    ijk, valid = assign_cells(points, count, voxel)
    vid, n_cells = linearize_zyx(ijk, valid, voxel)

    # Sort by cell id (stable, like ops/voxelize.voxelize), then dense
    # slot = rank of this point's distinct cell among occupied cells.
    order = jnp.argsort(vid)
    vid_s = vid[order]
    pts_s = points[order].astype(jnp.float32)
    valid_s = vid_s < n_cells
    first = (
        jnp.concatenate([jnp.ones((1,), bool), vid_s[1:] != vid_s[:-1]])
        & valid_s
    )
    slot_raw = jnp.cumsum(first) - 1
    keep = valid_s & (slot_raw < v_cap)
    slot = jnp.where(keep, slot_raw, v_cap).astype(jnp.int32)
    w = keep.astype(jnp.float32)

    # (8, N_pad) SoA value rows: features * weight, count row last.
    n_pad = kernel_block_rows(n, POINT_BLOCK)
    valsT = jnp.zeros((_SUBLANES, n_pad), jnp.float32)
    valsT = valsT.at[:f, :n].set(pts_s.T * w[None, :])
    valsT = valsT.at[_SUBLANES - 1, :n].set(w)
    slots_p = jnp.full((n_pad,), v_cap, jnp.int32).at[:n].set(slot)

    means8 = sorted_segment_mean_pallas(
        valsT,
        slots_p,
        num_slots=v_cap,
        interpret=interpret,
        pipeline=pipeline_mode(),
    )
    means = means8[:f, :v_cap].T  # (v_cap, f)

    # Epilogue: place per-slot means at their cells — V unique indices
    # (empty slots share the dump cell, sliced off), a set-scatter with
    # no duplicate-add serialization.
    cslot = jnp.where(first & keep, slot_raw, v_cap)
    cells = (
        jnp.full((v_cap + 1,), n_cells, jnp.int32)
        .at[cslot]
        .set(vid_s.astype(jnp.int32), mode="drop")[:v_cap]
    )
    canvas = jnp.zeros((n_cells + 1, f), jnp.float32)
    canvas = canvas.at[cells].set(means, mode="promise_in_bounds")
    return canvas[:n_cells].reshape(nz, ny, nx, f)
