"""On-device multi-object tracking head: Kalman + greedy association.

ROADMAP item 5's tracking head, built to compose with the detectors'
decoded outputs *without leaving HBM*: the whole per-frame step —
constant-velocity Kalman predict, two-stage ByteTrack-style greedy
association, update, birth/death bookkeeping — is one jit-compiled
function over fixed-shape arrays (``max_tracks`` slots, the detector's
``max_det`` rows), so the session layer (runtime/sessions.py) can chain
it after a detector launch and keep track state device-resident between
frames. ``jax.vmap`` over the step gives synchronized multi-camera
session groups for free (drivers/multicam.py stacks C cameras on the
leading axis).

Design choices, each motivated by the serving context:

  * **Hungarian-free greedy matching** — greedy closest-match
    association is within a hair of Hungarian on detection-quality
    tracks, and greedy is a fixed-trip ``lax.fori_loop`` of masked
    argmaxes — shape-static, jit-friendly, and bitwise-reproducible
    against the NumPy mirror below (``reference_step``), which the
    parity gate in tests/ compares association-for-association.
  * **Two-stage matching (ByteTrack)** — high-score detections
    associate first at a wide gate; still-unmatched tracks then get a
    second chance against LOW-score detections at a tighter gate,
    recovering occluded objects the score threshold would have dropped.
  * **Decoupled scalar Kalman** — per-axis (pos, vel) 2x2 blocks with
    diagonal noise reduce predict/update to elementwise arithmetic: no
    matrix inverses, nothing the VPU can't chew through in one pass,
    and the NumPy reference stays operation-for-operation identical.
  * **Measured velocity seeding** — when the detector carries a
    velocity head (CenterPoint, ``velocity_cols``), matched tracks fuse
    the measured (vx, vy) as a second scalar update and new tracks are
    born with it, so the motion prior is right from frame one.

Detection rows follow ops/detect3d_postprocess.py's packed convention
``[x, y, ..., score, label]``: centers are columns 0:2, score column
-2. Track ids are int32, strictly positive, offset by the session
layer's ``id_base`` so ids never alias across session restarts or
replica failovers (the handoff contract in runtime/router.py).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

#: Gated / impossible affinity. Large-negative finite (not -inf) so an
#: argmax over an all-gated matrix still returns index 0 and the
#: validity check ``best > GATED / 2`` stays well-defined in f32.
GATED = np.float32(-1e18)


@dataclasses.dataclass(frozen=True)
class TrackerConfig:
    """Static tracker shape/policy — hashable, so one jit per config."""

    max_tracks: int = 64
    #: ByteTrack score split: >= high associates in stage 1 (and may
    #: found new tracks); [low, high) only rescues existing tracks
    score_high: float = 0.5
    score_low: float = 0.1
    #: stage-1 association gate, center distance (world units)
    gate_dist: float = 5.0
    #: stage-2 (low-score rescue) gate — tighter: a weak detection must
    #: be right where the track predicted it
    gate_dist_low: float = 2.5
    #: Mahalanobis gate on the position innovation (chi-square, 2 dof,
    #: p=0.01 -> 9.21); <= 0 disables the statistical gate
    gate_maha2: float = 9.21
    #: consecutive missed frames before a track slot frees
    max_age: int = 3
    dt: float = 1.0
    #: process noise added per predict (position / velocity variance)
    q_pos: float = 0.1
    q_vel: float = 0.1
    #: measurement noise (position; velocity when measured)
    r_pos: float = 0.5
    r_vel: float = 1.0
    #: initial covariance of a newborn track
    p0_pos: float = 1.0
    p0_vel: float = 10.0
    #: detection columns holding measured (vx, vy) — CenterPoint's
    #: velocity head rides columns 7:9 of the packed row; None = no
    #: measured velocity (2D trackers, velocity-less 3D heads)
    velocity_cols: tuple | None = (7, 9)

    def __post_init__(self):
        if self.velocity_cols is not None:
            a, b = self.velocity_cols
            if b - a != 2:
                raise ValueError("velocity_cols must span exactly 2 columns")


#: state-dict leaves, all fixed-shape: the session layer stores exactly
#: this pytree on device between frames
STATE_KEYS = (
    "mean", "cov", "box", "tid", "age", "hits",
    "next_id", "frame", "births", "deaths",
)

#: output tensor names the session hook adds to a response
OUTPUT_KEYS = (
    "tracks", "track_ids", "tracks_valid", "track_assign", "det_track_ids",
    "innovation",
)

#: outputs a coast (predict-only) frame produces — the track table only;
#: there are no detections to associate on a coasted frame
COAST_OUTPUT_KEYS = ("tracks", "track_ids", "tracks_valid")


def init_state(cfg: TrackerConfig, det_dim: int, id_base: int = 0):
    """Fresh (host) tracker state for one stream. ``id_base`` offsets
    every id this state will ever mint — the session layer derives it
    from (manager namespace, session epoch) so a restarted session's
    ids can never collide with its previous life's."""
    t = int(cfg.max_tracks)
    return {
        # [x, y, vx, vy] per slot
        "mean": np.zeros((t, 4), np.float32),
        # per-axis 2x2 covariance packed [p00, p01, p11] (x/y share it)
        "cov": np.zeros((t, 3), np.float32),
        # last matched detection row, center/velocity refreshed from
        # the fused mean
        "box": np.zeros((t, int(det_dim)), np.float32),
        "tid": np.zeros((t,), np.int32),  # 0 = free slot
        "age": np.zeros((t,), np.int32),
        "hits": np.zeros((t,), np.int32),
        "next_id": np.asarray(int(id_base) + 1, np.int32),
        "frame": np.asarray(0, np.int32),
        "births": np.asarray(0, np.int32),
        "deaths": np.asarray(0, np.int32),
    }


# -- association ---------------------------------------------------------------


def greedy_assign(xp, cost, trips: int):
    """Greedy one-to-one matching over an affinity matrix.

    ``trips`` masked global argmaxes: take the best remaining
    (track, det) pair, bind it, blank its row and column. ``xp`` is
    ``jnp`` or ``np`` — the loop body is the same expression sequence
    for both (both argmaxes pick the FIRST maximum on ties, row-major),
    which is what makes the device/host parity gate bitwise. Returns
    ``(track_det, det_track)``: per-track matched detection index and
    per-detection matched track slot, -1 where unmatched."""
    t, n = cost.shape
    track_det = xp.full((t,), -1, xp.int32)
    det_track = xp.full((n,), -1, xp.int32)
    if xp is np:
        cost = cost.copy()
        for _ in range(trips):
            flat = int(np.argmax(cost))
            ti, di = flat // n, flat % n
            if cost[ti, di] > GATED / 2:
                track_det[ti] = di
                det_track[di] = ti
                cost[ti, :] = GATED
                cost[:, di] = GATED
        return track_det, det_track

    def body(_, carry):
        cost, track_det, det_track = carry
        flat = xp.argmax(cost)
        ti, di = flat // n, flat % n
        ok = cost[ti, di] > GATED / 2
        track_det = xp.where(
            ok, track_det.at[ti].set(di.astype(xp.int32)), track_det
        )
        det_track = xp.where(
            ok, det_track.at[di].set(ti.astype(xp.int32)), det_track
        )
        cost = xp.where(ok, cost.at[ti, :].set(GATED), cost)
        cost = xp.where(ok, cost.at[:, di].set(GATED), cost)
        return cost, track_det, det_track

    _, track_det, det_track = jax.lax.fori_loop(
        0, trips, body, (cost, track_det, det_track)
    )
    return track_det, det_track


def _affinity(xp, cfg, mean, cov, tid, centers, det_mask, gate_dist):
    """Negative squared center distance, gated on distance and (when
    enabled) the Mahalanobis position innovation. Rows: track slots;
    cols: detections. Inactive slots / masked detections are GATED."""
    dx = mean[:, 0:1] - centers[:, 0][None, :]
    dy = mean[:, 1:2] - centers[:, 1][None, :]
    d2 = dx * dx + dy * dy
    gated = d2 > np.float32(float(gate_dist) ** 2)
    if cfg.gate_maha2 > 0:
        # per-axis innovation variance post-predict: S = p00 + r
        s = cov[:, 0:1] + np.float32(cfg.r_pos)
        gated = gated | (d2 / s > np.float32(cfg.gate_maha2))
    keep = (tid > 0)[:, None] & det_mask[None, :] & ~gated
    return xp.where(keep, (-d2).astype(xp.float32), GATED)


# -- Kalman (decoupled per-axis scalar blocks) ---------------------------------


def _predict(xp, cfg, mean, cov):
    dt = np.float32(cfg.dt)
    x, y, vx, vy = mean[:, 0], mean[:, 1], mean[:, 2], mean[:, 3]
    p00, p01, p11 = cov[:, 0], cov[:, 1], cov[:, 2]
    x = x + vx * dt
    y = y + vy * dt
    n00 = p00 + dt * (p01 + p01) + dt * dt * p11 + np.float32(cfg.q_pos)
    n01 = p01 + dt * p11
    n11 = p11 + np.float32(cfg.q_vel)
    return xp.stack([x, y, vx, vy], axis=1), xp.stack([n00, n01, n11], axis=1)


def _update(xp, cfg, mean, cov, z_pos, z_vel, matched):
    """Scalar-gain update per axis for matched slots; unmatched slots
    pass through untouched. ``z_vel`` is None without a velocity head."""
    x, y, vx, vy = mean[:, 0], mean[:, 1], mean[:, 2], mean[:, 3]
    p00, p01, p11 = cov[:, 0], cov[:, 1], cov[:, 2]
    s = p00 + np.float32(cfg.r_pos)
    k0 = p00 / s
    k1 = p01 / s
    ix = z_pos[:, 0] - x
    iy = z_pos[:, 1] - y
    ux, uy = x + k0 * ix, y + k0 * iy
    uvx, uvy = vx + k1 * ix, vy + k1 * iy
    one = np.float32(1.0)
    u00 = (one - k0) * p00
    u01 = (one - k0) * p01
    u11 = p11 - k1 * p01
    if z_vel is not None:
        sv = u11 + np.float32(cfg.r_vel)
        kv = u11 / sv
        uvx = uvx + kv * (z_vel[:, 0] - uvx)
        uvy = uvy + kv * (z_vel[:, 1] - uvy)
        u11 = (one - kv) * u11
    m = matched
    mean = xp.stack(
        [
            xp.where(m, ux, x),
            xp.where(m, uy, y),
            xp.where(m, uvx, vx),
            xp.where(m, uvy, vy),
        ],
        axis=1,
    )
    cov = xp.stack(
        [
            xp.where(m, u00, p00),
            xp.where(m, u01, p01),
            xp.where(m, u11, p11),
        ],
        axis=1,
    )
    return mean, cov


# -- birth bookkeeping ---------------------------------------------------------


def _scatter_births(xp, t, n, takes, free_rank, placed, born_rank):
    """Order-preserving one-to-one map between taking slots and placed
    detections: the rank-i free slot receives the rank-i newborn.
    Returns ``(slot_det, det_slot)``: per-slot detection index (0 on
    non-taking slots) and per-detection slot index (0 where not
    placed). Both backends route through the same rank pairing, and
    every rank below the birth count has exactly one writer —
    deterministic, hence bitwise-comparable."""
    if xp is np:
        det_ids = np.nonzero(placed)[0].astype(np.int32)
        slot_ids = np.nonzero(takes)[0].astype(np.int32)
        slot_det = np.zeros((t,), np.int32)
        det_slot = np.zeros((n,), np.int32)
        slot_det[slot_ids] = det_ids
        det_slot[det_ids] = slot_ids
        return slot_det, det_slot
    # rank tables carry one junk row (index t) so non-placed /
    # non-taking writes land off the read range
    rank_det = xp.zeros((t + 1,), xp.int32)
    rank_det = rank_det.at[xp.where(placed, born_rank, t)].set(
        xp.where(placed, xp.arange(n, dtype=xp.int32), 0)
    )
    rank_slot = xp.zeros((t + 1,), xp.int32)
    rank_slot = rank_slot.at[xp.where(takes, free_rank, t)].set(
        xp.where(takes, xp.arange(t, dtype=xp.int32), 0)
    )
    slot_det = xp.where(takes, rank_det[xp.where(takes, free_rank, 0)], 0)
    det_slot = xp.where(placed, rank_slot[xp.where(placed, born_rank, 0)], 0)
    return slot_det, det_slot


# -- the per-frame step --------------------------------------------------------


def _step(xp, cfg: TrackerConfig, state, detections, valid):
    """One tracking frame. ``detections``: (N, D) packed rows,
    ``valid``: (N,) bool. Returns (new_state, outputs); outputs carry
    the full track table plus the per-detection association
    (``track_assign``) the parity gate checks bitwise."""
    t = int(cfg.max_tracks)
    detections = detections.astype(xp.float32)
    n = int(detections.shape[0])
    valid = valid.astype(np.bool_ if xp is np else jnp.bool_)
    score = detections[:, -2]
    centers = detections[:, 0:2]
    high = valid & (score >= np.float32(cfg.score_high))
    low = valid & ~high & (score >= np.float32(cfg.score_low))

    mean, cov = _predict(xp, cfg, state["mean"], state["cov"])

    trips = min(t, n)
    # stage 1: confident detections, wide gate
    cost1 = _affinity(xp, cfg, mean, cov, state["tid"], centers, high,
                      cfg.gate_dist)
    td1, dt1 = greedy_assign(xp, cost1, trips)
    # stage 2: still-unmatched tracks rescue low-score detections,
    # tighter gate
    tid2 = xp.where(td1 >= 0, xp.int32(0), state["tid"])
    cost2 = _affinity(xp, cfg, mean, cov, tid2, centers, low,
                      cfg.gate_dist_low)
    td2, dt2 = greedy_assign(xp, cost2, trips)

    track_det = xp.where(td1 >= 0, td1, td2)
    det_track = xp.where(dt1 >= 0, dt1, dt2)
    matched = track_det >= 0
    gather = xp.where(matched, track_det, 0)

    z_pos = centers[gather]
    z_vel = None
    if cfg.velocity_cols is not None:
        a, b = cfg.velocity_cols
        z_vel = detections[:, a:b][gather]

    # scene-dynamics statistic for the temporal-reuse scheduler
    # (runtime/temporal.py): mean normalized position innovation over
    # matched tracks — the same d2/s the Mahalanobis gate tests — plus
    # each unmatched HIGH detection charged the full gate (it beat no
    # prediction, i.e. a newly appeared object: maximal surprise). A
    # quiet scene reads ~0, a cut/burst reads >= the gate value, and K
    # adapts from it without any extra device work (computed pre-update
    # from values the step already holds).
    ivx = z_pos[:, 0] - mean[:, 0]
    ivy = z_pos[:, 1] - mean[:, 1]
    i_s = cov[:, 0] + np.float32(cfg.r_pos)
    i_d2 = ivx * ivx + ivy * ivy
    newborn_stat = high & (det_track < 0)
    gate_full = np.float32(cfg.gate_maha2 if cfg.gate_maha2 > 0 else 9.21)
    n_match_f = xp.sum(matched.astype(xp.float32))
    n_new_f = xp.sum(newborn_stat.astype(xp.float32))
    innov_sum = xp.sum(
        xp.where(matched, i_d2 / i_s, xp.float32(0.0))
    ) + gate_full * n_new_f
    innovation = (
        innov_sum / xp.maximum(n_match_f + n_new_f, xp.float32(1.0))
    ).astype(xp.float32)

    mean, cov = _update(xp, cfg, mean, cov, z_pos, z_vel, matched)

    # misses age; past max_age an active track's slot frees (and is
    # immediately reusable by this frame's births)
    active = state["tid"] > 0
    age = xp.where(matched, xp.int32(0), state["age"] + 1)
    dead = active & ~matched & (age > np.int32(cfg.max_age))
    tid = xp.where(dead, xp.int32(0), state["tid"])
    hits = xp.where(matched, state["hits"] + 1, state["hits"])
    box = xp.where(matched[:, None], detections[gather], state["box"])

    # births: unmatched HIGH detections claim free slots, rank-i slot
    # to rank-i detection (both ascending) — deterministic, replayable
    free = tid == 0
    newborn = high & (det_track < 0)
    free_rank = xp.cumsum(free.astype(xp.int32)) - 1
    born_rank = xp.cumsum(newborn.astype(xp.int32)) - 1
    n_born = xp.minimum(
        xp.sum(free.astype(xp.int32)), xp.sum(newborn.astype(xp.int32))
    )
    takes = free & (free_rank < n_born)
    placed = newborn & (born_rank < n_born)
    slot_det, det_slot = _scatter_births(
        xp, t, n, takes, free_rank, placed, born_rank
    )

    det_new = detections[slot_det]
    if cfg.velocity_cols is not None:
        a = cfg.velocity_cols[0]
        bvx, bvy = det_new[:, a], det_new[:, a + 1]
    else:
        bvx = bvy = xp.zeros((t,), xp.float32)
    b_mean = xp.stack([det_new[:, 0], det_new[:, 1], bvx, bvy], axis=1)
    b_cov = xp.broadcast_to(
        xp.asarray([cfg.p0_pos, 0.0, cfg.p0_vel], dtype=xp.float32), (t, 3)
    )
    new_ids = state["next_id"].astype(xp.int32) + free_rank
    mean = xp.where(takes[:, None], b_mean, mean)
    cov = xp.where(takes[:, None], b_cov, cov)
    box = xp.where(takes[:, None], det_new, box)
    tid = xp.where(takes, new_ids, tid)
    age = xp.where(takes, xp.int32(0), age)
    hits = xp.where(takes, xp.int32(1), hits)

    # refresh the reported row's center (and velocity columns, when
    # present) from the fused mean
    box = xp.concatenate([mean[:, 0:2], box[:, 2:]], axis=1)
    if cfg.velocity_cols is not None and box.shape[1] >= cfg.velocity_cols[1]:
        a = cfg.velocity_cols[0]
        box = xp.concatenate([box[:, :a], mean[:, 2:4], box[:, a + 2:]],
                             axis=1)

    new_state = {
        "mean": mean,
        "cov": cov,
        "box": box,
        "tid": tid,
        "age": age,
        "hits": hits,
        "next_id": state["next_id"] + n_born,
        "frame": state["frame"] + xp.int32(1),
        "births": state["births"] + n_born,
        "deaths": state["deaths"] + xp.sum(dead.astype(xp.int32)),
    }
    # per-detection association: matched track slot, else newborn slot,
    # else -1 — the tensor the parity gate compares bitwise
    assign_slot = xp.where(placed, det_slot, det_track).astype(xp.int32)
    det_track_ids = xp.where(
        assign_slot >= 0, tid[xp.where(assign_slot >= 0, assign_slot, 0)],
        xp.int32(-1),
    )
    outputs = {
        "tracks": box,
        "track_ids": tid,
        "tracks_valid": tid > 0,
        "track_assign": assign_slot,
        "det_track_ids": det_track_ids.astype(xp.int32),
        "innovation": innovation,
    }
    return new_state, outputs


def _coast(xp, cfg: TrackerConfig, state):
    """One predict-only (coast) frame: the constant-velocity prior
    advances every slot, covariance inflates by the process noise, and
    the reported boxes are refreshed from the predicted mean — no
    association, no update, no births or deaths. Ages and ids are
    untouched: a coast frame is a *deliberate* skip, not a miss, so the
    next keyframe sees exactly the miss-age it would have seen had the
    stream paused. Mirrors ``_step``'s expression sequence for the
    predict + box-refresh stanzas, so the parity gate compares bitwise."""
    mean, cov = _predict(xp, cfg, state["mean"], state["cov"])
    box = state["box"]
    box = xp.concatenate([mean[:, 0:2], box[:, 2:]], axis=1)
    if cfg.velocity_cols is not None and box.shape[1] >= cfg.velocity_cols[1]:
        a = cfg.velocity_cols[0]
        box = xp.concatenate([box[:, :a], mean[:, 2:4], box[:, a + 2:]],
                             axis=1)
    tid = state["tid"]
    new_state = {
        "mean": mean,
        "cov": cov,
        "box": box,
        "tid": tid,
        "age": state["age"],
        "hits": state["hits"],
        "next_id": state["next_id"],
        "frame": state["frame"] + xp.int32(1),
        "births": state["births"],
        "deaths": state["deaths"],
    }
    outputs = {
        "tracks": box,
        "track_ids": tid,
        "tracks_valid": tid > 0,
    }
    return new_state, outputs


@functools.lru_cache(maxsize=32)
def make_step(cfg: TrackerConfig):
    """The jit-compiled device step for one stream:
    (state, detections, valid) -> (state, outputs). Cached per config —
    one trace per (config, shape)."""
    return jax.jit(functools.partial(_step, jnp, cfg))


@functools.lru_cache(maxsize=32)
def make_group_step(cfg: TrackerConfig):
    """vmap of the step over a leading session-group axis: C
    synchronized cameras advance as one launch (drivers/multicam.py)."""
    return jax.jit(jax.vmap(functools.partial(_step, jnp, cfg)))


@functools.lru_cache(maxsize=32)
def make_coast_step(cfg: TrackerConfig):
    """The jit-compiled predict-only step for one stream:
    (state,) -> (state, outputs). Cached per config, one trace per
    (config, shape) — the whole temporal-reuse coast path is this one
    launch."""
    return jax.jit(functools.partial(_coast, jnp, cfg))


@functools.lru_cache(maxsize=32)
def make_group_coast(cfg: TrackerConfig):
    """vmap of the coast step over a leading session-group axis."""
    return jax.jit(jax.vmap(functools.partial(_coast, jnp, cfg)))


def reference_step(cfg: TrackerConfig, state, detections, valid):
    """NumPy mirror of the device step — same expression sequence, so
    associations are bitwise-comparable. The tests' ground truth."""
    state = {k: np.asarray(v) for k, v in state.items()}
    det = np.asarray(detections, np.float32)
    return _step(np, cfg, state, det, np.asarray(valid, bool))


def reference_coast(cfg: TrackerConfig, state):
    """NumPy mirror of the coast step — the temporal-reuse parity
    gate's ground truth."""
    state = {k: np.asarray(v) for k, v in state.items()}
    return _coast(np, cfg, state)
