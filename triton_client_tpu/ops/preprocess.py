"""Jittable image preprocessing.

The reference does host-side cv2.resize + a numpy /255 transpose
(communicator/ros_inference.py:140, clients/preprocess/yolov5_preprocess.py:12-24).
Here resize + normalize + layout live inside the compiled graph so the
host only hands over the raw decoded frame once; XLA fuses the
normalize into the first conv.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def normalize_image(img: jnp.ndarray, scaling: str = "yolo") -> jnp.ndarray:
    """Pixel scaling modes.

    Parity: utils/preprocess.py:127-157 (image_adjust) — NONE/INCEPTION/
    VGG/COCO modes — plus the YOLOv5 /255 path
    (clients/preprocess/yolov5_preprocess.py:20-24). Input is (..., 3)
    RGB uint8/float; output float32.
    """
    x = img.astype(jnp.float32)
    if scaling in ("yolo", "coco", "raw255"):
        return x / 255.0
    if scaling == "inception":
        return x / 127.5 - 1.0
    if scaling == "vgg":
        return x - jnp.asarray([123.0, 117.0, 104.0], jnp.float32)
    if scaling == "none":  # detectron-style: raw pixels, no scaling
        return x
    raise ValueError(f"unknown scaling mode: {scaling}")


@functools.partial(jax.jit, static_argnames=("out_hw",))
def resize_bilinear(img: jnp.ndarray, out_hw: tuple[int, int]) -> jnp.ndarray:
    """Bilinear resize of (H, W, C) to out_hw (the cv2.resize default)."""
    return jax.image.resize(
        img.astype(jnp.float32),
        (out_hw[0], out_hw[1], img.shape[-1]),
        method="bilinear",
    )


@functools.partial(jax.jit, static_argnames=("out_hw", "pad_value"))
def letterbox(
    img: jnp.ndarray, out_hw: tuple[int, int], pad_value: float = 114.0
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Aspect-preserving resize + center pad (YOLO letterbox).

    Returns (out, meta) where meta = [gain, pad_x, pad_y] for undoing in
    ``scale_boxes``. Shapes are static: the scale factor is computed from
    the static input shape at trace time.
    """
    h, w = img.shape[0], img.shape[1]
    oh, ow = out_hw
    gain = min(oh / h, ow / w)
    nh, nw = int(round(h * gain)), int(round(w * gain))
    resized = jax.image.resize(
        img.astype(jnp.float32), (nh, nw, img.shape[-1]), method="bilinear"
    )
    pad_y, pad_x = (oh - nh) // 2, (ow - nw) // 2
    out = jnp.full((oh, ow, img.shape[-1]), pad_value, jnp.float32)
    out = jax.lax.dynamic_update_slice(out, resized, (pad_y, pad_x, 0))
    meta = jnp.asarray([gain, pad_x, pad_y], jnp.float32)
    return out, meta


def image_to_nchw(img: jnp.ndarray) -> jnp.ndarray:
    """(H, W, C) -> (1, C, H, W), the reference wire layout
    (yolov5_preprocess.py:20-24). Models here natively use NHWC (the TPU
    conv layout); this exists for KServe-facade parity.
    """
    return jnp.transpose(img, (2, 0, 1))[None]
