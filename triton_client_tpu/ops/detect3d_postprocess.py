"""3D detection postprocess: per-anchor predictions -> packed detections.

Parity target: the server-side OpenPCDet post_processing the reference
invokes inside TritonPythonModel.execute (examples/pointpillar_kitti/
1/model.py:163) and the client's extract_boxes contract
(clients/postprocess/detector_3d_postprocess.py: pred_boxes (N, 7),
pred_scores, pred_labels with 1-indexed labels). Fixed shapes
throughout: score gate + top-k prefilter + rotated-BEV NMS.

``fused=True`` routes the suppression + packing tail through ONE
Pallas launch (ops/pallas_decode.fused_suppress_pack_3d) instead of
the nms_bev while_loop + gather/concat chain — bitwise-identical keep
sequences and packed rows (greedy == fixpoint, the equivalence
ops/nms pins by test). Pipelines pick the route at trace time from
ops/fused.fused_stage_enabled.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from triton_client_tpu.ops.boxes3d import nms_bev


@functools.partial(
    jax.jit, static_argnames=("max_det", "pre_max", "fused", "interpret")
)
def extract_boxes_3d(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    score_thresh: float = 0.1,
    iou_thresh: float = 0.01,
    max_det: int = 128,
    pre_max: int = 512,
    fused: bool = False,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """boxes (B, N, 7+e), scores (B, N, nc) -> packed per-image
    detections. Columns past the canonical 7 ride along untouched
    (CenterPoint appends its 2 velocity channels there; the reference's
    det3d decode carries them the same way) — NMS geometry always reads
    the first 7.

    Returns (detections (B, max_det, 9+e), valid (B, max_det)) with
    rows [x, y, z, dx, dy, dz, heading, extras..., score, label];
    label is 1-indexed
    (0 reserved for background, the OpenPCDet convention the reference's
    pedestrian filter indexes against, communicator/ros_inference3d.py:156).
    """

    def one_image(b: jnp.ndarray, s: jnp.ndarray):
        cls_score = s.max(axis=-1)
        label = s.argmax(axis=-1) + 1
        gated = jnp.where(cls_score > score_thresh, cls_score, -jnp.inf)
        k = min(pre_max, gated.shape[0])
        top_scores, top_idx = jax.lax.top_k(gated, k)
        return _nms_pack_one(
            b[top_idx], top_scores, label[top_idx], iou_thresh, max_det,
            fused=fused, interpret=interpret,
        )

    return jax.vmap(one_image)(boxes, scores)


def _nms_pack_one(
    cand_boxes, cand_scores, cand_labels, iou_thresh, max_det,
    fused: bool = False, interpret: bool = False,
):
    """(K, 7+e) candidates (+ scores with -inf padding, 1-indexed
    labels) -> packed (max_det, 9+e) rows [box7, extras..., score,
    label] + valid mask. BEV NMS reads only the canonical 7 columns."""
    if fused:
        from triton_client_tpu.ops.pallas_decode import fused_suppress_pack_3d

        return fused_suppress_pack_3d(
            cand_boxes, cand_scores, cand_labels,
            iou_thresh=iou_thresh, max_det=max_det, interpret=interpret,
        )
    idx, keep = nms_bev(
        cand_boxes[:, :7], cand_scores, iou_thresh=iou_thresh, max_det=max_det
    )
    out = jnp.concatenate(
        [
            cand_boxes[idx],
            jnp.where(keep, cand_scores[idx], 0.0)[:, None],
            cand_labels[idx].astype(cand_boxes.dtype)[:, None],
        ],
        axis=-1,
    )
    return jnp.where(keep[:, None], out, 0.0), keep


@functools.partial(jax.jit, static_argnames=("max_det", "fused", "interpret"))
def nms_pack_3d(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    labels: jnp.ndarray,
    iou_thresh: float = 0.01,
    max_det: int = 128,
    fused: bool = False,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Packed NMS over PRE-GATED candidates: boxes (B, K, 7+e), scores
    (B, K) with -inf padding, labels (B, K) 1-indexed. The fast path for
    models exposing decode_topk (top-k on raw logits before any box
    decode, so only K boxes are ever decoded instead of the full anchor
    grid — the OpenPCDet post_processing order, but with the gate moved
    in front of the decode where XLA can't fuse it away itself)."""
    return jax.vmap(
        lambda b, s, l: _nms_pack_one(
            b, s, l, iou_thresh, max_det, fused=fused, interpret=interpret
        )
    )(boxes, scores, labels)
