"""Fused decode+NMS+pack Pallas kernels (the detection epilogue).

After the model body, the reference path runs the candidate tail as a
chain of small XLA ops — xywh->xyxy, the class-offset trick, the NMS
formulation, index gathers, concat/where packing (ops/detect_postprocess,
ops/detect3d_postprocess) — each a separate HLO with its own HBM
round-trip for a few-KB working set. This module collapses the tail
into single Pallas launches with every operand VMEM-resident, so
detections are produced on-device in packed form and feed the session
tracker (PR 15) with zero host hops:

  * :func:`fused_decode_nms_2d` — ONE kernel: candidate box decode
    (xywh->xyxy), adaptive class-offset, the greedy suppression loop
    (ops/pallas_nms's proven formulation) and the packed
    ``(max_det, 6)`` detection rows. Bitwise-identical to the
    ``nms_padded`` reference path (same conversion math, same offset
    stride, same tie-breaks — pinned by tests/test_fused_parity.py).
  * :func:`fused_residual_decode` — the 3D anchor-residual decode +
    direction rectification for the K top-k candidates as one
    elementwise kernel (collapses decode_boxes + rectify_direction +
    concat into one launch). Bitwise vs the JITTED XLA tail under the
    interpreter — both sides make identical FMA-contraction choices
    under one compiler; an EAGER reference call can differ by 1 ulp on
    the mul+add center columns (LLVM contracts jitted code only).
    Documented ulp-level tolerance on real TPU hardware (Mosaic
    transcendental lowering).
  * :func:`fused_suppress_pack_3d` — rotated-BEV suppression + packing
    in one kernel. The N x N rotated IoU matrix stays where it is
    fastest (the fully lane-parallel XLA polygon clip, round-1/3
    measured); the kernel consumes it and replaces the fixpoint
    while_loop + cumsum-pack + three gathers + concat/where with one
    launch emitting ``(max_det, 9+e)`` rows. Keep sequences are
    bitwise-identical to ``nms_bev`` + ``_nms_pack_one`` (greedy ==
    fixpoint, the equivalence ops/nms pins by test).

What stays deliberately UNFUSED: score gating + top-k compaction
(XLA's sort-based top_k beats any in-kernel reformulation at these
widths and runs fused into the head convs), and the 3D rotated-IoU
matrix (see above). ``perf/profile_fused`` measures both seams.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_client_tpu.ops.pallas_nms import (
    _NEG_INF,
    masked_pick,
    write_lane_col,
)

_LANES = 128


def _round_up(n: int, m: int) -> int:
    return ((max(1, n) + m - 1) // m) * m


# -- 2D: decode + class-offset + NMS + pack in one launch ---------------------


def _decode_nms_pack_2d_kernel(
    cand_ref,
    thresh_ref,
    out_ref,
    live_ref,
    *,
    max_det,
    box_format,
    class_agnostic,
):
    """cand_ref: (8, N) rows [c0..c3 (box_format coords), score
    (0-filled), class, valid, 0]; out_ref: (8, max_det_pad) rows
    [x1, y1, x2, y2, score, class, keep, 0]. The suppression loop is
    ops/pallas_nms._nms_kernel's, extended with in-kernel decode and
    the packing epilogue. Offset coords (IoU space) and original
    coords (output space) both stay resident — the reference path's
    separate batched_nms + gather/concat stages collapse here."""
    n = cand_ref.shape[1]
    iou_thresh = thresh_ref[0]

    c0, c1 = cand_ref[0:1, :], cand_ref[1:2, :]
    c2, c3 = cand_ref[2:3, :], cand_ref[3:4, :]
    score = cand_ref[4:5, :]
    clsf = cand_ref[5:6, :]
    valid = cand_ref[6:7, :] > 0.0

    if box_format == "xywh":  # ops/boxes.xywh2xyxy, bit for bit
        x1, y1 = c0 - c2 * 0.5, c1 - c3 * 0.5
        x2, y2 = c0 + c2 * 0.5, c1 + c3 * 0.5
    elif box_format == "xyxy":
        x1, y1, x2, y2 = c0, c1, c2, c3
    else:
        raise ValueError(f"box_format must be xywh|xyxy, got {box_format!r}")

    if class_agnostic:
        ox1, oy1, ox2, oy2 = x1, y1, x2, y2
    else:
        # ops/nms.batched_nms's adaptive stride: max |coord| over the
        # candidate set (fp max is associative, so the reduction
        # reorders bitwise-safely; zero pad lanes cannot raise it)
        m = jnp.maximum(jnp.maximum(jnp.abs(x1), jnp.abs(y1)),
                        jnp.maximum(jnp.abs(x2), jnp.abs(y2)))
        stride = jnp.max(m) * 2.0 + 1.0
        off = clsf * stride
        ox1, oy1, ox2, oy2 = x1 + off, y1 + off, x2 + off, y2 + off

    area = (ox2 - ox1) * (oy2 - oy1)
    live_ref[:] = jnp.where(valid, score, _NEG_INF)
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, n), 1)
    out_lane = jax.lax.broadcasted_iota(jnp.int32, (1, out_ref.shape[1]), 1)

    def body(i, _):
        live = live_ref[:]
        best_score = jnp.max(live)
        best = jnp.argmax(live[0, :]).astype(jnp.int32)
        is_valid = best_score > _NEG_INF
        sel = lane == best

        bx1o, by1o = masked_pick(sel, ox1), masked_pick(sel, oy1)
        bx2o, by2o = masked_pick(sel, ox2), masked_pick(sel, oy2)
        barea = masked_pick(sel, area)
        iw = jnp.clip(jnp.minimum(ox2, bx2o) - jnp.maximum(ox1, bx1o), 0.0, None)
        ih = jnp.clip(jnp.minimum(oy2, by2o) - jnp.maximum(oy1, by1o), 0.0, None)
        inter = iw * ih
        iou = inter / jnp.maximum(area + barea - inter, 1e-9)
        suppress = (iou > iou_thresh) | sel
        live_ref[:] = jnp.where(suppress & is_valid, _NEG_INF, live)

        vals = (
            masked_pick(sel, x1), masked_pick(sel, y1),
            masked_pick(sel, x2), masked_pick(sel, y2),
            masked_pick(sel, score), masked_pick(sel, clsf),
            1.0,
        )
        for r, v in enumerate(vals):
            write_lane_col(
                out_ref, r, out_lane, i, jnp.where(is_valid, v, 0.0)
            )
        return 0

    out_ref[:] = jnp.zeros(out_ref.shape, jnp.float32)
    jax.lax.fori_loop(0, max_det, body, 0)


@functools.partial(
    jax.jit,
    static_argnames=("box_format", "max_det", "class_agnostic", "interpret"),
)
def fused_decode_nms_2d(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    classes: jnp.ndarray,
    valid: jnp.ndarray,
    iou_thresh=0.45,
    max_det: int = 300,
    box_format: str = "xywh",
    class_agnostic: bool = False,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """One-launch candidate tail: boxes (K, 4) in ``box_format``,
    scores (K,) 0-filled on invalid slots, classes (K,) int, valid (K,)
    bool -> packed ``(max_det, 6)`` [x1, y1, x2, y2, score, class] rows
    + (max_det,) keep mask — the exact ``nms_padded`` contract."""
    k = boxes.shape[0]
    k_pad = _round_up(k, _LANES)
    md_pad = _round_up(max_det, _LANES)

    cand = jnp.zeros((8, k_pad), jnp.float32)
    cand = cand.at[0:4, :k].set(boxes.astype(jnp.float32).T)
    cand = cand.at[4, :k].set(scores.astype(jnp.float32))
    cand = cand.at[5, :k].set(classes.astype(jnp.float32))
    cand = cand.at[6, :k].set(valid.astype(jnp.float32))
    thresh = jnp.reshape(jnp.asarray(iou_thresh, jnp.float32), (1,))

    with jax.named_scope("fused:decode_nms"):
        out = pl.pallas_call(
            functools.partial(
                _decode_nms_pack_2d_kernel,
                max_det=max_det,
                box_format=box_format,
                class_agnostic=class_agnostic,
            ),
            out_shape=jax.ShapeDtypeStruct((8, md_pad), jnp.float32),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.VMEM((1, k_pad), jnp.float32)],
            interpret=interpret,
        )(cand, thresh)
    dets = out[0:6, :max_det].T
    keep = out[6, :max_det] > 0.0
    return dets, keep


# -- 3D: residual decode + rectify as one elementwise launch ------------------


def _residual_decode_kernel(
    d_ref, a_ref, dir_ref, out_ref, *, num_dir_bins, dir_offset
):
    """models/pointpillars.decode_boxes + rectify_direction, SoA rows.
    d_ref/a_ref: (8, K) delta/anchor rows [x, y, z, dx, dy, dz, r, 0];
    dir_ref: (1, K) f32 direction bin; out_ref: (8, K) decoded rows."""
    xa, ya, za = a_ref[0:1, :], a_ref[1:2, :], a_ref[2:3, :]
    dxa, dya, dza = a_ref[3:4, :], a_ref[4:5, :], a_ref[5:6, :]
    ra = a_ref[6:7, :]
    diag = jnp.sqrt(dxa * dxa + dya * dya)
    out_ref[0:1, :] = d_ref[0:1, :] * diag + xa
    out_ref[1:2, :] = d_ref[1:2, :] * diag + ya
    out_ref[2:3, :] = d_ref[2:3, :] * dza + za
    out_ref[3:4, :] = jnp.exp(jnp.clip(d_ref[3:4, :], -10, 10)) * dxa
    out_ref[4:5, :] = jnp.exp(jnp.clip(d_ref[4:5, :], -10, 10)) * dya
    out_ref[5:6, :] = jnp.exp(jnp.clip(d_ref[5:6, :], -10, 10)) * dza
    rot = d_ref[6:7, :] + ra
    period = 2 * jnp.pi / num_dir_bins
    out = rot - dir_offset
    out = out - jnp.floor(out / period) * period + dir_offset
    out_ref[6:7, :] = out + period * dir_ref[0:1, :]
    out_ref[7:8, :] = jnp.zeros_like(ra)


@functools.partial(
    jax.jit, static_argnames=("num_dir_bins", "dir_offset", "interpret")
)
def fused_residual_decode(
    deltas: jnp.ndarray,
    anchors: jnp.ndarray,
    dir_bin: jnp.ndarray,
    num_dir_bins: int,
    dir_offset: float,
    interpret: bool = False,
) -> jnp.ndarray:
    """(K, 7) deltas + (K, 7) anchors + (K,) dir bins -> (K, 7) decoded
    boxes with rectified heading, one elementwise Pallas launch."""
    k = deltas.shape[0]
    k_pad = _round_up(k, _LANES)
    d = jnp.zeros((8, k_pad), jnp.float32).at[0:7, :k].set(
        deltas.astype(jnp.float32).T
    )
    a = jnp.zeros((8, k_pad), jnp.float32).at[0:7, :k].set(
        anchors.astype(jnp.float32).T
    )
    db = jnp.zeros((1, k_pad), jnp.float32).at[0, :k].set(
        dir_bin.astype(jnp.float32)
    )
    with jax.named_scope("fused:decode_nms"):
        out = pl.pallas_call(
            functools.partial(
                _residual_decode_kernel,
                num_dir_bins=num_dir_bins,
                dir_offset=dir_offset,
            ),
            out_shape=jax.ShapeDtypeStruct((8, k_pad), jnp.float32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 3,
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            interpret=interpret,
        )(d, a, db)
    return out[0:7, :k].T


# -- 3D: rotated suppression + pack in one launch -----------------------------


def _suppress_pack_3d_kernel(
    iou_ref, rows_ref, thresh_ref, out_ref, live_ref, *, max_det, width
):
    """iou_ref: (N, N) rotated IoU of SCORE-SORTED candidates;
    rows_ref: (16, N) sorted rows [box7+extras (width cols), score
    (-inf gated), label, 0...]; out_ref: (16, max_det_pad) rows
    [box7+extras, score, label, keep, 0...]. The greedy loop picks the
    best live candidate, reads its IoU ROW with a masked sublane
    reduction (no dynamic indexing), suppresses, and packs — the
    while_loop fixpoint + gather/concat packing of _nms_pack_one in
    one launch."""
    n = rows_ref.shape[1]
    iou_thresh = thresh_ref[0]
    score = rows_ref[width : width + 1, :]
    live_ref[:] = score  # already -inf on gated/pad slots
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, n), 1)
    out_lane = jax.lax.broadcasted_iota(jnp.int32, (1, out_ref.shape[1]), 1)
    riota = jax.lax.broadcasted_iota(jnp.int32, (n, n), 0)

    def body(i, _):
        live = live_ref[:]
        best_score = jnp.max(live)
        best = jnp.argmax(live[0, :]).astype(jnp.int32)
        is_valid = best_score > _NEG_INF
        sel = lane == best

        # the selected candidate's IoU row, via sublane masking
        iou_row = jnp.sum(
            jnp.where(riota == best, iou_ref[:], 0.0), axis=0, keepdims=True
        )
        suppress = (iou_row > iou_thresh) | sel
        live_ref[:] = jnp.where(suppress & is_valid, _NEG_INF, live)

        for r in range(width + 2):  # box+extras, score, label
            v = masked_pick(sel, rows_ref[r : r + 1, :])
            write_lane_col(
                out_ref, r, out_lane, i, jnp.where(is_valid, v, 0.0)
            )
        write_lane_col(
            out_ref, width + 2, out_lane, i,
            jnp.where(is_valid, 1.0, 0.0),
        )
        return 0

    out_ref[:] = jnp.zeros(out_ref.shape, jnp.float32)
    jax.lax.fori_loop(0, max_det, body, 0)


@functools.partial(jax.jit, static_argnames=("max_det", "interpret"))
def fused_suppress_pack_3d(
    cand_boxes: jnp.ndarray,
    cand_scores: jnp.ndarray,
    cand_labels: jnp.ndarray,
    iou_thresh=0.01,
    max_det: int = 128,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(K, 7+e) candidates + (K,) -inf-gated scores + (K,) 1-indexed
    labels -> packed ``(max_det, 9+e)`` rows [box7, extras, score,
    label] + keep mask — the exact ``_nms_pack_one`` contract. Sort and
    the rotated IoU matrix stay in XLA (module docstring); suppression
    and packing run in one launch."""
    from triton_client_tpu.ops.boxes3d import boxes7_to_bev, rotated_iou_bev

    k, width = cand_boxes.shape
    k_pad = _round_up(k, _LANES)
    md_pad = _round_up(max_det, _LANES)
    if width + 3 > 16:
        raise ValueError(f"too many box columns for the packed rows: {width}")

    # score-sort exactly like nms_bev (stable, -inf padding sinks)
    order = jnp.argsort(-cand_scores, stable=True).astype(jnp.int32)
    sb = cand_boxes[order].astype(jnp.float32)
    ss = cand_scores[order].astype(jnp.float32)
    sl = cand_labels[order].astype(jnp.float32)
    bev = boxes7_to_bev(sb[:, :7])
    iou = rotated_iou_bev(bev, bev)

    iou_p = jnp.zeros((k_pad, k_pad), jnp.float32).at[:k, :k].set(iou)
    rows = jnp.full((16, k_pad), 0.0, jnp.float32)
    rows = rows.at[0:width, :k].set(sb.T)
    rows = rows.at[width, :].set(_NEG_INF)  # pad lanes never selected
    rows = rows.at[width, :k].set(ss)
    rows = rows.at[width + 1, :k].set(sl)
    thresh = jnp.reshape(jnp.asarray(iou_thresh, jnp.float32), (1,))

    with jax.named_scope("fused:decode_nms"):
        out = pl.pallas_call(
            functools.partial(
                _suppress_pack_3d_kernel, max_det=max_det, width=width
            ),
            out_shape=jax.ShapeDtypeStruct((16, md_pad), jnp.float32),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.VMEM((1, k_pad), jnp.float32)],
            interpret=interpret,
        )(iou_p, rows, thresh)
    dets = out[0 : width + 2, :max_det].T
    keep = out[width + 2, :max_det] > 0.0
    return dets, keep
