"""End-to-end 2D detection postprocess: raw head output -> packed detections.

Behavioral parity with the reference's extract_boxes
(clients/postprocess/yolov5_postprocess.py:28-125): confidence gate,
conf = obj * cls, xywh -> xyxy, best-class-only selection, class-offset
batched NMS, max_det cap. Re-designed fixed-shape so the whole thing
jits and vmaps over the batch:

  (B, N, 5+nc) --conf gate + top-k--> (B, max_nms, ...) --NMS--> (B, max_det, 6)

The reference's variable-length outputs and its 10 s NMS watchdog
(yolov5_postprocess.py:51,120-122) are unnecessary here: runtime is
deterministic by construction.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from triton_client_tpu.ops.boxes import xywh2xyxy
from triton_client_tpu.ops.nms import nms_padded


@functools.partial(
    jax.jit, static_argnames=("max_det", "max_nms", "class_agnostic", "multi_label")
)
def extract_boxes(
    prediction: jnp.ndarray,
    conf_thresh: float = 0.3,
    iou_thresh: float = 0.45,
    max_det: int = 300,
    max_nms: int = 1024,
    class_agnostic: bool = False,
    multi_label: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Raw YOLO-style predictions -> packed per-image detections.

    Args:
      prediction: (B, N, 5 + nc) decoded [cx, cy, w, h, obj, cls...].
      conf_thresh: final-confidence gate (obj * cls), reference default
        0.3 (communicator/ros_inference.py:148).
      iou_thresh: NMS IoU threshold, reference default 0.45.
      max_det: max detections per image (reference max_det=300).
      max_nms: candidate cap fed to NMS (reference max_nms=30000; fixed
        top-k here — scores below the top max_nms are dropped, which
        only matters in pathologically dense scenes).
      multi_label: emit one candidate per (box, class) over the
        threshold rather than best-class-only.

    Returns:
      (detections, valid): (B, max_det, 6) [x1, y1, x2, y2, conf, cls]
      rows (zeros when invalid) and (B, max_det) bool mask.
    """
    nc = prediction.shape[-1] - 5

    def one_image(pred: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
        boxes = xywh2xyxy(pred[:, :4])
        obj = pred[:, 4]
        cls_conf = pred[:, 5:] * obj[:, None]  # conf = obj * cls

        if multi_label and nc > 1:
            # One candidate per (box, class) pair over the threshold.
            # Top-k runs on the flat (N*nc,) scores; boxes/classes are
            # derived from the surviving indices (idx // nc, idx % nc)
            # so the (N*nc, 4) box expansion is never materialized.
            flat_conf = cls_conf.reshape(-1)
            gated = jnp.where(flat_conf > conf_thresh, flat_conf, -jnp.inf)
            k = min(max_nms, gated.shape[0])
            top_scores, top_idx = jax.lax.top_k(gated, k)
            cand_boxes = boxes[top_idx // nc]
            cand_classes = top_idx % nc
        else:
            classes = jnp.argmax(cls_conf, axis=-1)
            scores = jnp.max(cls_conf, axis=-1)
            gated = jnp.where(scores > conf_thresh, scores, -jnp.inf)
            k = min(max_nms, gated.shape[0])
            top_scores, top_idx = jax.lax.top_k(gated, k)
            cand_boxes = boxes[top_idx]
            cand_classes = classes[top_idx]

        top_valid = top_scores > -jnp.inf
        return nms_padded(
            cand_boxes,
            # scores carry the gate's -inf in invalid slots; nms_padded
            # re-masks by top_valid, and packed rows are zeroed anyway —
            # but pass the ungated values so output confs are clean.
            jnp.where(top_valid, top_scores, 0.0),
            cand_classes,
            top_valid,
            iou_thresh=iou_thresh,
            max_det=max_det,
            class_agnostic=class_agnostic,
        )

    return jax.vmap(one_image)(prediction)
