"""End-to-end 2D detection postprocess: raw head output -> packed detections.

Behavioral parity with the reference's extract_boxes
(clients/postprocess/yolov5_postprocess.py:28-125): confidence gate,
conf = obj * cls, xywh -> xyxy, best-class-only selection, class-offset
batched NMS, max_det cap. Re-designed fixed-shape so the whole thing
jits and vmaps over the batch:

  (B, N, 5+nc) --conf gate + top-k--> (B, max_nms, ...) --NMS--> (B, max_det, 6)

The reference's variable-length outputs and its 10 s NMS watchdog
(yolov5_postprocess.py:51,120-122) are unnecessary here: runtime is
deterministic by construction.

``fused=True`` collapses the post-top-k tail — xywh->xyxy decode,
class offset, suppression loop and packing — into ONE Pallas launch
(ops/pallas_decode.fused_decode_nms_2d) instead of the nms_padded op
chain. Bitwise-identical rows (pinned by tests/test_fused_parity.py);
pipelines pick the route at trace time from ops/fused.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from triton_client_tpu.ops.boxes import xywh2xyxy
from triton_client_tpu.ops.nms import nms_padded


def _packed_nms(
    boxes, scores, classes, valid, iou_thresh, max_det, class_agnostic,
    box_format: str, fused: bool, interpret: bool,
):
    """nms_padded vs the fused single-launch tail. ``box_format`` tells
    the fused kernel whether decode is still pending ("xywh" — the
    conversion the XLA path already did before top-k happens in-kernel
    instead)."""
    if fused:
        from triton_client_tpu.ops.pallas_decode import fused_decode_nms_2d

        return fused_decode_nms_2d(
            boxes, scores, classes, valid,
            iou_thresh=iou_thresh, max_det=max_det, box_format=box_format,
            class_agnostic=class_agnostic, interpret=interpret,
        )
    if box_format == "xywh":
        boxes = xywh2xyxy(boxes)
    return nms_padded(
        boxes, scores, classes, valid,
        iou_thresh=iou_thresh, max_det=max_det,
        class_agnostic=class_agnostic,
    )


def _gate_topk_nms(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    classes: jnp.ndarray,
    conf_thresh: float,
    iou_thresh: float,
    max_det: int,
    max_nms: int,
    class_agnostic: bool = False,
    box_format: str = "xyxy",
    fused: bool = False,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Shared single-image tail: confidence gate -> top-k prefilter ->
    class-aware NMS -> packed (max_det, 6) rows. Invalid top-k slots
    carry the gate's -inf in ``gated`` but 0.0 in the packed output so
    confs stay clean. Gate + top-k stay XLA on purpose: the sort-based
    top_k beats any in-kernel reformulation and fuses into the head."""
    gated = jnp.where(scores > conf_thresh, scores, -jnp.inf)
    k = min(max_nms, gated.shape[0])
    top_scores, top_idx = jax.lax.top_k(gated, k)
    top_valid = top_scores > -jnp.inf
    return _packed_nms(
        boxes[top_idx],
        jnp.where(top_valid, top_scores, 0.0),
        classes[top_idx],
        top_valid,
        iou_thresh, max_det, class_agnostic, box_format, fused, interpret,
    )


def _multilabel_topk_nms(
    boxes: jnp.ndarray,
    per_class_scores: jnp.ndarray,
    conf_thresh: float,
    iou_thresh: float,
    max_det: int,
    max_nms: int,
    class_agnostic: bool = False,
    box_format: str = "xyxy",
    fused: bool = False,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Single-image multi-label tail: every (box, class) pair over the
    threshold is a candidate. Top-k runs on the flat (N*nc,) scores;
    boxes/classes are derived from surviving indices (idx // nc,
    idx % nc) so the (N*nc, 4) box expansion is never materialized."""
    nc = per_class_scores.shape[-1]
    flat = per_class_scores.reshape(-1)
    gated = jnp.where(flat > conf_thresh, flat, -jnp.inf)
    k = min(max_nms, gated.shape[0])
    top_scores, top_idx = jax.lax.top_k(gated, k)
    top_valid = top_scores > -jnp.inf
    return _packed_nms(
        boxes[top_idx // nc],
        jnp.where(top_valid, top_scores, 0.0),
        top_idx % nc,
        top_valid,
        iou_thresh, max_det, class_agnostic, box_format, fused, interpret,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "max_det", "max_nms", "class_agnostic", "multi_label", "fused",
        "interpret",
    ),
)
def extract_boxes(
    prediction: jnp.ndarray,
    conf_thresh: float = 0.3,
    iou_thresh: float = 0.45,
    max_det: int = 300,
    max_nms: int = 1024,
    class_agnostic: bool = False,
    multi_label: bool = False,
    fused: bool = False,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Raw YOLO-style predictions -> packed per-image detections.

    Args:
      prediction: (B, N, 5 + nc) decoded [cx, cy, w, h, obj, cls...].
      conf_thresh: final-confidence gate (obj * cls), reference default
        0.3 (communicator/ros_inference.py:148).
      iou_thresh: NMS IoU threshold, reference default 0.45.
      max_det: max detections per image (reference max_det=300).
      max_nms: candidate cap fed to NMS (reference max_nms=30000; fixed
        top-k here — scores below the top max_nms are dropped, which
        only matters in pathologically dense scenes).
      multi_label: emit one candidate per (box, class) over the
        threshold rather than best-class-only.

    Returns:
      (detections, valid): (B, max_det, 6) [x1, y1, x2, y2, conf, cls]
      rows (zeros when invalid) and (B, max_det) bool mask.
    """
    nc = prediction.shape[-1] - 5

    def one_image(pred: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
        # fused path defers xywh->xyxy into the kernel (the "decode"
        # half of decode+NMS — conversion commutes with the top-k
        # gather, and *0.5 is exact, so rows stay bitwise-identical)
        boxes = pred[:, :4] if fused else xywh2xyxy(pred[:, :4])
        fmt = "xywh" if fused else "xyxy"
        obj = pred[:, 4]
        cls_conf = pred[:, 5:] * obj[:, None]  # conf = obj * cls

        if multi_label and nc > 1:
            return _multilabel_topk_nms(
                boxes,
                cls_conf,
                conf_thresh,
                iou_thresh,
                max_det,
                max_nms,
                class_agnostic,
                box_format=fmt,
                fused=fused,
                interpret=interpret,
            )
        return _gate_topk_nms(
            boxes,
            jnp.max(cls_conf, axis=-1),
            jnp.argmax(cls_conf, axis=-1),
            conf_thresh,
            iou_thresh,
            max_det,
            max_nms,
            class_agnostic,
            box_format=fmt,
            fused=fused,
            interpret=interpret,
        )

    return jax.vmap(one_image)(prediction)


@functools.partial(
    jax.jit, static_argnames=("max_det", "max_nms", "fused", "interpret")
)
def extract_boxes_yolov4(
    boxes: jnp.ndarray,
    confs: jnp.ndarray,
    conf_thresh: float = 0.4,
    iou_thresh: float = 0.6,
    max_det: int = 300,
    max_nms: int = 1024,
    fused: bool = False,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """YOLOv4 two-output wire contract -> packed per-image detections.

    Behavioral parity with the reference's post_processing
    (tools/utils.py:166-233): best-class selection over pre-multiplied
    confs, confidence gate, per-class greedy NMS (realized here with the
    class-offset trick instead of a python per-class loop). The
    reference emits 7-element rows duplicating the confidence
    (tools/utils.py:219); here rows are the framework-uniform
    [x1, y1, x2, y2, conf, class].

    Args:
      boxes: (B, N, 1, 4) or (B, N, 4) normalized [x1, y1, x2, y2]
        (examples/YOLOv4/config.pbtxt "boxes").
      confs: (B, N, nc) obj*cls scores (config.pbtxt "confs").

    Returns:
      (detections, valid): (B, max_det, 6) rows in the boxes' coordinate
      units and (B, max_det) bool mask.
    """
    if boxes.ndim == 4:
        boxes = boxes[:, :, 0, :]

    def one_image(b: jnp.ndarray, c: jnp.ndarray):
        return _gate_topk_nms(
            b,
            jnp.max(c, axis=-1),
            jnp.argmax(c, axis=-1),
            conf_thresh,
            iou_thresh,
            max_det,
            max_nms,
            fused=fused,
            interpret=interpret,
        )

    return jax.vmap(one_image)(boxes, confs)


@functools.partial(
    jax.jit,
    static_argnames=(
        "max_det", "max_nms", "class_agnostic", "multi_label", "fused",
        "interpret",
    ),
)
def extract_boxes_scored(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    conf_thresh: float = 0.05,
    iou_thresh: float = 0.5,
    max_det: int = 100,
    max_nms: int = 1024,
    class_agnostic: bool = False,
    multi_label: bool = True,
    fused: bool = False,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Decoded-box detectors (RetinaNet/FCOS) -> packed detections.

    The reference's detectron family has NMS server-side and its client
    consumes finished boxes (clients/postprocess/detectron_postprocess.py:
    26-38); this op IS that server side, in-jit. Defaults follow
    detectron2's test-time config (score 0.05, NMS 0.5, 100 dets).

    Args:
      boxes: (B, N, 4) xyxy in input pixels (already decoded).
      scores: (B, N, nc) per-class probabilities.
      multi_label: detectron semantics — every (box, class) over the
        threshold is a candidate (default), vs best-class-only.

    Returns:
      (detections, valid): (B, max_det, 6) [x1, y1, x2, y2, score,
      class] + (B, max_det) mask.
    """
    nc = scores.shape[-1]

    def one_image(b: jnp.ndarray, s: jnp.ndarray):
        if multi_label and nc > 1:
            return _multilabel_topk_nms(
                b,
                s,
                conf_thresh,
                iou_thresh,
                max_det,
                max_nms,
                class_agnostic,
                fused=fused,
                interpret=interpret,
            )
        return _gate_topk_nms(
            b,
            jnp.max(s, axis=-1),
            jnp.argmax(s, axis=-1),
            conf_thresh,
            iou_thresh,
            max_det,
            max_nms,
            class_agnostic,
            fused=fused,
            interpret=interpret,
        )

    return jax.vmap(one_image)(boxes, scores)
