"""Fixed-shape greedy NMS for TPU.

The reference delegates NMS to torchvision.ops.nms (C++/CUDA,
clients/postprocess/yolov5_postprocess.py:108) with data-dependent box
counts. XLA requires static shapes and no data-dependent control flow,
so this is a re-design, not a port:

  * candidate sets are fixed-size: callers pre-gate by confidence and
    top-k to ``max_nms`` boxes, with invalid slots carrying score -inf;
  * suppression runs a fixed ``max_det``-iteration ``lax.fori_loop``:
    each step selects the highest-scoring live box, emits it, and kills
    every live box with IoU > threshold against it;
  * output is always (max_det,) indices plus a validity mask, so the
    whole postprocess stays inside one jit and nothing re-compiles when
    the number of detections changes frame to frame.

Memory is O(max_det * N) via per-iteration IoU rows (no N x N matrix),
so it scales to the reference's 16128-box YOLO heads without blowing
VMEM. Class-aware ("batched") NMS uses the same coordinate-offset trick
as the reference (yolov5_postprocess.py:106-107).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from triton_client_tpu.ops.boxes import box_area


# The (N, N) IoU matrix the fixpoint formulation materializes: 4 bytes
# x N^2 — 64 MB at 4096, past which the sequential loop wins on memory.
_FIXPOINT_MAX_N = 4096


def _nms_mode(n: int, max_det: int) -> str:
    """Route between the NMS formulations (env override:
    TRITON_CLIENT_TPU_NMS=fixpoint|pallas|xla). Decided at trace time —
    shapes are static under jit, so the choice is baked into the
    executable. Auto: the fixpoint matrix form (sequential-step count =
    suppression-chain depth, not max_det) whenever the IoU matrix is
    affordable; the sequential XLA loop otherwise."""
    mode = os.environ.get("TRITON_CLIENT_TPU_NMS", "auto")
    if mode in ("xla", "fixpoint"):
        return mode
    if mode == "pallas":
        from triton_client_tpu.ops.pallas_nms import vmem_fits

        if not vmem_fits(n, max_det):
            import logging

            logging.getLogger(__name__).warning(
                "TRITON_CLIENT_TPU_NMS=pallas but n=%d exceeds the VMEM "
                "budget; falling back to the fixpoint form",
                n,
            )
            return "fixpoint" if n <= _FIXPOINT_MAX_N else "xla"
        return "pallas"
    return "fixpoint" if n <= _FIXPOINT_MAX_N else "xla"


def _iou_row(
    box: jnp.ndarray, box_a: jnp.ndarray, boxes: jnp.ndarray, areas: jnp.ndarray
) -> jnp.ndarray:
    """IoU of one (4,) xyxy box (area ``box_a``) against (N, 4) boxes
    with precomputed (N,) ``areas`` — areas are loop-invariant in the
    suppression loop, so they are computed once outside."""
    lt = jnp.maximum(box[:2], boxes[:, :2])
    rb = jnp.minimum(box[2:], boxes[:, 2:])
    wh = jnp.clip(rb - lt, 0.0, None)
    inter = wh[:, 0] * wh[:, 1]
    return inter / jnp.maximum(box_a + areas - inter, 1e-9)


def nms(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    iou_thresh: float = 0.45,
    max_det: int = 300,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Greedy NMS over (N, 4) xyxy boxes and (N,) scores.

    Returns ``(indices, valid)``: (max_det,) int32 indices into the input
    (arbitrary where invalid) and a (max_det,) bool mask. Slots whose
    input score is -inf (padding) are never selected.

    Formulation routing (fixpoint matrix form / Pallas kernel /
    sequential XLA loop) happens at TRACE time: callers jitted around
    this see the choice baked into their executable until retrace
    (TRITON_CLIENT_TPU_NMS env override). All three produce identical
    kept-index sequences.
    """
    n = boxes.shape[0]
    mode = _nms_mode(n, max_det)
    if mode == "pallas":
        from triton_client_tpu.ops.pallas_nms import nms_pallas

        return nms_pallas(
            boxes,
            scores,
            iou_thresh=iou_thresh,
            max_det=max_det,
            # Off-TPU (forced via env) the kernel runs interpreted.
            interpret=jax.default_backend() != "tpu",
        )
    if mode == "fixpoint":
        return _nms_fixpoint(boxes, scores, iou_thresh, max_det=max_det)
    return _nms_xla(boxes, scores, iou_thresh, max_det=max_det)


@functools.partial(jax.jit, static_argnames=("max_det",))
def _nms_fixpoint(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    iou_thresh: float = 0.45,
    max_det: int = 300,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Exact greedy NMS as a suppression-graph fixpoint — the TPU-shaped
    formulation.

    The textbook greedy loop (argmax -> suppress -> repeat, `_nms_xla`)
    runs max_det tiny sequential steps; on TPU each step is
    latency-bound, so 300 iterations dominate the whole 2D pipeline.
    Greedy NMS is equivalently the unique fixpoint of

        kept_i = valid_i and not any(edge_ji and kept_j)

    over the score-ordered suppression DAG (edge_ji: j outscores i and
    IoU > thresh). Iterating that recurrence finalizes one DAG layer
    per pass, so it converges in max-chain-depth passes (single digits
    in practice) of WIDE (N, N) vector ops instead of max_det narrow
    ones. Equivalence to the sequential loop (incl. first-index tie
    breaks) is pinned by tests against `_nms_xla` and OpenCV's C++ NMS.
    """
    neg_inf = jnp.asarray(-jnp.inf, scores.dtype)
    # Stable descending score order reproduces argmax's first-max-wins
    # tie break; -inf rows (padding) sink to the bottom.
    order = jnp.argsort(-scores, stable=True).astype(jnp.int32)
    sboxes = boxes[order].astype(jnp.float32)
    valid0 = scores[order] > neg_inf

    areas = box_area(sboxes)
    lt = jnp.maximum(sboxes[:, None, :2], sboxes[None, :, :2])
    rb = jnp.minimum(sboxes[:, None, 2:], sboxes[None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0, None)
    inter = wh[..., 0] * wh[..., 1]
    iou = inter / jnp.maximum(areas[:, None] + areas[None, :] - inter, 1e-9)
    return fixpoint_keep_sorted(iou, valid0, order, iou_thresh, max_det)


def fixpoint_keep_sorted(
    siou: jnp.ndarray,
    valid0: jnp.ndarray,
    order: jnp.ndarray,
    iou_thresh,
    max_det: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Fixpoint core shared by axis-aligned and rotated-BEV NMS:
    ``siou`` is the (N, N) IoU matrix of SCORE-SORTED candidates,
    ``valid0`` their live mask, ``order`` the sorted->original index
    map. Returns the sequential loop's ((max_det,) indices into the
    ORIGINAL array, valid) contract."""
    n = siou.shape[0]
    # edge[j, i]: j (strictly higher-ranked) suppresses i when kept
    rank = jnp.arange(n)
    edge = (siou > iou_thresh) & (rank[:, None] < rank[None, :]) & valid0[:, None]

    def cond(state):
        kept, prev, it = state
        return (it < n) & jnp.any(kept != prev)

    def body(state):
        kept, _, it = state
        new = valid0 & ~jnp.any(edge & kept[:, None], axis=0)
        return new, kept, it + 1

    kept, _, _ = jax.lax.while_loop(
        cond, body, (valid0, jnp.zeros_like(valid0), jnp.int32(0))
    )

    # Pack the first max_det kept (already score-ordered) into the
    # sequential loop's (indices, valid) contract.
    kept_rank = jnp.cumsum(kept) - 1
    slot = jnp.where(kept & (kept_rank < max_det), kept_rank, max_det)
    indices = jnp.zeros((max_det + 1,), jnp.int32).at[slot].set(order)[:max_det]
    valid = jnp.arange(max_det) < jnp.sum(kept)
    return indices, valid


@functools.partial(jax.jit, static_argnames=("max_det",))
def _nms_xla(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    iou_thresh: float = 0.45,
    max_det: int = 300,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    n = boxes.shape[0]
    neg_inf = jnp.asarray(-jnp.inf, scores.dtype)
    areas = box_area(boxes)

    def body(i, state):
        live_scores, indices, valid = state
        best = jnp.argmax(live_scores)
        best_score = live_scores[best]
        is_valid = best_score > neg_inf
        indices = indices.at[i].set(best.astype(jnp.int32))
        valid = valid.at[i].set(is_valid)
        ious = _iou_row(boxes[best], areas[best], boxes, areas)
        suppress = (ious > iou_thresh) | (jnp.arange(n) == best)
        live_scores = jnp.where(suppress & is_valid, neg_inf, live_scores)
        return live_scores, indices, valid

    indices = jnp.zeros((max_det,), jnp.int32)
    valid = jnp.zeros((max_det,), bool)
    _, indices, valid = jax.lax.fori_loop(0, max_det, body, (scores, indices, valid))
    return indices, valid


@functools.partial(jax.jit, static_argnames=("max_det", "class_agnostic"))
def batched_nms(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    classes: jnp.ndarray,
    iou_thresh: float = 0.45,
    max_det: int = 300,
    class_agnostic: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Class-aware NMS via the per-class coordinate offset trick."""
    # Same spirit as the reference's fixed max_wh=4096 pixel offset
    # (yolov5_postprocess.py:49), but the stride adapts to the data
    # range and the math runs in f32 regardless of input dtype: a fixed
    # 4096 offset in f32 quantizes normalized [0,1] boxes to ~1/32-image
    # steps by class ~80 (corrupting IoU) and cannot separate classes at
    # all for coordinates above 4096; bf16 offsets lose all sub-32px
    # structure from class 1 on.
    boxes32 = boxes.astype(jnp.float32)
    if class_agnostic:
        offset_boxes = boxes32
    else:
        stride = jnp.max(jnp.abs(boxes32)) * 2.0 + 1.0
        offset_boxes = boxes32 + (classes.astype(jnp.float32) * stride)[:, None]
    return nms(offset_boxes, scores, iou_thresh=iou_thresh, max_det=max_det)


@functools.partial(jax.jit, static_argnames=("max_det", "class_agnostic"))
def nms_padded(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    classes: jnp.ndarray,
    valid: jnp.ndarray,
    iou_thresh: float = 0.45,
    max_det: int = 300,
    class_agnostic: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """NMS over a padded candidate set, returning packed (max_det, 6) detections.

    Inputs are fixed-size candidate arrays (from a top-k prefilter);
    ``valid`` masks live slots. Output rows are [x1, y1, x2, y2, score,
    class] with zeros in invalid slots, plus the (max_det,) validity mask
    — the fixed-shape analogue of the reference's variable-length
    "(n, 6) tensor per image" (yolov5_postprocess.py:34).
    """
    masked_scores = jnp.where(valid, scores, -jnp.inf)
    idx, keep = batched_nms(
        boxes,
        masked_scores,
        classes,
        iou_thresh=iou_thresh,
        max_det=max_det,
        class_agnostic=class_agnostic,
    )
    out = jnp.concatenate(
        [
            boxes[idx],
            scores[idx][:, None],
            classes[idx].astype(boxes.dtype)[:, None],
        ],
        axis=-1,
    )
    out = jnp.where(keep[:, None], out, 0.0)
    return out, keep
