"""Fixed-shape greedy NMS for TPU.

The reference delegates NMS to torchvision.ops.nms (C++/CUDA,
clients/postprocess/yolov5_postprocess.py:108) with data-dependent box
counts. XLA requires static shapes and no data-dependent control flow,
so this is a re-design, not a port:

  * candidate sets are fixed-size: callers pre-gate by confidence and
    top-k to ``max_nms`` boxes, with invalid slots carrying score -inf;
  * suppression runs a fixed ``max_det``-iteration ``lax.fori_loop``:
    each step selects the highest-scoring live box, emits it, and kills
    every live box with IoU > threshold against it;
  * output is always (max_det,) indices plus a validity mask, so the
    whole postprocess stays inside one jit and nothing re-compiles when
    the number of detections changes frame to frame.

Memory is O(max_det * N) via per-iteration IoU rows (no N x N matrix),
so it scales to the reference's 16128-box YOLO heads without blowing
VMEM. Class-aware ("batched") NMS uses the same coordinate-offset trick
as the reference (yolov5_postprocess.py:106-107).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from triton_client_tpu.ops.boxes import box_area


def _use_pallas(n: int, max_det: int) -> bool:
    """Route to the Pallas kernel on TPU (env override:
    TRITON_CLIENT_TPU_NMS=pallas|xla). Decided at trace time — shapes
    are static under jit, so the choice is baked into the executable."""
    mode = os.environ.get("TRITON_CLIENT_TPU_NMS", "auto")
    if mode == "xla":
        return False
    from triton_client_tpu.ops.pallas_nms import vmem_fits

    fits = vmem_fits(n, max_det)
    if mode == "pallas":
        if not fits:
            import logging

            logging.getLogger(__name__).warning(
                "TRITON_CLIENT_TPU_NMS=pallas but n=%d exceeds the VMEM "
                "budget; falling back to the XLA loop",
                n,
            )
        return fits
    return jax.default_backend() == "tpu" and fits


def _iou_row(
    box: jnp.ndarray, box_a: jnp.ndarray, boxes: jnp.ndarray, areas: jnp.ndarray
) -> jnp.ndarray:
    """IoU of one (4,) xyxy box (area ``box_a``) against (N, 4) boxes
    with precomputed (N,) ``areas`` — areas are loop-invariant in the
    suppression loop, so they are computed once outside."""
    lt = jnp.maximum(box[:2], boxes[:, :2])
    rb = jnp.minimum(box[2:], boxes[:, 2:])
    wh = jnp.clip(rb - lt, 0.0, None)
    inter = wh[:, 0] * wh[:, 1]
    return inter / jnp.maximum(box_a + areas - inter, 1e-9)


def nms(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    iou_thresh: float = 0.45,
    max_det: int = 300,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Greedy NMS over (N, 4) xyxy boxes and (N,) scores.

    Returns ``(indices, valid)``: (max_det,) int32 indices into the input
    (arbitrary where invalid) and a (max_det,) bool mask. Slots whose
    input score is -inf (padding) are never selected.

    Backend routing (XLA loop vs Pallas kernel) happens at TRACE time:
    callers jitted around this see the choice baked into their
    executable until retrace (TRITON_CLIENT_TPU_NMS env override).
    """
    n = boxes.shape[0]
    if _use_pallas(n, max_det):
        from triton_client_tpu.ops.pallas_nms import nms_pallas

        return nms_pallas(
            boxes,
            scores,
            iou_thresh=iou_thresh,
            max_det=max_det,
            # Off-TPU (forced via env) the kernel runs interpreted.
            interpret=jax.default_backend() != "tpu",
        )
    return _nms_xla(boxes, scores, iou_thresh, max_det=max_det)


@functools.partial(jax.jit, static_argnames=("max_det",))
def _nms_xla(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    iou_thresh: float = 0.45,
    max_det: int = 300,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    n = boxes.shape[0]
    neg_inf = jnp.asarray(-jnp.inf, scores.dtype)
    areas = box_area(boxes)

    def body(i, state):
        live_scores, indices, valid = state
        best = jnp.argmax(live_scores)
        best_score = live_scores[best]
        is_valid = best_score > neg_inf
        indices = indices.at[i].set(best.astype(jnp.int32))
        valid = valid.at[i].set(is_valid)
        ious = _iou_row(boxes[best], areas[best], boxes, areas)
        suppress = (ious > iou_thresh) | (jnp.arange(n) == best)
        live_scores = jnp.where(suppress & is_valid, neg_inf, live_scores)
        return live_scores, indices, valid

    indices = jnp.zeros((max_det,), jnp.int32)
    valid = jnp.zeros((max_det,), bool)
    _, indices, valid = jax.lax.fori_loop(0, max_det, body, (scores, indices, valid))
    return indices, valid


@functools.partial(jax.jit, static_argnames=("max_det", "class_agnostic"))
def batched_nms(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    classes: jnp.ndarray,
    iou_thresh: float = 0.45,
    max_det: int = 300,
    class_agnostic: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Class-aware NMS via the per-class coordinate offset trick."""
    # Same spirit as the reference's fixed max_wh=4096 pixel offset
    # (yolov5_postprocess.py:49), but the stride adapts to the data
    # range and the math runs in f32 regardless of input dtype: a fixed
    # 4096 offset in f32 quantizes normalized [0,1] boxes to ~1/32-image
    # steps by class ~80 (corrupting IoU) and cannot separate classes at
    # all for coordinates above 4096; bf16 offsets lose all sub-32px
    # structure from class 1 on.
    boxes32 = boxes.astype(jnp.float32)
    if class_agnostic:
        offset_boxes = boxes32
    else:
        stride = jnp.max(jnp.abs(boxes32)) * 2.0 + 1.0
        offset_boxes = boxes32 + (classes.astype(jnp.float32) * stride)[:, None]
    return nms(offset_boxes, scores, iou_thresh=iou_thresh, max_det=max_det)


@functools.partial(jax.jit, static_argnames=("max_det", "class_agnostic"))
def nms_padded(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    classes: jnp.ndarray,
    valid: jnp.ndarray,
    iou_thresh: float = 0.45,
    max_det: int = 300,
    class_agnostic: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """NMS over a padded candidate set, returning packed (max_det, 6) detections.

    Inputs are fixed-size candidate arrays (from a top-k prefilter);
    ``valid`` masks live slots. Output rows are [x1, y1, x2, y2, score,
    class] with zeros in invalid slots, plus the (max_det,) validity mask
    — the fixed-shape analogue of the reference's variable-length
    "(n, 6) tensor per image" (yolov5_postprocess.py:34).
    """
    masked_scores = jnp.where(valid, scores, -jnp.inf)
    idx, keep = batched_nms(
        boxes,
        masked_scores,
        classes,
        iou_thresh=iou_thresh,
        max_det=max_det,
        class_agnostic=class_agnostic,
    )
    out = jnp.concatenate(
        [
            boxes[idx],
            scores[idx][:, None],
            classes[idx].astype(boxes.dtype)[:, None],
        ],
        axis=-1,
    )
    out = jnp.where(keep[:, None], out, 0.0)
    return out, keep
