"""Submanifold sparse 3D convolution, TPU-style (fixed occupancy budget).

The reference's SECOND-IoU runs spconv CUDA sparse convolutions at
0.05 m voxels (examples/second_iou/1/model.py:96-157; built at
docker/server_3d/Dockerfile:41-55). The dense emulation tops out at
0.1 m (the 0.05 m volume is 5.4 GB — BASELINE.md grid sweep), while
occupancy is only ~60k voxels of 90M cells, so this module implements
the sparse stack the TPU way: static shapes everywhere, gathers +
per-offset MXU matmuls instead of hash-table rulebooks.

Representation per level — a fixed-budget voxel set:
  * ``ijk (V, 3)`` int32 cell coords [z, y, x] (padding rows anything),
  * ``feats (V, C)``,
  * ``valid (V,)`` bool.

Neighbor lookup is a dense int32 slot table over the full cell grid
(built once per level per scan): 90M cells x int32 = 360 MB HBM at the
reference 0.05 m grid — affordable transient state on a 16 GB chip,
and each submanifold layer at that level reuses it. Convs then are,
per kernel offset, a row gather + a (V, Cin) x (Cin, Cout) matmul —
exactly the shape the MXU wants.

Operators (MinkowskiEngine semantics, the standard TPU-friendly
variant of spconv):
  * ``subm_conv``  — outputs only at input sites (spconv SubMConv3d);
  * ``sparse_strided_conv`` — stride-2 downsample whose output sites
    are unique(floor(ijk / 2)) (Minkowski strided conv; spconv's
    SparseConv3d generates a slightly larger site set — up to one
    extra cell along odd borders — an accepted, documented departure).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class VoxelSet(NamedTuple):
    """One sparse level: fixed-budget voxel coords + features."""

    ijk: jnp.ndarray    # (V, 3) int32 [z, y, x]
    feats: jnp.ndarray  # (V, C)
    valid: jnp.ndarray  # (V,) bool
    grid: tuple[int, int, int]  # (nz, ny, nx) cell extents


def linear_ids(ijk: jnp.ndarray, valid: jnp.ndarray, grid) -> jnp.ndarray:
    """(V,) linearized (z * ny + y) * nx + x; invalid rows -> n_cells
    (the dump slot)."""
    nz, ny, nx = grid
    inb = (
        valid
        & (ijk[:, 0] >= 0) & (ijk[:, 0] < nz)
        & (ijk[:, 1] >= 0) & (ijk[:, 1] < ny)
        & (ijk[:, 2] >= 0) & (ijk[:, 2] < nx)
    )
    flat = (ijk[:, 0] * ny + ijk[:, 1]) * nx + ijk[:, 2]
    return jnp.where(inb, flat, nz * ny * nx)


def slot_table(vs: VoxelSet) -> jnp.ndarray:
    """Dense (n_cells + 1,) int32 table: cell id -> row in the voxel
    set, -1 where unoccupied. The +1 dump slot absorbs invalid rows."""
    nz, ny, nx = vs.grid
    ids = linear_ids(vs.ijk, vs.valid, vs.grid)
    table = jnp.full((nz * ny * nx + 1,), -1, jnp.int32)
    table = table.at[ids].set(
        jnp.arange(vs.ijk.shape[0], dtype=jnp.int32),
        mode="drop",
    )
    # invalid rows all landed on the dump entry — restore its -1 so an
    # out-of-range neighbor never resolves to a real-looking row
    return table.at[-1].set(-1)


def kernel_offsets(k: int = 3) -> np.ndarray:
    """(k^3, 3) [dz, dy, dx] offsets, center-ordered last dim fastest."""
    r = np.arange(k) - (k - 1) // 2
    return np.stack(np.meshgrid(r, r, r, indexing="ij"), -1).reshape(-1, 3)


def gather_neighbor_slots(
    table: jnp.ndarray,
    vs: VoxelSet,
    offsets: np.ndarray,
    base_scale: int = 1,
) -> jnp.ndarray:
    """(K, V) int32 neighbor rows (-1 = missing). ``base_scale`` maps
    output coords to the finer input lattice (2 for stride-2 convs):
    neighbor of output site o is input cell base_scale*o + offset."""
    nz, ny, nx = vs.grid

    def one(off):
        n_ijk = vs.ijk * base_scale + jnp.asarray(off, jnp.int32)[None]
        ids = linear_ids(n_ijk, vs.valid, (nz, ny, nx))
        return table[ids]

    return jnp.stack([one(off) for off in offsets])


def offset_matmul_sum(
    in_feats: jnp.ndarray,    # (V_in, Cin)
    nbr_slots: jnp.ndarray,   # (K, V_out)
    weights: jnp.ndarray,     # (K, Cin, Cout)
) -> jnp.ndarray:
    """sum_k gather(in_feats, nbr_slots[k]) @ weights[k] — the sparse
    conv compute core. Missing neighbors (-1) read a zero row, exactly
    the zeros a dense conv sees at unoccupied cells."""
    v_in, cin = in_feats.shape
    padded = jnp.concatenate(
        [in_feats, jnp.zeros((1, cin), in_feats.dtype)], axis=0
    )
    slots = jnp.where(nbr_slots < 0, v_in, nbr_slots)  # -1 -> zero row

    def body(acc, kw):
        slot_k, w_k = kw
        return acc + padded[slot_k] @ w_k, None

    out0 = jnp.zeros((nbr_slots.shape[1], weights.shape[2]), in_feats.dtype)
    out, _ = jax.lax.scan(body, out0, (slots, weights))
    return out


def _compact_unique(ids: jnp.ndarray, budget: int, dump: int):
    """Sorted unique-compaction shared by the downsampler and the
    sparse VFE: ``ids`` with ``dump`` marking invalid -> (out_ids
    (budget,) int32 padded with dump, valid (budget,), order, s_ids,
    first, rank) where rank is each sorted row's unique-cell index."""
    order = jnp.argsort(ids)
    s_ids = ids[order]
    first = jnp.concatenate(
        [jnp.ones((1,), bool), s_ids[1:] != s_ids[:-1]]
    ) & (s_ids < dump)
    rank = jnp.cumsum(first) - 1
    out_ids = jnp.full((budget,), dump, jnp.int32)
    out_ids = out_ids.at[jnp.where(first, rank, budget)].set(
        s_ids, mode="drop"
    )
    return out_ids, out_ids < dump, order, s_ids, first, rank


def _unflatten(ids: jnp.ndarray, valid: jnp.ndarray, grid) -> jnp.ndarray:
    """(V,) linear ids -> (V, 3) [z, y, x] (invalid rows zeroed)."""
    nz, ny, nx = grid
    safe = jnp.where(valid, ids, 0)
    z = safe // (ny * nx)
    y = (safe // nx) % ny
    x = safe % nx
    return jnp.stack([z, y, x], axis=1).astype(jnp.int32)


def downsample_sites(vs: VoxelSet, budget: int) -> VoxelSet:
    """Unique(floor(ijk / 2)) output sites of a stride-2 conv, compacted
    into a fixed ``budget``. The coarse extent is ceil(n/2) per axis —
    the dense stride-2 padding-1 output size — so odd-extent levels
    keep their top plane. Features are left empty — the strided conv
    fills them."""
    nz, ny, nx = vs.grid
    cgrid = ((nz + 1) // 2, (ny + 1) // 2, (nx + 1) // 2)
    coarse = vs.ijk // 2
    ids = linear_ids(coarse, vs.valid, cgrid)  # invalid -> dump id
    dump = cgrid[0] * cgrid[1] * cgrid[2]
    out_ids, o_valid, _, _, _, _ = _compact_unique(ids, budget, dump)
    o_ijk = _unflatten(out_ids, o_valid, cgrid)
    return VoxelSet(o_ijk, jnp.zeros((budget, 0)), o_valid, cgrid)


def subm_conv(
    vs: VoxelSet,
    table: jnp.ndarray,
    weights: jnp.ndarray,  # (27, Cin, Cout)
) -> jnp.ndarray:
    """Submanifold 3x3x3 conv: (V, Cout) at the SAME sites. At every
    occupied site the result equals a dense conv's (unoccupied
    neighbors contribute the same zeros), and no new sites appear —
    spconv SubMConv3d semantics."""
    nbr = gather_neighbor_slots(table, vs, kernel_offsets(3))
    out = offset_matmul_sum(vs.feats, nbr, weights)
    return jnp.where(vs.valid[:, None], out, 0.0)


def sparse_strided_conv(
    vs: VoxelSet,
    table: jnp.ndarray,
    weights: jnp.ndarray,  # (k^3, Cin, Cout)
    budget: int,
) -> VoxelSet:
    """Stride-2 sparse conv: output sites are the stride-2 lattice
    cells floor(ijk/2). Kernel size comes from the weights' leading
    dim: 27 -> 3x3x3 padding 1 (out[o] = sum_d w[d] in[2o + d],
    d in [-1, 1]^3 — value-identical to the dense stride-2 conv at
    those sites); 8 -> 2x2x2 padding 0 (d in {0, 1}^3 — each input
    feeds exactly one output, so the 8-offset kernel does a third of
    the 27-offset one's gather work; Minkowski/TorchSparse's standard
    downsample shape, and the perf default here: neighbor lookups are
    the sparse stack's dominant cost on TPU)."""
    k3 = weights.shape[0]
    k = {8: 2, 27: 3}.get(k3)
    if k is None:
        raise ValueError(f"strided conv kernel must be 2^3 or 3^3, got {k3}")
    out_sites = downsample_sites(vs, budget)
    scaled = VoxelSet(out_sites.ijk, out_sites.feats, out_sites.valid, vs.grid)
    # k=3: offsets [-1, 1] around 2o (padding 1); k=2: {0, 1} (pad 0)
    nbr = gather_neighbor_slots(table, scaled, kernel_offsets(k), base_scale=2)
    out = offset_matmul_sum(vs.feats, nbr, weights)
    out = jnp.where(out_sites.valid[:, None], out, 0.0)
    return VoxelSet(out_sites.ijk, out, out_sites.valid, out_sites.grid)


def densify(vs: VoxelSet) -> jnp.ndarray:
    """(nz, ny, nx, C) dense volume from a voxel set — the
    sparse->dense handoff for tail levels whose grids are small enough
    for real MXU convs (a 352x400x10 level is ~0.2 GB; the gathers a
    sparse conv would do there cost more than the dense FLOPs)."""
    nz, ny, nx = vs.grid
    c = vs.feats.shape[-1]
    ids = linear_ids(vs.ijk, vs.valid, vs.grid)
    canvas = jnp.zeros((nz * ny * nx + 1, c), vs.feats.dtype)
    canvas = canvas.at[ids].set(vs.feats, mode="drop")
    return canvas[:-1].reshape(nz, ny, nx, c)


def scatter_bev(vs: VoxelSet) -> jnp.ndarray:
    """Final z-fold: scatter (V, C) into the dense (ny, nx, nz * C)
    BEV the 2D backbone consumes (the dense path's transpose+reshape,
    sparse-side)."""
    nz, ny, nx = vs.grid
    vol = densify(vs)
    return jnp.transpose(vol, (1, 2, 0, 3)).reshape(
        ny, nx, nz * vs.feats.shape[-1]
    )


def points_to_voxelset(
    points: jnp.ndarray,  # (N, F) padded cloud
    count: jnp.ndarray,   # () real rows
    voxel_cfg,
    budget: int,
) -> VoxelSet:
    """Sparse MeanVFE: unique occupied cells (sorted compaction, capped
    at ``budget``) with per-cell mean features — the sparse-side
    replacement for scattering means into the 90M-cell dense volume."""
    from triton_client_tpu.ops.voxelize import assign_cells

    nx, ny, nz = voxel_cfg.grid_size
    ijk_xyz, valid = assign_cells(points, count, voxel_cfg)
    # assign_cells gives [x, y, z] order; flip to [z, y, x]
    ijk = jnp.stack([ijk_xyz[:, 2], ijk_xyz[:, 1], ijk_xyz[:, 0]], axis=1)
    ids = linear_ids(ijk, valid, (nz, ny, nx))
    dump = nz * ny * nx
    n = points.shape[0]
    out_ids, o_valid, order, s_ids, first, rank = _compact_unique(
        ids, budget, dump
    )
    # voxel row per original point (points beyond budget -> dropped)
    slot_sorted = jnp.where(s_ids < dump, rank, budget)
    slot_sorted = jnp.where(slot_sorted < budget, slot_sorted, budget)
    slot = jnp.zeros((n,), jnp.int32).at[order].set(
        slot_sorted.astype(jnp.int32)
    )

    f = points.shape[1]
    acc = jnp.zeros((budget + 1, f + 1), points.dtype)
    w = valid.astype(points.dtype)[:, None]
    acc = acc.at[slot].add(
        jnp.concatenate([points, jnp.ones_like(w)], axis=1) * w
    )
    feats = acc[:budget, :f] / jnp.maximum(acc[:budget, f:], 1.0)
    v_ijk = _unflatten(out_ids, o_valid, (nz, ny, nx))
    return VoxelSet(
        v_ijk, jnp.where(o_valid[:, None], feats, 0.0), o_valid, (nz, ny, nx)
    )
