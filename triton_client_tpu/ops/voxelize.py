"""Static-shape point-cloud voxelization (pillars) for TPU.

The reference delegates voxelization to OpenPCDet's C++/CUDA
DataProcessor (clients/preprocess/preprocess_3d.py:13-25) or det3d's
VoxelGenerator (clients/preprocess/voxelize.py:13-24), producing
*dynamic* voxel counts that force per-frame shape rewrites in the wire
request (communicator/ros_inference3d.py:131-139) — the exact pattern
XLA cannot compile. This is the TPU re-design:

  * fixed budgets: N points in (padded), V voxels out, K points/voxel —
    the (max_voxels, max_points_per_voxel) budget already present in
    the reference configs (data/kitti_dataset.yaml:64-70: 40000 x 32);
  * sort-based grouping: points are sorted by linearized voxel id
    (lax.sort, static shape), segment boundaries found by neighbor
    comparison, per-point slot = rank within segment; everything is a
    vectorized scatter, no data-dependent loops;
  * overflow beyond V voxels or K points/voxel is dropped — identical
    semantics to the reference generators' budget caps.

Returns the 3-tensor contract the 3D clients expect
(clients/detector_3d_client.py:29-41): voxels (V, K, F),
coords (V, 3) [z, y, x], num_points (V,), plus a voxel-valid mask.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class VoxelConfig:
    """Grid geometry (reference data/kitti_dataset.yaml / pointpillar.yaml)."""

    point_cloud_range: tuple[float, float, float, float, float, float] = (
        0.0, -39.68, -3.0, 69.12, 39.68, 1.0,
    )
    voxel_size: tuple[float, float, float] = (0.16, 0.16, 4.0)
    max_voxels: int = 16000
    max_points_per_voxel: int = 32
    # Raw per-point features fed to the VFE: 4 = [x, y, z, intensity]
    # (KITTI), 5 adds the sweep time-lag channel Δt (nuScenes 10-sweep
    # aggregation, reference data/nusc_centerpoint_pp_02voxel_two_pfn_
    # 10sweep.py + clients/preprocess/voxelize.py:38-47 where single
    # sweeps get a zero-padded time column).
    point_features: int = 4

    @property
    def grid_size(self) -> tuple[int, int, int]:
        """(nx, ny, nz) voxel grid dims."""
        r, v = self.point_cloud_range, self.voxel_size
        return (
            int(round((r[3] - r[0]) / v[0])),
            int(round((r[4] - r[1]) / v[1])),
            int(round((r[5] - r[2]) / v[2])),
        )


def assign_cells(
    points: jnp.ndarray, num_points: jnp.ndarray, config: VoxelConfig
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Shared cell assignment: (N, F>=3) padded cloud -> (ijk (N, 3)
    int32 [x, y, z] cell, valid (N,) bool). The single source of the
    grid-boundary semantics for the grouped voxelizer below AND the
    sort-free scatter VFE (models/pointpillars.py augment_points) — the
    two paths' bit-exact agreement depends on sharing this."""
    n = points.shape[0]
    nx, ny, nz = config.grid_size
    r = jnp.asarray(config.point_cloud_range)
    vs = jnp.asarray(config.voxel_size)
    ijk = jnp.floor((points[:, :3] - r[:3]) / vs).astype(jnp.int32)
    valid = jnp.all((ijk >= 0) & (ijk < jnp.asarray([nx, ny, nz])), axis=1)
    valid &= jnp.arange(n) < num_points
    return ijk, valid


def linearize_zyx(
    ijk: jnp.ndarray, valid: jnp.ndarray, config: VoxelConfig
) -> tuple[jnp.ndarray, int]:
    """Flatten [x, y, z] integer cells to the canonical z-major cell id
    ((z*ny + y)*nx + x); invalid rows get the dump id n_cells. Shared by
    the grouped voxelizer and SECOND's scatter mean VFE so the two
    paths' linearization can never diverge. Returns (vid, n_cells)."""
    nx, ny, nz = config.grid_size
    n_cells = nx * ny * nz
    vid = (ijk[:, 2] * ny + ijk[:, 1]) * nx + ijk[:, 0]
    return jnp.where(valid, vid, n_cells), n_cells


@functools.partial(jax.jit, static_argnames=("config",))
def voxelize(
    points: jnp.ndarray, num_points: jnp.ndarray, config: VoxelConfig
) -> dict[str, jnp.ndarray]:
    """points: (N, F) padded point cloud (F >= 3, xyz first);
    num_points: () int count of real rows. Returns dict:
      voxels      (V, K, F)  grouped points, zero-padded
      coords      (V, 3)     [z, y, x] integer voxel coords (-1 invalid)
      num_points_per_voxel (V,) int32
      voxel_valid (V,) bool
    """
    n, f = points.shape
    nx, ny, nz = config.grid_size
    v_cap, k_cap = config.max_voxels, config.max_points_per_voxel

    ijk, in_range = assign_cells(points, num_points, config)

    # Linearized voxel id; invalid points get a sentinel that sorts last.
    vid, sentinel = linearize_zyx(ijk, in_range, config)

    # Sort points by voxel id (stable, static shape).
    order = jnp.argsort(vid)
    vid_s = vid[order]
    pts_s = points[order]
    valid_s = vid_s < sentinel

    # Segment starts -> voxel slots; rank within segment -> point slots.
    first = jnp.concatenate(
        [jnp.ones((1,), bool), vid_s[1:] != vid_s[:-1]]
    ) & valid_s
    voxel_slot = jnp.cumsum(first) - 1  # (N,) index of this point's voxel
    seg_start_idx = jnp.where(first, jnp.arange(n), 0)
    start_of_mine = jax.lax.associative_scan(jnp.maximum, seg_start_idx)
    point_slot = jnp.arange(n) - start_of_mine

    keep = valid_s & (voxel_slot < v_cap) & (point_slot < k_cap)
    vslot = jnp.where(keep, voxel_slot, v_cap)  # overflow -> dropped row
    pslot = jnp.where(keep, point_slot, k_cap)

    voxels = jnp.zeros((v_cap + 1, k_cap + 1, f), points.dtype)
    voxels = voxels.at[vslot, pslot].set(pts_s)[:v_cap, :k_cap]

    counts = jnp.zeros((v_cap + 1,), jnp.int32)
    counts = counts.at[vslot].add(keep.astype(jnp.int32))[:v_cap]

    # Voxel integer coords, scattered from each segment's first point.
    ijk_s = ijk[order]
    coords = jnp.full((v_cap + 1, 3), -1, jnp.int32)
    cslot = jnp.where(first & (voxel_slot < v_cap), voxel_slot, v_cap)
    # [z, y, x] ordering, the reference 3D wire contract
    zyx = jnp.stack([ijk_s[:, 2], ijk_s[:, 1], ijk_s[:, 0]], axis=1)
    coords = coords.at[cslot].set(zyx)[:v_cap]

    return {
        "voxels": voxels,
        "coords": coords,
        "num_points_per_voxel": counts,
        "voxel_valid": counts > 0,
    }


def pad_points(points: np.ndarray, n_budget: int) -> tuple[np.ndarray, int]:
    """Host-side helper: pad/truncate a raw (M, F) cloud to the static
    (n_budget, F) input; returns (padded, real_count)."""
    m = min(points.shape[0], n_budget)
    out = np.zeros((n_budget, points.shape[1]), points.dtype)
    out[:m] = points[:m]
    return out, m
