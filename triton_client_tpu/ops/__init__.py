"""L1 numeric kernels: pure jittable functions on fixed shapes.

TPU-native replacements for the reference's delegated hot loops
(torchvision NMS, OpenPCDet voxelization, struct.unpack byte codecs).
Everything here is shape-static and differentiable-friendly so XLA can
fuse it into the surrounding model graph.
"""

from triton_client_tpu.ops.boxes import (
    xywh2xyxy,
    xyxy2xywh,
    box_iou,
    box_area,
    scale_boxes,
)
from triton_client_tpu.ops.nms import nms, batched_nms, nms_padded
from triton_client_tpu.ops.preprocess import (
    normalize_image,
    letterbox,
    resize_bilinear,
    image_to_nchw,
)
from triton_client_tpu.ops.yolo_decode import decode_yolo_grid

__all__ = [
    "xywh2xyxy",
    "xyxy2xywh",
    "box_iou",
    "box_area",
    "scale_boxes",
    "nms",
    "batched_nms",
    "nms_padded",
    "normalize_image",
    "letterbox",
    "resize_bilinear",
    "image_to_nchw",
    "decode_yolo_grid",
]
