"""Pallas TPU kernel for greedy NMS (VMEM-resident suppression loop).

The reference offloads NMS to torchvision's C++/CUDA op
(clients/postprocess/yolov5_postprocess.py:108). The XLA fallback here
(ops/nms.py) expresses the greedy loop as a ``lax.fori_loop`` over HLO;
this kernel instead runs the whole loop inside ONE Pallas program with
every operand pinned in VMEM:

  * boxes live as a transposed (8, N) struct-of-arrays block so each
    IoU row is pure lane-parallel VPU work (x1/y1/x2/y2/area rows, N
    lanes, padded to a 128 multiple);
  * the max_det-iteration argmax -> gather -> IoU -> mask loop never
    leaves the core: no per-iteration kernel launches, no HBM traffic
    between iterations;
  * outputs are (1, max_det) index/valid rows (lane-tiled), squeezed at
    the wrapper.

The wrapper pads N up to a lane multiple and exposes the same
``(indices, valid)`` contract as ops.nms.nms, so ops.nms can route to
it transparently on TPU (interpret mode keeps CPU tests honest).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_NEG_INF = float("-inf")


def masked_pick(sel: jnp.ndarray, row: jnp.ndarray) -> jnp.ndarray:
    """Extract one lane's value with a masked lane reduction (Mosaic
    has no dynamic_slice on values) — the gather-free idiom every
    suppression-loop kernel here shares. ``sel``: (1, N) one-hot lane
    mask; ``row``: (1, N) values."""
    return jnp.sum(jnp.where(sel, row, 0.0))


def write_lane_col(out_ref, r: int, out_lane: jnp.ndarray, i, value) -> None:
    """Write ``value`` into column ``i`` of sublane row ``r`` of a
    (rows, max_det) output block via an iota==i masked select — the
    lane-parallel form of ``out[r, i] = value`` shared by the packing
    epilogues (ops/pallas_decode) and this kernel's index writes."""
    cur = out_ref[r : r + 1, :]
    out_ref[r : r + 1, :] = jnp.where(out_lane == i, value, cur)


def _nms_kernel(boxes_ref, scores_ref, thresh_ref, idx_ref, valid_ref, live_ref, *, max_det):
    """boxes_ref: (8, N) rows [x1, y1, x2, y2, area, 0, 0, 0];
    scores_ref: (1, N); thresh_ref: (1,) SMEM scalar IoU threshold
    (an input, not a closure constant, so a traced threshold from an
    enclosing jit works); outputs (1, max_det) int32 / bool;
    live_ref: (1, N) f32 scratch holding still-live scores.

    No dynamic indexing anywhere: the selected box's coordinates are
    extracted with masked lane reductions (Mosaic has no dynamic_slice
    on values), and per-iteration outputs accumulate via iota==i masked
    writes — everything stays lane-parallel VPU work.
    """
    n = scores_ref.shape[1]
    iou_thresh = thresh_ref[0]
    live_ref[:] = scores_ref[:]

    x1 = boxes_ref[0:1, :]
    y1 = boxes_ref[1:2, :]
    x2 = boxes_ref[2:3, :]
    y2 = boxes_ref[3:4, :]
    area = boxes_ref[4:5, :]
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, n), 1)
    out_lane = jax.lax.broadcasted_iota(jnp.int32, idx_ref.shape, 1)

    def body(i, _):
        live = live_ref[:]
        best_score = jnp.max(live)
        best = jnp.argmax(live[0, :]).astype(jnp.int32)
        is_valid = best_score > _NEG_INF
        sel = lane == best  # one-hot over lanes

        idx_ref[:] = jnp.where(out_lane == i, best, idx_ref[:])
        # valid is carried as i32 (i1 vector selects don't lower).
        valid_ref[:] = jnp.where(
            out_lane == i, is_valid.astype(jnp.int32), valid_ref[:]
        )

        def pick(row):  # masked lane reduction replaces a gather
            return jnp.sum(jnp.where(sel, row, 0.0))

        bx1, by1, bx2, by2, barea = pick(x1), pick(y1), pick(x2), pick(y2), pick(area)

        iw = jnp.clip(jnp.minimum(x2, bx2) - jnp.maximum(x1, bx1), 0.0, None)
        ih = jnp.clip(jnp.minimum(y2, by2) - jnp.maximum(y1, by1), 0.0, None)
        inter = iw * ih
        iou = inter / jnp.maximum(area + barea - inter, 1e-9)

        suppress = (iou > iou_thresh) | sel
        live_ref[:] = jnp.where(suppress & is_valid, _NEG_INF, live)
        return 0

    idx_ref[:] = jnp.zeros(idx_ref.shape, jnp.int32)
    valid_ref[:] = jnp.zeros(valid_ref.shape, jnp.int32)
    jax.lax.fori_loop(0, max_det, body, 0)


@functools.partial(jax.jit, static_argnames=("max_det", "interpret"))
def nms_pallas(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    iou_thresh=0.45,
    max_det: int = 300,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Greedy NMS over (N, 4) xyxy boxes + (N,) scores on the TPU core.

    Same contract as ops.nms.nms: (max_det,) int32 indices into the
    input + (max_det,) bool validity; -inf scores are padding and never
    selected.
    """
    n = boxes.shape[0]
    n_pad = max(_LANES, ((n + _LANES - 1) // _LANES) * _LANES)
    md_pad = max(_LANES, ((max_det + _LANES - 1) // _LANES) * _LANES)

    boxes32 = boxes.astype(jnp.float32)
    area = (boxes32[:, 2] - boxes32[:, 0]) * (boxes32[:, 3] - boxes32[:, 1])
    # (8, N) struct-of-arrays block (8 sublanes = f32 tile height).
    packed = jnp.zeros((8, n_pad), jnp.float32)
    packed = packed.at[0:4, :n].set(boxes32.T)
    packed = packed.at[4, :n].set(area)
    padded_scores = jnp.full((1, n_pad), _NEG_INF, jnp.float32)
    padded_scores = padded_scores.at[0, :n].set(scores.astype(jnp.float32))

    thresh = jnp.reshape(jnp.asarray(iou_thresh, jnp.float32), (1,))
    idx, valid = pl.pallas_call(
        functools.partial(_nms_kernel, max_det=max_det),
        out_shape=(
            jax.ShapeDtypeStruct((1, md_pad), jnp.int32),
            jax.ShapeDtypeStruct((1, md_pad), jnp.int32),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[pltpu.VMEM((1, n_pad), jnp.float32)],
        interpret=interpret,
    )(packed, padded_scores, thresh)
    return idx[0, :max_det], valid[0, :max_det].astype(jnp.bool_)


def vmem_fits(n: int, max_det: int = 300, budget_bytes: int = 12 << 20) -> bool:
    """Whether the kernel's VMEM working set fits comfortably."""
    n_pad = max(_LANES, ((n + _LANES - 1) // _LANES) * _LANES)
    md_pad = max(_LANES, ((max_det + _LANES - 1) // _LANES) * _LANES)
    working = 8 * n_pad * 4 + 2 * n_pad * 4 + md_pad * 8
    return working < budget_bytes
