"""tpu-perception-serving: a TPU-native perception inference framework.

A brand-new JAX/XLA/Pallas re-design of the capabilities of
niqbal996/triton_client (a ROS->gRPC client for remote Triton GPU
inference). Instead of shipping frames over the network to a GPU server,
models are jit-compiled and dispatched in-process on a TPU mesh; the
gRPC/KServe-v2 protocol is retained only as an optional facade for
drop-in ROS interop.

Layer map (mirrors reference SURVEY.md section 1, re-designed TPU-first):

  L5  cli/          entry points (detect2d, detect3d, replay, evaluate)
  L4  drivers/      inference drivers (file/bag/ros sources, pipelined)
  L3  channel/      transport seam (TPUChannel in-process, GRPCChannel)
  L2  models/ + per-model pipelines (preprocess/forward/postprocess)
  L1  ops/          numeric kernels (NMS, IoU, voxelize, decode) in XLA/Pallas

plus parallel/ (mesh + sharding), runtime/ (serving: registry, queue,
micro-batcher), eval/ (mAP evaluator), utils/.
"""

__version__ = "0.1.0"
