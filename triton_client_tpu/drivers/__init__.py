"""Inference drivers (L4): the event loops that pump sources through
channels/pipelines into sinks."""

from triton_client_tpu.drivers.driver import (
    DriverStats,
    InferenceDriver,
    channel_infer,
    detect2d_infer,
    detect3d_infer,
)

__all__ = [
    "DriverStats",
    "InferenceDriver",
    "channel_infer",
    "detect2d_infer",
    "detect3d_infer",
]
