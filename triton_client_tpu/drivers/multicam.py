"""Multi-camera lockstep driver: N streams -> one DP-sharded batch.

The reference's nearest analogue is "ensemble multi-camera" serving —
declared server-side config only (README.md:119 TODO; instance_group
replication). Here it is first-class: one frame is pulled from each
camera source per tick, stacked into a (C, H, W, 3) batch whose leading
axis TPUChannel shards over the mesh's ``data`` axis, inferred in ONE
device dispatch, and the packed results are demuxed back to per-camera
sinks. With C cameras on a data=C mesh each chip serves one camera, and
the batch rides ICI instead of C separate host round-trips.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Mapping, Sequence

import numpy as np

from triton_client_tpu.drivers.driver import DriverStats, latency_stats


class MultiCameraDriver:
    """Lockstep pull loop over N frame sources.

    ``infer`` receives {"images": (C, H, W, 3)} -> outputs whose leading
    axis is the camera axis (the repository infer_fn contract). A sink
    receives (camera_index, frame, per_camera_result) — the index is the
    ORIGINAL camera slot, stable even after other cameras drop out.

    ``on_stream_end`` decides what happens when a camera source
    exhausts mid-run:
      * ``"stop"`` (default) — the whole run ends at the first
        exhausted camera. Ragged tails would silently skew a camera's
        latency statistics, and a session-grouped tracker (detections
        stacked on the camera axis feed ONE server-side session group)
        rejects a group-size change mid-stream, so the safe default is
        to end the group together.
      * ``"drop"`` — the exhausted camera leaves the lockstep group and
        the survivors keep ticking until every source is dry. The batch
        (and any downstream session group) SHRINKS at that tick; only
        use this when the consumer tolerates a camera-axis resize."""

    def __init__(
        self,
        infer: Callable[[Mapping[str, np.ndarray]], Mapping[str, Any]],
        sources: Sequence[Any],
        sink: Callable[[int, Any, Mapping[str, Any]], None] | None = None,
        warmup: int = 1,
        on_stream_end: str = "stop",
    ) -> None:
        if not sources:
            raise ValueError("need at least one camera source")
        if on_stream_end not in ("stop", "drop"):
            raise ValueError(
                f"on_stream_end must be 'stop' or 'drop', "
                f"not {on_stream_end!r}"
            )
        self.infer = infer
        self.sources = list(sources)
        self.sink = sink
        self.warmup = warmup
        self.on_stream_end = on_stream_end

    def run(self, max_ticks: int = 0) -> DriverStats:
        iters = [iter(s) for s in self.sources]
        live = list(range(len(self.sources)))
        latencies: list[float] = []
        ticks = 0
        frames_served = 0
        t_start = None
        while not max_ticks or ticks < max_ticks:
            frames = []  # (original camera index, frame)
            still = []
            stopped = False
            for ci in live:
                frame = next(iters[ci], None)
                if frame is None:
                    if self.on_stream_end == "stop":
                        stopped = True
                        break
                    continue  # drop: camera leaves the lockstep group
                still.append(ci)
                frames.append((ci, frame))
            if stopped or not frames:
                break
            live = still
            batch = np.stack([np.asarray(f.data) for _, f in frames])
            if ticks == 0:
                for _ in range(self.warmup):
                    self.infer({"images": batch})
                t_start = time.perf_counter()
            t0 = time.perf_counter()
            result = self.infer({"images": batch})
            latencies.append(time.perf_counter() - t0)
            if self.sink is not None:
                for bi, (ci, frame) in enumerate(frames):
                    per_cam = {
                        k: np.asarray(v)[bi]
                        for k, v in result.items()
                        if np.ndim(v) > 0 and np.shape(v)[0] == len(frames)
                    }
                    self.sink(ci, frame, per_cam)
            ticks += 1
            frames_served += len(frames)

        wall = (time.perf_counter() - t_start) if t_start is not None else 0.0
        return latency_stats(
            latencies, frames=frames_served, wall_s=wall, ticks=ticks
        )
