"""Multi-camera lockstep driver: N streams -> one DP-sharded batch.

The reference's nearest analogue is "ensemble multi-camera" serving —
declared server-side config only (README.md:119 TODO; instance_group
replication). Here it is first-class: one frame is pulled from each
camera source per tick, stacked into a (C, H, W, 3) batch whose leading
axis TPUChannel shards over the mesh's ``data`` axis, inferred in ONE
device dispatch, and the packed results are demuxed back to per-camera
sinks. With C cameras on a data=C mesh each chip serves one camera, and
the batch rides ICI instead of C separate host round-trips.

Cross-camera suppression (ISSUE 19): rigidly mounted rigs overlap, so
an object fully visible in camera A's processed view need not be
re-detected in camera B's overlap strip the same tick. ``OverlapRegion``
declares those strips; when every tracked object in a view falls inside
overlap regions whose peer camera IS in this tick's batch, the view is
skipped entirely — zero detector cost for that camera this tick.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Mapping, Sequence

import numpy as np

from triton_client_tpu.drivers.driver import DriverStats, latency_stats


@dataclasses.dataclass(frozen=True)
class OverlapRegion:
    """One directed overlap declaration: the axis-aligned strip
    ``(x0, y0, x1, y1)`` in ``camera``'s pixel coordinates that is also
    covered by ``peer``'s field of view. A view may be suppressed for a
    tick only against peers actually processed that tick — suppression
    never chains through another suppressed view."""

    camera: int
    peer: int
    region: tuple[float, float, float, float]

    def __post_init__(self) -> None:
        if self.camera == self.peer:
            raise ValueError("a camera cannot overlap itself")
        x0, y0, x1, y1 = self.region
        if not (x1 > x0 and y1 > y0):
            raise ValueError(f"degenerate overlap region {self.region}")


class MultiCameraDriver:
    """Lockstep pull loop over N frame sources.

    ``infer`` receives {"images": (C, H, W, 3)} -> outputs whose leading
    axis is the camera axis (the repository infer_fn contract). A sink
    receives (camera_index, frame, per_camera_result) — the index is the
    ORIGINAL camera slot, stable even after other cameras drop out.

    ``on_stream_end`` decides what happens when a camera source
    exhausts mid-run:
      * ``"stop"`` (default) — the whole run ends at the first
        exhausted camera. Ragged tails would silently skew a camera's
        latency statistics, and a session-grouped tracker (detections
        stacked on the camera axis feed ONE server-side session group)
        rejects a group-size change mid-stream, so the safe default is
        to end the group together.
      * ``"drop"`` — the exhausted camera leaves the lockstep group and
        the survivors keep ticking until every source is dry. The batch
        (and any downstream session group) SHRINKS at that tick; only
        use this when the consumer tolerates a camera-axis resize.

    ``suppression`` (ISSUE 19): a sequence of OverlapRegion. Each tick,
    views are considered in camera-index order; a view is dropped from
    the batch when it has at least one currently tracked object and
    EVERY tracked center (read from the previous tick's per-camera
    ``tracks``/``tracks_valid`` outputs) lies inside an overlap region
    whose peer is in this tick's batch. Empty views (nothing tracked)
    are never suppressed — a new object could be entering. A view is
    force-processed after ``max_consecutive_suppress`` skips so stale
    track positions cannot pin it suppressed forever. CAVEAT: like
    ``"drop"``, suppression shrinks the batch (shape change -> retrace)
    and is incompatible with a single server-side session GROUP, which
    rejects a camera-axis resize; use per-camera sessions or a
    stateless consumer. ``temporal`` optionally names a
    runtime.temporal.TemporalReusePlane whose suppression counter
    (``tpu_serving_suppressed_views_total``) each skip increments."""

    def __init__(
        self,
        infer: Callable[[Mapping[str, np.ndarray]], Mapping[str, Any]],
        sources: Sequence[Any],
        sink: Callable[[int, Any, Mapping[str, Any]], None] | None = None,
        warmup: int = 1,
        on_stream_end: str = "stop",
        suppression: Sequence[OverlapRegion] | None = None,
        max_consecutive_suppress: int = 2,
        temporal=None,
    ) -> None:
        if not sources:
            raise ValueError("need at least one camera source")
        if on_stream_end not in ("stop", "drop"):
            raise ValueError(
                f"on_stream_end must be 'stop' or 'drop', "
                f"not {on_stream_end!r}"
            )
        self.infer = infer
        self.sources = list(sources)
        self.sink = sink
        self.warmup = warmup
        self.on_stream_end = on_stream_end
        self.temporal = temporal
        self.max_consecutive_suppress = max(1, int(max_consecutive_suppress))
        self._overlaps: dict[int, list[OverlapRegion]] = {}
        for ov in suppression or ():
            if not (0 <= ov.camera < len(sources)) or not (
                0 <= ov.peer < len(sources)
            ):
                raise ValueError(
                    f"overlap {ov} references a camera outside "
                    f"0..{len(sources) - 1}"
                )
            self._overlaps.setdefault(ov.camera, []).append(ov)
        self.suppressed_views = 0

    # -- suppression ---------------------------------------------------------

    def _suppress(
        self,
        frames: list,
        last_tracks: dict[int, tuple[np.ndarray, np.ndarray]],
        streak: dict[int, int],
    ) -> tuple[list, list]:
        """Partition the tick's (ci, frame) list into (kept, skipped).

        Views are scanned in ascending camera order; a view's overlap
        peers count only if they are already KEPT this tick, so two
        mutually overlapping views can never suppress each other in the
        same tick (the lower index is processed and covers the other)."""
        kept: list = []
        kept_ids: set[int] = set()
        skipped: list = []
        # peers later in index order can still cover an earlier view, as
        # long as they are present this tick and not themselves
        # suppressed — precompute presence, then resolve in order with
        # the rule that a peer must not be suppressed.
        present = {ci for ci, _ in frames}
        for ci, frame in frames:
            regs = self._overlaps.get(ci, ())
            tr = last_tracks.get(ci)
            if (
                regs
                and tr is not None
                and streak.get(ci, 0) < self.max_consecutive_suppress
                and self._all_covered(tr, regs, present, kept_ids, ci)
            ):
                skipped.append((ci, frame))
                streak[ci] = streak.get(ci, 0) + 1
                continue
            kept.append((ci, frame))
            kept_ids.add(ci)
            streak[ci] = 0
        return kept, skipped

    def _all_covered(
        self,
        tr: tuple[np.ndarray, np.ndarray],
        regs: Sequence[OverlapRegion],
        present: set[int],
        kept_ids: set[int],
        ci: int,
    ) -> bool:
        tracks, valid = tr
        centers = np.asarray(tracks, np.float32).reshape(
            len(tracks), -1
        )[np.asarray(valid, bool)][:, :2]
        if centers.size == 0:
            return False  # nothing tracked: a new object could enter
        # usable peers: present this tick AND either already kept (lower
        # index, decided) or not themselves suppressible (no overlap
        # declarations) — never another still-undecided suppressible view
        usable = {
            r.peer
            for r in regs
            if r.peer in present
            and (r.peer in kept_ids or (r.peer > ci and r.peer not in self._overlaps))
        }
        if not usable:
            return False
        covered = np.zeros(len(centers), bool)
        for r in regs:
            if r.peer not in usable:
                continue
            x0, y0, x1, y1 = r.region
            covered |= (
                (centers[:, 0] >= x0)
                & (centers[:, 0] < x1)
                & (centers[:, 1] >= y0)
                & (centers[:, 1] < y1)
            )
        return bool(covered.all())

    # -- run loop ------------------------------------------------------------

    def run(self, max_ticks: int = 0) -> DriverStats:
        iters = [iter(s) for s in self.sources]
        live = list(range(len(self.sources)))
        latencies: list[float] = []
        ticks = 0
        frames_served = 0
        t_start = None
        last_tracks: dict[int, tuple[np.ndarray, np.ndarray]] = {}
        streak: dict[int, int] = {}
        while not max_ticks or ticks < max_ticks:
            frames = []  # (original camera index, frame)
            still = []
            stopped = False
            for ci in live:
                frame = next(iters[ci], None)
                if frame is None:
                    if self.on_stream_end == "stop":
                        stopped = True
                        break
                    continue  # drop: camera leaves the lockstep group
                still.append(ci)
                frames.append((ci, frame))
            if stopped or not frames:
                break
            live = still
            if self._overlaps:
                frames, skipped = self._suppress(frames, last_tracks, streak)
                if skipped:
                    self.suppressed_views += len(skipped)
                    if self.temporal is not None:
                        try:
                            self.temporal.record_suppressed(len(skipped))
                        except Exception:
                            pass
                if not frames:
                    # every view suppressed (mutual-coverage pathology);
                    # the streak cap breaks the cycle next tick
                    ticks += 1
                    continue
            batch = np.stack([np.asarray(f.data) for _, f in frames])
            if ticks == 0:
                for _ in range(self.warmup):
                    self.infer({"images": batch})
                t_start = time.perf_counter()
            t0 = time.perf_counter()
            result = self.infer({"images": batch})
            latencies.append(time.perf_counter() - t0)
            for bi, (ci, frame) in enumerate(frames):
                per_cam = {
                    k: np.asarray(v)[bi]
                    for k, v in result.items()
                    if np.ndim(v) > 0 and np.shape(v)[0] == len(frames)
                }
                if "tracks" in per_cam and "tracks_valid" in per_cam:
                    last_tracks[ci] = (
                        per_cam["tracks"],
                        per_cam["tracks_valid"],
                    )
                if self.sink is not None:
                    self.sink(ci, frame, per_cam)
            ticks += 1
            frames_served += len(frames)

        wall = (time.perf_counter() - t_start) if t_start is not None else 0.0
        stats = latency_stats(
            latencies, frames=frames_served, wall_s=wall, ticks=ticks
        )
        stats.suppressed = self.suppressed_views
        return stats
