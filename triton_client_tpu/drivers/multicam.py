"""Multi-camera lockstep driver: N streams -> one DP-sharded batch.

The reference's nearest analogue is "ensemble multi-camera" serving —
declared server-side config only (README.md:119 TODO; instance_group
replication). Here it is first-class: one frame is pulled from each
camera source per tick, stacked into a (C, H, W, 3) batch whose leading
axis TPUChannel shards over the mesh's ``data`` axis, inferred in ONE
device dispatch, and the packed results are demuxed back to per-camera
sinks. With C cameras on a data=C mesh each chip serves one camera, and
the batch rides ICI instead of C separate host round-trips.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Mapping, Sequence

import numpy as np

from triton_client_tpu.drivers.driver import DriverStats, latency_stats


class MultiCameraDriver:
    """Lockstep pull loop over N frame sources.

    ``infer`` receives {"images": (C, H, W, 3)} -> outputs whose leading
    axis is the camera axis (the repository infer_fn contract). A sink
    receives (camera_index, frame, per_camera_result). Streams advance
    in lockstep; the run ends when ANY camera is exhausted (ragged tails
    would silently skew a camera's latency statistics)."""

    def __init__(
        self,
        infer: Callable[[Mapping[str, np.ndarray]], Mapping[str, Any]],
        sources: Sequence[Any],
        sink: Callable[[int, Any, Mapping[str, Any]], None] | None = None,
        warmup: int = 1,
    ) -> None:
        if not sources:
            raise ValueError("need at least one camera source")
        self.infer = infer
        self.sources = list(sources)
        self.sink = sink
        self.warmup = warmup

    def run(self, max_ticks: int = 0) -> DriverStats:
        iters = [iter(s) for s in self.sources]
        latencies: list[float] = []
        ticks = 0
        t_start = None
        while not max_ticks or ticks < max_ticks:
            frames = []
            for it in iters:
                frame = next(it, None)
                if frame is None:
                    break
                frames.append(frame)
            if len(frames) < len(iters):
                break
            batch = np.stack([np.asarray(f.data) for f in frames])
            if ticks == 0:
                for _ in range(self.warmup):
                    self.infer({"images": batch})
                t_start = time.perf_counter()
            t0 = time.perf_counter()
            result = self.infer({"images": batch})
            latencies.append(time.perf_counter() - t0)
            if self.sink is not None:
                for ci, frame in enumerate(frames):
                    per_cam = {
                        k: np.asarray(v)[ci]
                        for k, v in result.items()
                        if np.ndim(v) > 0 and np.shape(v)[0] == len(frames)
                    }
                    self.sink(ci, frame, per_cam)
            ticks += 1

        wall = (time.perf_counter() - t_start) if t_start is not None else 0.0
        return latency_stats(
            latencies, frames=ticks * len(self.sources), wall_s=wall, ticks=ticks
        )
