"""The inference event loop (L4).

The reference's drivers are ROS-callback-shaped: preprocessing of frame
N+1 can't start until frame N's blocking RPC returns
(communicator/ros_inference.py:117-175, SURVEY.md section 2.10). Here the
loop is pull-driven with a bounded prefetch queue: a producer thread
reads + decodes upcoming frames while the accelerator runs the current
one, so host IO and device compute overlap — the driver-level half of
SURVEY.md hard part (d).

The driver is model-agnostic: it pumps ``Frame``s through an
``infer(data) -> {name: array}`` callable (adapters below wrap the 2D/3D
pipelines and the channel seam), optionally scores against ground truth,
and reports throughput + latency percentiles.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Any, Callable, Mapping

import numpy as np

from triton_client_tpu.io.sinks import Sink
from triton_client_tpu.io.sources import Frame, FrameSource

InferFn = Callable[[np.ndarray], Mapping[str, Any]]
# The --async variant: the callable dispatches and returns a future
# whose result() yields the Mapping (channel/base.py InferFuture).
AsyncInferFn = Callable[[np.ndarray], Any]

_SENTINEL = object()


@dataclasses.dataclass
class DriverStats:
    frames: int = 0
    wall_s: float = 0.0
    fps: float = 0.0
    p50_ms: float = 0.0
    p99_ms: float = 0.0
    mean_ms: float = 0.0
    # device dispatches; == frames single-stream, frames/cameras for the
    # multi-camera lockstep driver (latency percentiles are per-tick)
    ticks: int = 0
    # camera views skipped by cross-camera suppression (ISSUE 19): the
    # multi-camera driver omits a view from the tick's batch when every
    # tracked object in it projects into overlap regions already covered
    # by a processed peer this tick
    suppressed: int = 0

    def to_dict(self) -> dict[str, float]:
        return dataclasses.asdict(self)


def latency_stats(latencies_s: list, frames: int, wall_s: float, ticks: int) -> "DriverStats":
    """Shared percentile/fps math for the single- and multi-stream drivers."""
    lat_ms = np.asarray(latencies_s) * 1e3
    n = len(latencies_s)
    return DriverStats(
        frames=frames,
        wall_s=wall_s,
        fps=frames / wall_s if wall_s > 0 else 0.0,
        p50_ms=float(np.percentile(lat_ms, 50)) if n else 0.0,
        p99_ms=float(np.percentile(lat_ms, 99)) if n else 0.0,
        mean_ms=float(lat_ms.mean()) if n else 0.0,
        ticks=ticks,
    )


class InferenceDriver:
    """Prefetching pull loop: source -> infer -> sink (+ eval)."""

    def __init__(
        self,
        infer: InferFn,
        source: FrameSource,
        sink: Sink | None = None,
        prefetch: int = 4,
        warmup: int = 1,
        evaluator=None,
        gt_lookup: Callable[[Frame], np.ndarray | None] | None = None,
        profiler=None,
        batch_size: int = 1,
        inflight: int = 1,
    ) -> None:
        """``evaluator``: DetectionEvaluator scored via ``gt_lookup``,
        which maps a frame to (n_gt, 5) [x1, y1, x2, y2, cls] or None.
        ``profiler``: optional StageProfiler; records source/infer/sink
        stage latencies (the per-stage view the reference only had as
        commented-out prints, ros_inference3d.py:209-210).
        ``batch_size`` > 1 stacks that many frames per device dispatch
        (the reference's -b flag made real — it only ever sized the gRPC
        message cap, grpc_channel.py:26-29); frames must share a shape
        (resize upstream), and results demux back per frame.
        ``inflight`` > 1 selects the async pump (the reference's unused
        --async flag made real): ``infer`` must then return a future
        (``.result() -> Mapping``) and up to ``inflight`` dispatches
        overlap, retired in issue order. Mutually exclusive with
        ``batch_size`` > 1."""
        self.infer = infer
        self.source = source
        self.sink = sink
        self.prefetch = prefetch
        self.warmup = warmup
        self.evaluator = evaluator
        self.gt_lookup = gt_lookup
        self.profiler = profiler
        self.batch_size = max(1, int(batch_size))
        self.inflight = max(1, int(inflight))
        if self.batch_size > 1 and self.inflight > 1:
            raise ValueError(
                "batch_size and inflight both pipeline the device; "
                "pick one (batched sync dispatch or async futures)"
            )

    def run(self, max_frames: int = 0) -> DriverStats:
        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        error: list[BaseException] = []

        def produce() -> None:
            try:
                it = iter(self.source)
                i = 0
                while not max_frames or i < max_frames:
                    t0 = time.perf_counter()
                    frame = next(it, _SENTINEL)
                    if frame is _SENTINEL:
                        break
                    if self.profiler is not None:
                        # decode/read time, overlapped with infer by the
                        # prefetch queue — visible here, not in e2e p50
                        self.profiler.record("source", time.perf_counter() - t0)
                    q.put(frame)
                    i += 1
            except BaseException as e:  # propagate into the consumer
                error.append(e)
            finally:
                q.put(_SENTINEL)

        producer = threading.Thread(target=produce, daemon=True)
        producer.start()

        latencies: list[float] = []
        n = 0
        first = q.get()
        if first is _SENTINEL:
            if error:
                raise error[0]
            return DriverStats()
        # Warmup compiles outside the timed window (first jit trace is
        # tens of seconds on TPU; the reference has no analogue because
        # its compile cost sits server-side). Batched mode warms the
        # BATCHED shape — warming single-frame would leave the real
        # trace cold.
        frame = first
        b = self.batch_size
        for _ in range(self.warmup):
            if self.inflight > 1:
                self.infer(frame.data).result()
            elif b > 1:
                self.infer(np.stack([np.asarray(frame.data)] * b))
            else:
                self.infer(frame.data)

        if self.inflight > 1:
            return self._run_async(q, first, error)

        ticks = 0
        t_start = time.perf_counter()
        try:
            while frame is not _SENTINEL:
                batch = [frame]
                while len(batch) < b:
                    nxt = q.get()
                    if nxt is _SENTINEL:
                        frame = _SENTINEL  # outer loop ends after this batch
                        break
                    batch.append(nxt)

                t0 = time.perf_counter()
                if b > 1:
                    datas = [np.asarray(f.data) for f in batch]
                    if len({d.shape for d in datas}) > 1:
                        raise ValueError(
                            "batched dispatch needs uniform frame shapes; "
                            f"got {sorted({d.shape for d in datas})} — "
                            "resize upstream or use batch_size=1"
                        )
                    # pad a trailing partial batch to the warmed shape:
                    # a (b-1, ...) dispatch would retrace/rejit inside
                    # the timed loop (tens of seconds on TPU)
                    datas += [datas[-1]] * (b - len(batch))
                    result = self.infer(np.stack(datas))
                else:
                    result = self.infer(batch[0].data)
                dt = time.perf_counter() - t0
                latencies.append(dt)
                ticks += 1
                if self.profiler is not None:
                    self.profiler.record("infer", dt)
                n += len(batch)

                if b > 1:
                    # one host conversion per batch, not per frame
                    arrs = {k: np.asarray(v) for k, v in result.items()}
                for i, f in enumerate(batch):
                    if b > 1:
                        per = {
                            k: v[i]
                            if np.ndim(v) > 0 and np.shape(v)[0] == b
                            else v
                            for k, v in arrs.items()
                        }
                    else:
                        per = result
                    self._deliver(f, per)
                if frame is not _SENTINEL:
                    frame = q.get()
            wall = time.perf_counter() - t_start
        finally:
            # Close even on infer errors / KeyboardInterrupt: buffered
            # sinks (the output bag writer) must flush their index or
            # every frame processed so far is lost.
            if self.sink is not None:
                self.sink.close()
        if error:
            raise error[0]

        return latency_stats(latencies, frames=n, wall_s=wall, ticks=ticks)

    def _run_async(self, q: queue.Queue, first, error: list) -> DriverStats:
        """Async pump: keep up to ``inflight`` dispatches outstanding,
        retire in issue order. ``infer`` returns futures here. Per-frame
        latency is issue->retire (true end-to-end including pipeline
        wait), so p50 under load reads higher than the sync path's even
        as fps improves — that is the honest tradeoff of pipelining."""
        import collections

        latencies: list[float] = []
        pending: collections.deque = collections.deque()
        n = 0
        frame = first
        t_start = time.perf_counter()

        def retire() -> None:
            nonlocal n
            f, t0, fut = pending.popleft()
            result = fut.result()
            dt = time.perf_counter() - t0
            latencies.append(dt)
            if self.profiler is not None:
                self.profiler.record("infer", dt)
            n += 1
            self._deliver(f, result)

        try:
            while True:
                # dispatch the frame in hand, retire once the window is
                # full, and only then block on the source for the next
                # frame — a slow source therefore delays a ready result
                # by at most one source period, not inflight periods
                if frame is not _SENTINEL:
                    t0 = time.perf_counter()
                    pending.append((frame, t0, self.infer(frame.data)))
                if pending and (
                    frame is _SENTINEL or len(pending) >= self.inflight
                ):
                    retire()
                if frame is _SENTINEL:
                    if not pending:
                        break
                else:
                    frame = q.get()
            wall = time.perf_counter() - t_start
        finally:
            if self.sink is not None:
                self.sink.close()
        if error:
            raise error[0]
        return latency_stats(latencies, frames=n, wall_s=wall, ticks=n)

    def _deliver(self, frame, per: Mapping[str, Any]) -> None:
        """Per-frame tail shared by the sync and async loops: sink write
        + optional GT scoring."""
        if self.sink is not None:
            t1 = time.perf_counter()
            self.sink.write(frame, per)
            if self.profiler is not None:
                self.profiler.record("sink", time.perf_counter() - t1)
        if self.evaluator is not None and self.gt_lookup is not None:
            gts = self.gt_lookup(frame)
            if gts is not None:
                # the evaluator's adapter owns the output contract
                # (2D packed detections vs 3D pred_boxes dict)
                self.evaluator.add_frame_from(per, gts)


def detect2d_infer(pipeline) -> InferFn:
    """Adapter over Detect2DPipeline.infer's (dets, valid) pair."""

    def fn(image: np.ndarray) -> Mapping[str, Any]:
        dets, valid = pipeline.infer(image)
        return {"detections": dets, "valid": valid}

    return fn


def detect3d_infer(pipeline) -> InferFn:
    """Adapter over Detect3DPipeline.infer's dict (already packed as the
    reference 3D client contract pred_boxes/scores/labels)."""

    def fn(points: np.ndarray) -> Mapping[str, Any]:
        return pipeline.infer(points)

    return fn


def detect3d_infer_async(pipeline) -> AsyncInferFn:
    """Async adapter for the in-process 3D pipeline: host prep + jit
    dispatch happen at call time (JAX dispatch is asynchronous), the
    blocking device->host read is deferred into the returned future —
    so the driver voxel-pads scan N+1 while the chip runs scan N."""

    def fn(points: np.ndarray):
        return pipeline.infer_dispatch(points)

    return fn


def channel_infer3d(
    channel,
    model_name: str,
    model_version: str = "",
    z_offset: float | None = None,
    asynchronous: bool = False,
) -> InferFn | AsyncInferFn:
    """Remote 3D adapter: host-side prep (z offset, bucketed padding)
    configured from the SERVED metadata (override z_offset to force a
    client-side sensor correction), then the points/num_points padded
    contract over the channel — the reference's remote 3D client flow
    (parse_model -> per-frame request mutation,
    communicator/ros_inference3d.py:120-149) without per-frame dynamic
    shapes."""
    import bisect
    import logging

    from triton_client_tpu.channel.base import InferRequest
    from triton_client_tpu.ops.voxelize import pad_points

    log = logging.getLogger(__name__)
    spec = channel.get_metadata(model_name, model_version)
    buckets = sorted(spec.extra.get("point_buckets", [32768, 65536, 131072]))
    if z_offset is None:
        z_offset = float(spec.extra.get("z_offset", 0.0))
    # served contract widths: input features (5 for sweep-time models)
    # and detection-row layout [box7, extras..., score, label]
    pf = int(spec.inputs[0].shape[-1]) if len(spec.inputs[0].shape) else 4
    if pf <= 0:
        pf = 4  # wildcard dim: the classic 4-feature contract
    det_w = int(spec.outputs[0].shape[-1])

    def make_request(points: np.ndarray) -> InferRequest:
        points = points[:, :pf].astype(np.float32)
        if points.shape[1] < pf:
            # narrow cloud into a wider served contract: zero-fill the
            # missing channels (single sweep -> Δt = 0), mirroring
            # Detect3DPipeline.infer_dispatch
            points = np.pad(points, ((0, 0), (0, pf - points.shape[1])))
        if z_offset:
            points[:, 2] += z_offset
        if len(points) > buckets[-1]:
            log.warning(
                "point cloud (%d pts) exceeds largest served bucket (%d); "
                "tail points dropped — raise the server's point_buckets",
                len(points), buckets[-1],
            )
        budget = buckets[min(bisect.bisect_left(buckets, len(points)), len(buckets) - 1)]
        padded, m = pad_points(points, budget)
        return InferRequest(
            model_name=model_name,
            model_version=model_version,
            inputs={"points": padded, "num_points": np.asarray(m, np.int32)},
        )

    # rows are [box7, extras..., score, label]; velocity presence comes
    # from the served metadata flag when the server publishes one
    # (every _detect3d_spec does, True or False); third-party KServe
    # servers that publish nothing fall back to the classic CenterPoint
    # row width of 11
    has_velocity = spec.extra.get("with_velocity")
    if has_velocity is None:
        has_velocity = det_w == 11

    def unpack(resp) -> Mapping[str, Any]:
        dets = np.asarray(resp.outputs["detections"])
        valid = np.asarray(resp.outputs["valid"])
        live = dets[valid]
        w = live.shape[1] if live.ndim == 2 else det_w
        out = {
            "pred_boxes": live[:, :7],
            "pred_scores": live[:, w - 2],
            "pred_labels": live[:, w - 1].astype(np.int32),
        }
        if has_velocity:
            out["pred_velocities"] = live[:, 7:9]
        return out

    if asynchronous:
        return lambda points: channel.do_inference_async(
            make_request(points)
        ).map(unpack)
    return lambda points: unpack(channel.do_inference(make_request(points)))


def channel_infer(
    channel,
    model_name: str,
    input_name: str = "images",
    model_version: str = "",
    asynchronous: bool = False,
) -> InferFn | AsyncInferFn:
    """Adapter that round-trips through a BaseChannel (TPUChannel for
    in-process, GRPCChannel for the KServe facade) — the composition the
    reference wires in main.py:131-139. With ``asynchronous=True`` the
    returned callable yields futures for the driver's inflight pump."""
    from triton_client_tpu.channel.base import InferRequest

    def make_request(data: np.ndarray) -> InferRequest:
        if input_name == "images" and data.ndim == 3:
            data = data[None]
        return InferRequest(
            model_name=model_name,
            model_version=model_version,
            inputs={input_name: data},
        )

    def unpack(resp) -> Mapping[str, Any]:
        out = dict(resp.outputs)
        if input_name == "images" and "detections" in out:
            # un-batch single-frame results for sink/eval uniformity
            if out["detections"].ndim == 3 and out["detections"].shape[0] == 1:
                out = {k: v[0] for k, v in out.items()}
        return out

    if asynchronous:
        return lambda data: channel.do_inference_async(
            make_request(data)
        ).map(unpack)
    return lambda data: unpack(channel.do_inference(make_request(data)))
