"""Live ROS adapter (import-gated — rospy is absent in TPU containers).

Parity with the reference's live drivers: subscribe a camera topic
(CompressedImage/Image, communicator/ros_inference.py:91-96) or a
PointCloud2 topic with queue_size 50 (communicator/ros_inference3d.py:110),
run the in-process TPU pipeline instead of a remote gRPC hop, and
publish annotated images / 3D box arrays back.

Design departure: the reference runs inference inside the subscriber
callback, serializing decode and compute (SURVEY.md section 2.10). Here the
callback only enqueues; a worker drains the LATEST frame (drop-stale
policy, bounded queue) so a slow model degrades to lower frame rate
instead of unbounded lag — and host decode overlaps device compute.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Mapping

import numpy as np

try:  # pragma: no cover - exercised only on a ROS-enabled host
    import rospy
    from sensor_msgs.msg import CompressedImage, Image, PointCloud2

    _HAVE_ROS = True
except ImportError:
    rospy = None
    _HAVE_ROS = False


def available() -> bool:
    return _HAVE_ROS


def _require_ros() -> None:
    if not _HAVE_ROS:
        raise ImportError(
            "rospy is not installed — live ROS mode needs a ROS noetic "
            "environment (see the reference docker/amd64 image); use the "
            "file/video/synthetic sources otherwise"
        )


class RosDetect2D:  # pragma: no cover - needs a ROS master
    """Camera topic -> TPU pipeline -> annotated Image topic."""

    def __init__(
        self,
        infer: Callable[[np.ndarray], Mapping[str, Any]],
        sub_topic: str,
        pub_topic: str,
        class_names: tuple[str, ...] = (),
        compressed: bool = True,
        queue_size: int = 4,
    ) -> None:
        _require_ros()
        import cv2
        from cv_bridge import CvBridge

        self._cv2 = cv2
        self._bridge = CvBridge()
        self.infer = infer
        self.class_names = class_names
        self._q: queue.Queue = queue.Queue(maxsize=queue_size)
        msg_type = CompressedImage if compressed else Image
        self._sub = rospy.Subscriber(sub_topic, msg_type, self._callback, queue_size=1)
        self._pub = rospy.Publisher(pub_topic, Image, queue_size=1)
        self._compressed = compressed

    def _callback(self, msg) -> None:
        # enqueue-only callback; drop the oldest frame when full
        try:
            self._q.put_nowait(msg)
        except queue.Full:
            try:
                self._q.get_nowait()
            except queue.Empty:
                pass
            self._q.put_nowait(msg)

    def spin(self) -> None:
        from triton_client_tpu.io.draw import draw_boxes

        while not rospy.is_shutdown():
            try:
                msg = self._q.get(timeout=0.5)
            except queue.Empty:
                continue
            if self._compressed:
                arr = np.frombuffer(msg.data, np.uint8)
                bgr = self._cv2.imdecode(arr, self._cv2.IMREAD_COLOR)
                rgb = bgr[..., ::-1]
            else:
                rgb = self._bridge.imgmsg_to_cv2(msg, "rgb8")
            result = self.infer(np.ascontiguousarray(rgb))
            annotated = draw_boxes(
                rgb, result["detections"], result.get("valid"), self.class_names
            )
            out = self._bridge.cv2_to_imgmsg(annotated[..., ::-1], "bgr8")
            out.header = msg.header
            self._pub.publish(out)


class RosDetect3D:  # pragma: no cover - needs a ROS master
    """PointCloud2 topic -> 3D pipeline -> Detection3DArray topic."""

    def __init__(
        self,
        infer: Callable[[np.ndarray], Mapping[str, Any]],
        sub_topic: str,
        pub_topic: str,
        queue_size: int = 50,
        score_thresh: float = 0.5,
    ) -> None:
        _require_ros()
        from sensor_msgs import point_cloud2  # noqa: F401

        self.infer = infer
        self.score_thresh = score_thresh
        self._q: queue.Queue = queue.Queue(maxsize=4)
        self._sub = rospy.Subscriber(
            sub_topic, PointCloud2, self._callback, queue_size=queue_size
        )
        try:
            from vision_msgs.msg import Detection3DArray

            self._pub = rospy.Publisher(pub_topic, Detection3DArray, queue_size=1)
        except ImportError:
            self._pub = None
            rospy.logwarn("vision_msgs absent; 3D detections will not be published")

    def _callback(self, msg) -> None:
        try:
            self._q.put_nowait(msg)
        except queue.Full:
            pass  # drop newest under backpressure, keep latency bounded

    def spin(self) -> None:
        from sensor_msgs import point_cloud2

        while not rospy.is_shutdown():
            try:
                msg = self._q.get(timeout=0.5)
            except queue.Empty:
                continue
            pts = np.asarray(
                list(
                    point_cloud2.read_points(
                        msg, field_names=("x", "y", "z", "intensity")
                    )
                ),
                np.float32,
            )
            result = self.infer(pts)
            if self._pub is not None:
                self._pub.publish(
                    _to_detection3d_array(result, msg.header, self.score_thresh)
                )


def _to_detection3d_array(result, header, score_thresh):  # pragma: no cover
    """pred arrays -> vision_msgs Detection3DArray with yaw->quaternion
    (parity: communicator/ros_inference3d.py:117-118,158-205)."""
    from geometry_msgs.msg import Point, Quaternion
    from vision_msgs.msg import Detection3D, Detection3DArray, ObjectHypothesisWithPose

    arr = Detection3DArray()
    arr.header = header
    boxes = np.asarray(result["pred_boxes"])
    scores = np.asarray(result["pred_scores"])
    labels = np.asarray(result["pred_labels"])
    for box, score, label in zip(boxes, scores, labels):
        if score < score_thresh:
            continue
        det = Detection3D()
        det.header = header
        x, y, z, dx, dy, dz, yaw = box[:7]
        det.bbox.center.position = Point(x=float(x), y=float(y), z=float(z))
        det.bbox.center.orientation = Quaternion(
            x=0.0, y=0.0, z=float(np.sin(yaw / 2)), w=float(np.cos(yaw / 2))
        )
        det.bbox.size.x, det.bbox.size.y, det.bbox.size.z = (
            float(dx),
            float(dy),
            float(dz),
        )
        hyp = ObjectHypothesisWithPose()
        hyp.id = int(label)
        hyp.score = float(score)
        det.results.append(hyp)
        arr.detections.append(det)
    return arr
