"""Metric history ring: per-model×tenant rate/util/MFU over time.

ROADMAP item 4's predictive autoscaling needs a traffic HISTORY to
forecast from — promote models ahead of the ramp the last N mornings
showed — and PR 9 declared the collector's per-tenant history as its
feed. This module is that feed: a fixed-interval ring of snapshots,
each one the DELTA of the DeviceTimeLedger's cumulative account over
the interval,

  {"t": unix_seconds, "interval_s": ...,
   "utilization": window busy ratio,
   "models": {"model|tenant": {"launches_per_s": ...,
                               "device_s_per_s": ...,
                               "mfu": ...}},
   "quality": {"model|variant": {"map50": ..., "map": ...,
                                 "velocity_mae": ...,
                                 "id_switch_rate": ...}}}

exported live at ``GET /history`` (?n=K most recent) and persisted to
JSON on drain, so a restart — or the autoscaler's offline trainer —
reads the same shape the live endpoint serves.

The ``quality`` key (ISSUE 17) appears when a quality plane is attached
(:meth:`attach_quality`): the last finished shadow-scoring window per
model×variant, so accuracy trends ride the same ring — and the same
persist/restore path — as the rate/MFU rows they must be judged
against.

The ring is bounded (``capacity`` intervals, default 360 × 10 s = 1 h)
and ``tick()`` is plain dict arithmetic off two ledger snapshots: no
host syncs, no device work — safe on the telemetry timer thread.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque

log = logging.getLogger(__name__)


class MetricHistory:
    """Fixed-interval ring of serving-rate snapshots.

    ``ledger``: an obs.device_time.DeviceTimeLedger (the source of
    device-seconds / launches / MFU). ``interval_s``: snapshot spacing;
    ``capacity``: ring depth. The background thread starts only on
    :meth:`start`; tests call :meth:`tick` directly.
    """

    def __init__(
        self,
        ledger=None,
        interval_s: float = 10.0,
        capacity: int = 360,
    ) -> None:
        self._ledger = ledger
        self._quality = None
        self.interval_s = max(0.5, float(interval_s))
        self.capacity = max(2, int(capacity))
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._last: dict | None = None
        self._last_t = time.perf_counter()
        self._ticks = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def attach_quality(self, quality) -> None:
        """Wire a quality plane whose ``history_row()`` (last finished
        window per model×variant) lands on every tick."""
        self._quality = quality

    # -- recording ------------------------------------------------------------

    def tick(self, now: float | None = None) -> dict | None:
        """Take one snapshot: the ledger's cumulative account diffed
        against the previous tick, normalized to rates. Returns the
        appended entry (None when no ledger is wired)."""
        if self._ledger is None:
            return None
        try:
            snap = self._ledger.snapshot()
        except Exception:
            log.exception("history tick: ledger snapshot failed")
            return None
        t = time.perf_counter() if now is None else float(now)
        with self._lock:
            prev, prev_t = self._last, self._last_t
            self._last, self._last_t = snap, t
            dt = max(t - prev_t, 1e-9) if prev is not None else None
            entry = self._entry(snap, prev, dt)
            if self._quality is not None:
                try:
                    entry["quality"] = self._quality.history_row()
                except Exception:
                    log.debug("history tick: quality row failed",
                              exc_info=True)
            self._ring.append(entry)
            self._ticks += 1
        return entry

    @staticmethod
    def _entry(snap: dict, prev: dict | None, dt: float | None) -> dict:
        """One ring entry from consecutive ledger snapshots. The first
        tick has no delta baseline: rates are 0, util/MFU still export
        (they are window gauges, not counters)."""
        window = snap.get("window") or {}
        mfu = window.get("mfu") or {}
        device_s = snap.get("device_seconds") or {}
        launches = snap.get("launches") or {}
        prev_device = (prev or {}).get("device_seconds") or {}
        prev_launches = (prev or {}).get("launches") or {}
        models: dict[str, dict] = {}
        for key, total in device_s.items():
            model = key.split("|", 1)[0]
            d_dev = total - prev_device.get(key, 0.0) if dt else 0.0
            d_launch = (
                launches.get(model, 0) - prev_launches.get(model, 0)
                if dt
                else 0
            )
            models[key] = {
                "launches_per_s": (d_launch / dt) if dt else 0.0,
                "device_s_per_s": (d_dev / dt) if dt else 0.0,
                "mfu": float(mfu.get(model, 0.0)),
            }
        return {
            "t": time.time(),
            "interval_s": dt or 0.0,
            "utilization": float(window.get("utilization", 0.0)),
            "models": models,
        }

    # -- reading --------------------------------------------------------------

    def snapshots(self, n: int = 0) -> list[dict]:
        """Most recent ``n`` entries (0 = everything buffered),
        oldest first."""
        with self._lock:
            entries = list(self._ring)
        if n and n > 0:
            entries = entries[-n:]
        return entries

    def stats(self) -> dict:
        with self._lock:
            return {
                "interval_s": self.interval_s,
                "capacity": self.capacity,
                "buffered": len(self._ring),
                "ticks": self._ticks,
            }

    # -- persistence (the drain path) -----------------------------------------

    def persist(self, path: str) -> int:
        """Write the ring to ``path`` as JSON; returns the entry count.
        Called from InferenceServer.drain() so the history survives the
        restart it is most needed across."""
        doc = {
            "interval_s": self.interval_s,
            "persisted_at": time.time(),
            "snapshots": self.snapshots(),
        }
        with open(path, "w") as fh:
            json.dump(doc, fh)
        return len(doc["snapshots"])

    @staticmethod
    def load(path: str) -> dict:
        """Read a persisted history document (the autoscaler's offline
        side of the round-trip)."""
        with open(path) as fh:
            return json.load(fh)

    def restore(self, doc: dict) -> int:
        """Seed the ring from a persisted document (newest entries kept
        when the document exceeds capacity)."""
        entries = list(doc.get("snapshots") or [])
        with self._lock:
            for e in entries[-self.capacity:]:
                self._ring.append(e)
        return min(len(entries), self.capacity)

    # -- background loop ------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="metric-history", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.tick()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
