"""Device-time ledger: per-model×tenant device-seconds + live MFU.

ROADMAP items 1/2/4 all argue about device *time* — how much of it the
chips spend executing vs idle, and which tenant consumed it — yet until
this ledger nothing accumulated it: the ``device_execute`` span lands
in each request's trace and histogram bucket and is forgotten. The
ledger is the standing account: every launch's device-execute window
(``t_launched -> block_until_ready``, the same interval the trace
records, so ledger totals reconcile with the histogram sum by
construction) accrues into

  * cumulative per-``model|tenant`` device-seconds
    (``tpu_serving_device_seconds_total{model,tenant}``),
  * a rolling-window device-utilization ratio — busy device-seconds
    over elapsed wall × device count
    (``tpu_serving_device_utilization_ratio``),
  * live per-model MFU — achieved flops over the window against the
    precision policy's peak (``tpu_serving_mfu{model}``), using the
    same analytic flops / POLICY_PEAK accounting the bench records
    (``spec.extra["flops_per_call"]`` + ``extra["precision"]``).

``record`` runs on the resolve() readback path (executor threads,
once per launch) and is rooted in tpulint's HOT_PATH_ROOTS: pure float
and dict work under one short lock, no host syncs.
"""

from __future__ import annotations

import collections
import threading
import time

# Re-exported from the roofline module — the single home of the
# per-chip peaks (bench.py imports the same table), so served MFU,
# bench MFU, and the roofline ceiling all divide by one denominator.
from triton_client_tpu.obs.roofline import (  # noqa: F401
    POLICY_PEAK_FLOPS,
    V5E_PEAK_FLOPS,
)


class DeviceTimeLedger:
    """Accumulates per-launch device-execute durations.

    ``tenants``: a ``runtime.lifecycle.TenantTable`` (or anything
    answering ``tenant_of(model) -> str``); models outside any tenant
    land under ``"default"``. ``devices``: chips the busy ratio is
    normalized over. ``window_s``: rolling window for the LIVE
    utilization/MFU gauges (cumulative counters never reset).

    Flops metadata is learned lazily per model from the ``spec_extra``
    mapping the channel passes on each record (first one wins):
    ``flops_per_call`` — analytic flops of one launch at its serving
    batch — and ``precision`` — the policy name keying
    :data:`POLICY_PEAK_FLOPS`. Models without flops metadata still
    account device-seconds; their MFU is simply not reported.
    """

    def __init__(
        self,
        tenants=None,
        devices: int = 1,
        window_s: float = 60.0,
        buckets: int = 12,
    ) -> None:
        self._tenants = tenants
        self._devices = max(1, int(devices))
        self._window_s = float(window_s)
        self._n_buckets = max(2, int(buckets))
        self._bucket_w = self._window_s / self._n_buckets
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        # cumulative account (the Prometheus counters)
        self._device_s: collections.Counter = collections.Counter()
        self._launches: collections.Counter = collections.Counter()
        self._total_device_s = 0.0
        # per-model flops metadata learned from spec.extra
        self._flops_per_call: dict[str, float] = {}
        self._peak_flops: dict[str, float] = {}
        # rolling window: ring of (bucket_index, {model: [dev_s, flops]})
        self._ring: collections.deque = collections.deque(
            maxlen=self._n_buckets
        )
        self._tenant_cache: dict[str, str] = {}

    # -- recording ------------------------------------------------------------

    def tenant_of(self, model: str) -> str:
        tenant = self._tenant_cache.get(model)
        if tenant is None:
            tenant = "default"
            if self._tenants is not None:
                try:
                    tenant = self._tenants.tenant_of(model) or "default"
                except Exception:
                    tenant = "default"
            self._tenant_cache[model] = tenant
        return tenant

    def record(
        self, model: str, duration_s: float, spec_extra=None, tenant=None
    ) -> None:
        """Account one launch's device-execute window. Called from the
        channel's resolve() with the SAME (t_launched, t_ready)
        interval the trace's device_execute span gets — the two
        measurements cannot drift.

        ``tenant`` overrides the table lookup — streaming-session
        launches pass ``stream:<sequence_id>`` so the tenant axis
        answers "device seconds per live stream" directly
        (runtime/sessions.py)."""
        if duration_s < 0:
            duration_s = 0.0
        if tenant is None:
            tenant = self.tenant_of(model)
        flops = self._flops_per_call.get(model)
        if flops is None and spec_extra:
            try:
                flops = float(spec_extra.get("flops_per_call") or 0.0)
            except (TypeError, ValueError):
                flops = 0.0
            self._flops_per_call[model] = flops
            precision = str(spec_extra.get("precision") or "f32")
            self._peak_flops[model] = POLICY_PEAK_FLOPS.get(
                precision, V5E_PEAK_FLOPS
            )
        now = time.perf_counter()
        idx = int(now / self._bucket_w)
        with self._lock:
            self._device_s[f"{model}|{tenant}"] += duration_s
            self._launches[model] += 1
            self._total_device_s += duration_s
            if not self._ring or self._ring[-1][0] != idx:
                self._ring.append((idx, {}))
            per_model = self._ring[-1][1]
            cell = per_model.get(model)
            if cell is None:
                cell = per_model[model] = [0.0, 0.0]
            cell[0] += duration_s
            cell[1] += flops or 0.0

    # -- reading --------------------------------------------------------------

    def _window_totals(self, now: float):
        """(elapsed_s, busy_s, {model: [dev_s, flops]}) over the live
        window — caller holds the lock."""
        idx_now = int(now / self._bucket_w)
        floor = idx_now - self._n_buckets + 1
        busy = 0.0
        per_model: dict[str, list[float]] = {}
        for idx, models in self._ring:
            if idx < floor:
                continue
            for model, (dev_s, flops) in models.items():
                cell = per_model.get(model)
                if cell is None:
                    cell = per_model[model] = [0.0, 0.0]
                cell[0] += dev_s
                cell[1] += flops
                busy += dev_s
        elapsed = min(now - self._t0, self._window_s)
        return max(elapsed, 1e-9), busy, per_model

    def utilization(self) -> float:
        """Rolling-window busy fraction: device-seconds executed over
        elapsed wall × devices."""
        now = time.perf_counter()
        with self._lock:
            elapsed, busy, _ = self._window_totals(now)
        return min(1.0, busy / (elapsed * self._devices))

    def mfu(self) -> dict[str, float]:
        """Live per-model MFU over the rolling window (only models
        with flops metadata)."""
        now = time.perf_counter()
        with self._lock:
            elapsed, _, per_model = self._window_totals(now)
            peaks = dict(self._peak_flops)
        out = {}
        for model, (_dev_s, flops) in per_model.items():
            peak = peaks.get(model) or 0.0
            if flops > 0 and peak > 0:
                out[model] = flops / elapsed / (peak * self._devices)
        return out

    def device_seconds(self) -> dict[str, float]:
        """Cumulative ``{"model|tenant": seconds}``."""
        with self._lock:
            return dict(self._device_s)

    def snapshot(self) -> dict:
        now = time.perf_counter()
        with self._lock:
            elapsed, busy, per_model = self._window_totals(now)
            device_s = dict(self._device_s)
            launches = dict(self._launches)
            total = self._total_device_s
            peaks = dict(self._peak_flops)
            uptime = now - self._t0
        mfu = {
            model: flops / elapsed / ((peaks.get(model) or 0.0) * self._devices)
            for model, (_d, flops) in per_model.items()
            if flops > 0 and peaks.get(model)
        }
        return {
            "devices": self._devices,
            "uptime_s": uptime,
            "device_seconds": device_s,
            "launches": launches,
            "total_device_seconds": total,
            "busy_fraction": min(1.0, total / (max(uptime, 1e-9) * self._devices)),
            "window": {
                "seconds": elapsed,
                "device_seconds": busy,
                "utilization": min(1.0, busy / (elapsed * self._devices)),
                "mfu": mfu,
            },
        }
