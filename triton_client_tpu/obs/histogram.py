"""Fixed-bucket latency histograms for the SLO observability ring.

PR 2's collector exports counters and gauges; percentiles existed only
inside ``StageProfiler``'s in-memory reservoir, invisible to
``snapshot()``/``delta()`` and to any scraper that wants a windowed
p99. This module is the missing primitive: a thread-safe fixed-bucket
histogram whose snapshot is a plain dict of numbers, so it rides the
same ``RuntimeCollector.snapshot()``/``delta()`` path as every counter
— perf scripts diff two snapshots and read the WINDOW's percentiles,
exactly like they diff staged/launched counts today.

Representation choices, all load-bearing:

  * buckets are NON-cumulative per-bucket counts keyed by the upper
    bound's repr (``"0.005"`` ... ``"inf"``). ``delta()``'s recursive
    numeric diff then yields the window's per-bucket counts for free;
    cumulative counts would survive the diff too, but non-cumulative
    keeps ``quantile_from_snapshot`` trivially correct on both a raw
    snapshot and a delta.
  * bounds are FIXED at construction (default: the serving-latency
    ladder ``PrometheusStageExporter`` already exports, widened at the
    sub-millisecond end for device-execute spans). Fixed bounds mean
    two histograms — or two snapshots of one — are always mergeable
    and diffable; adaptive bounds are not.
  * ``observe`` is one bisect + two adds under a per-histogram lock —
    cheap enough to feed from ``Tracer.finish`` on every request
    without measurable throughput cost (the <=2% acceptance gate).

``HistogramFamily`` keys child histograms by ``(model, stage)`` — the
label set the collector exports as ``tpu_serving_latency_seconds`` —
with the stage names the tentpole fixes: queue_delay, merge_wait,
device_execute, readback, e2e.
"""

from __future__ import annotations

import bisect
import math
import threading

# Upper bounds in seconds. Spans from 250us (a fast device_execute on a
# warm small model) to 60s (a tunnel-degraded e2e); the +Inf overflow
# bucket is implicit. Matches the spirit of profiling._BUCKETS but
# extends both ends so per-stage spans and tunnel e2e both resolve.
DEFAULT_BUCKETS: tuple[float, ...] = (
    0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

# The per-request span names that feed SLO stages, and the stage label
# each exports under. batch_queue covers admission window + ready-queue
# + slot backpressure end to end; merge_wait (recorded per member by
# the batcher) is the ready-queue portion alone.
SLO_STAGES: dict[str, str] = {
    "batch_queue": "queue_delay",
    "merge_wait": "merge_wait",
    "device_execute": "device_execute",
    "readback": "readback",
}


class LatencyHistogram:
    """One fixed-bucket histogram (counts + sum), thread-safe."""

    __slots__ = ("_bounds", "_counts", "_overflow", "_sum", "_count", "_lock")

    def __init__(self, buckets: tuple[float, ...] = DEFAULT_BUCKETS) -> None:
        self._bounds = tuple(sorted(float(b) for b in buckets))
        if not self._bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self._counts = [0] * len(self._bounds)
        self._overflow = 0
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, seconds: float) -> None:
        v = float(seconds)
        if v < 0 or math.isnan(v):
            v = 0.0  # clock skew / bad sample: clamp, never throw
        i = bisect.bisect_left(self._bounds, v)
        with self._lock:
            if i < len(self._bounds):
                self._counts[i] += 1
            else:
                self._overflow += 1
            self._sum += v
            self._count += 1

    def snapshot(self) -> dict:
        """``{"buckets": {"<bound>": n, ..., "inf": n}, "sum": s,
        "count": c}`` — every leaf numeric, so ``RuntimeCollector.delta``
        diffs two snapshots into the window's histogram."""
        with self._lock:
            buckets = {
                repr(b): c for b, c in zip(self._bounds, self._counts)
            }
            buckets["inf"] = self._overflow
            return {"buckets": buckets, "sum": self._sum, "count": self._count}

    def quantile(self, q: float) -> float:
        return quantile_from_snapshot(self.snapshot(), q)

    @property
    def bounds(self) -> tuple[float, ...]:
        return self._bounds


def quantile_from_snapshot(snap: dict, q: float) -> float:
    """Estimate the ``q``-quantile (0..1) from a histogram snapshot OR
    a ``delta()`` of two snapshots (non-cumulative bucket counts).

    Linear interpolation inside the target bucket — the same estimator
    Prometheus' ``histogram_quantile`` uses — so a test can bound the
    error by the bucket's width. Returns 0.0 on an empty histogram and
    the largest finite bound when the quantile lands in +Inf."""
    buckets = snap.get("buckets") or {}
    items = sorted(
        ((float(k), int(v)) for k, v in buckets.items() if k != "inf"),
    )
    overflow = int(buckets.get("inf", 0))
    total = sum(c for _, c in items) + overflow
    if total <= 0:
        return 0.0
    rank = max(0.0, min(1.0, float(q))) * total
    seen = 0
    lo = 0.0
    for bound, c in items:
        if seen + c >= rank and c > 0:
            frac = (rank - seen) / c
            return lo + (bound - lo) * frac
        seen += c
        lo = bound
    return items[-1][0] if items else 0.0


class HistogramFamily:
    """Child ``LatencyHistogram`` per (model, stage) label pair.

    ``observe`` creates children lazily under the family lock; reads
    (``snapshot``/``quantile``) take one consistent pass. Keys join as
    ``"model|stage"`` in snapshots — the same ``|``-joined convention
    the collector's error counter uses, so ``delta()`` output stays
    flat and JSON-friendly."""

    def __init__(self, buckets: tuple[float, ...] = DEFAULT_BUCKETS) -> None:
        self._buckets = tuple(buckets)
        self._children: dict[tuple[str, str], LatencyHistogram] = {}
        self._lock = threading.Lock()

    def child(self, model: str, stage: str) -> LatencyHistogram:
        key = (str(model), str(stage))
        h = self._children.get(key)
        if h is None:
            with self._lock:
                h = self._children.get(key)
                if h is None:
                    h = self._children[key] = LatencyHistogram(self._buckets)
        return h

    def observe(self, model: str, stage: str, seconds: float) -> None:
        self.child(model, stage).observe(seconds)

    def quantile(self, model: str, stage: str, q: float) -> float:
        with self._lock:
            h = self._children.get((str(model), str(stage)))
        return h.quantile(q) if h is not None else 0.0

    def count(self, model: str, stage: str) -> int:
        with self._lock:
            h = self._children.get((str(model), str(stage)))
        return h.snapshot()["count"] if h is not None else 0

    def snapshot(self) -> dict:
        with self._lock:
            children = dict(self._children)
        return {
            f"{model}|{stage}": h.snapshot()
            for (model, stage), h in sorted(children.items())
        }

    def items(self):
        with self._lock:
            return sorted(self._children.items())
