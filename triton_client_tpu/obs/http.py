"""Telemetry HTTP endpoint: /metrics + /traces + /snapshot on one port.

Replaces ``prometheus_client.start_http_server`` on the serving metrics
port so the same port the Prometheus scraper already targets (the
reference's :8002 story, data/prometheus.yml) also serves the request
traces and the raw collector snapshot:

  GET /metrics   Prometheus exposition of the server's registry
  GET /traces    Chrome-trace JSON of the tracer ring buffer
                 (?n=K limits to the K most recent; load in Perfetto)
                 (?slo_violations=1 serves the SLO tail-sampler ring
                 instead: only exemplars that missed their deadline or
                 landed at/above the live per-model p99)
  GET /snapshot  RuntimeCollector.snapshot() as JSON (debug/automation)
  GET /profile   on-demand jax.profiler capture (?seconds=N, default 1,
                 capped at 60; ?top_k=K bounds the op rows): blocks for
                 the window, writes the XLA + device timeline into a
                 server-local directory, and returns its path PLUS the
                 parsed per-op summary (obs.opstats: op, kind, model,
                 occurrences, device time) as JSON. One capture at a
                 time — a concurrent request gets 409 (jax.profiler is
                 a process-global singleton; overlapping captures
                 abort). A trace that fails to parse still returns the
                 capture path (op_summary_error names the failure) and
                 NEVER wedges the capture guard.
  GET /history   the MetricHistory ring (?n=K most recent snapshots):
                 per-model×tenant launch/device-time rates, utilization
                 and MFU at a fixed interval (obs/history.py).

Paths degrade independently: without prometheus_client /metrics is 503
but traces still export; without a tracer /traces is 404 (and without
an SLO tracker, ?slo_violations=1 is 404); without jax /profile is 503;
without a history ring /history is 404.
"""

from __future__ import annotations

import json
import logging
import tempfile
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

log = logging.getLogger(__name__)

#: hard ceiling for one /profile capture window
_PROFILE_MAX_S = 60.0


class TelemetryServer:
    """Bound on construction (port 0 picks an ephemeral port — tests and
    multi-server processes); serves on a daemon thread until close()."""

    def __init__(
        self,
        port: int = 8002,
        registry=None,
        tracer=None,
        collector=None,
        host: str = "0.0.0.0",
        slo=None,
        history=None,
    ) -> None:
        self._registry = registry
        self._tracer = tracer
        self._collector = collector
        self._slo = slo
        self._history = history
        # /profile concurrency guard: jax.profiler keeps ONE process-
        # global capture; a second start_trace raises mid-capture and
        # would kill the first requester's window too
        self._profile_lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # no per-scrape stderr spam
                log.debug("telemetry http: " + fmt, *args)

            def do_GET(self):
                try:
                    outer._handle(self)
                except BrokenPipeError:
                    pass  # scraper went away mid-response
                except Exception:
                    log.exception("telemetry handler failed for %s", self.path)
                    try:
                        self.send_error(500)
                    except Exception:
                        pass

        self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="telemetry-http",
            daemon=True,
        )
        self._thread.start()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def _handle(self, req) -> None:
        parsed = urlparse(req.path)
        path = parsed.path.rstrip("/") or "/"
        if path == "/metrics":
            if self._registry is None:
                self._send(req, 503, b"prometheus_client unavailable\n")
                return
            import prometheus_client

            body = prometheus_client.generate_latest(self._registry)
            self._send(req, 200, body, prometheus_client.CONTENT_TYPE_LATEST)
        elif path in ("/traces", "/trace"):
            q = parse_qs(parsed.query)
            try:
                n = int(q.get("n", ["0"])[0])
            except ValueError:
                n = 0
            if q.get("slo_violations", ["0"])[0] not in ("0", ""):
                if self._slo is None:
                    self._send(req, 404, b"slo tracking disabled\n")
                    return
                from triton_client_tpu.obs.trace import chrome_trace

                payload = chrome_trace(self._slo.violations(n))
            elif self._tracer is None:
                self._send(req, 404, b"tracing disabled\n")
                return
            else:
                payload = self._tracer.chrome_trace(n)
            body = json.dumps(payload).encode()
            self._send(req, 200, body, "application/json")
        elif path == "/snapshot":
            if self._collector is None:
                self._send(req, 404, b"collector disabled\n")
                return
            body = json.dumps(self._collector.snapshot(), default=str).encode()
            self._send(req, 200, body, "application/json")
        elif path == "/profile":
            self._profile(req, parsed)
        elif path == "/history":
            if self._history is None:
                self._send(req, 404, b"metric history disabled\n")
                return
            q = parse_qs(parsed.query)
            try:
                n = int(q.get("n", ["0"])[0])
            except ValueError:
                n = 0
            body = json.dumps(
                {
                    "stats": self._history.stats(),
                    "snapshots": self._history.snapshots(n),
                }
            ).encode()
            self._send(req, 200, body, "application/json")
        elif path == "/":
            self._send(
                req, 200,
                b"tpu_serving telemetry: /metrics /traces /snapshot "
                b"/profile /history\n",
            )
        else:
            self._send(req, 404, b"not found\n")

    @property
    def profile_lock(self) -> threading.Lock:
        """The process-global capture guard. The ContinuousSampler
        shares this lock so background windows and on-demand /profile
        captures can never overlap (jax.profiler is a singleton)."""
        return self._profile_lock

    def _profile(self, req, parsed) -> None:
        """Blocking jax.profiler capture window; refuses overlap. The
        response carries the capture path AND the parsed per-op summary
        (obs.opstats). The guard covers ONLY the profiler singleton:
        it is released in a finally before the (pure-file) parse, so a
        malformed trace degrades to an ``op_summary_error`` field and
        can never wedge future captures."""
        q = parse_qs(parsed.query)
        try:
            seconds = float(q.get("seconds", ["1"])[0])
        except ValueError:
            self._send(req, 400, b"seconds must be a number\n")
            return
        try:
            top_k = int(q.get("top_k", ["20"])[0])
        except ValueError:
            top_k = 20
        seconds = min(max(seconds, 0.05), _PROFILE_MAX_S)
        try:
            import jax
        except ImportError:
            self._send(req, 503, b"jax unavailable; /profile disabled\n")
            return
        if not self._profile_lock.acquire(blocking=False):
            self._send(
                req, 409, b"a profile capture is already in progress\n"
            )
            return
        try:
            log_dir = tempfile.mkdtemp(prefix="tpu_serving_profile_")
            jax.profiler.start_trace(log_dir)
            try:
                time.sleep(seconds)
            finally:
                jax.profiler.stop_trace()
        except Exception as e:
            log.exception("profile capture failed")
            self._send(req, 500, f"profile capture failed: {e}\n".encode())
            return
        finally:
            self._profile_lock.release()
        doc = {"log_dir": log_dir, "seconds": seconds}
        try:
            from triton_client_tpu.obs import opstats

            modules = None
            if self._collector is not None:
                hlo_modules = getattr(self._collector, "hlo_modules", None)
                if callable(hlo_modules):
                    modules = hlo_modules()
            doc["op_summary"] = opstats.summarize_profile_dir(
                log_dir, hlo_modules=modules, top_k=top_k
            )
        except Exception as e:
            log.exception("profile trace parse failed")
            doc["op_summary_error"] = str(e)
        self._send(req, 200, json.dumps(doc).encode(), "application/json")

    @staticmethod
    def _send(req, code: int, body: bytes, ctype: str = "text/plain") -> None:
        req.send_response(code)
        req.send_header("Content-Type", ctype)
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)
