"""Roofline attribution: measured flops/bytes -> bound class + ceiling.

ROADMAP item 2 says the chips are almost idle (MFU 1.4-7%) but nothing
in the stack can say *why*: is a model compute-bound (fuse harder, use
the MXU at int8) or bandwidth-bound (keep intermediates in VMEM, shrink
the working set)? The roofline model answers with two numbers per
model:

  arithmetic intensity  I = flops / bytes            (flop per HBM byte)
  machine knee          K = peak_flops / peak_bw     (flop per byte)

I >= K means the MXU ceiling binds (compute-bound: the attainable rate
is ``peak_flops / flops`` calls/s); I < K means the HBM ceiling binds
(bandwidth-bound: ``peak_bw / bytes`` calls/s). The attainable-fps
ceiling next to the measured fps is the honest headroom statement —
"yolov5n serves 1,685 fps against an 8,900 fps roofline" names the gap
a kernel PR must close.

flops/bytes come MEASURED from XLA's own cost model at launcher-build
time (``jax.stages.Lowered.cost_analysis()`` — no backend compile, a
few ms of tracing the launcher already paid) and are recorded into
``model.spec.extra``:

  measured_flops_per_call / measured_bytes_per_call   XLA cost model
  measured_batch                                      rows they were
                                                      measured at
  flops_per_call                                      overwritten with
                                                      the measured
                                                      value (the ledger
                                                      and MFU gauges
                                                      then use it)
  analytic_flops_per_call                             the previous
                                                      hand-maintained
                                                      seed, kept as a
                                                      labeled
                                                      comparison only
  hlo_module                                          the jit module
                                                      name opstats maps
                                                      device ops back
                                                      to this model by

This module is also the single home of the per-chip peaks: bench.py
and obs/device_time.py used to carry duplicate POLICY_PEAK_FLOPS
tables; both now import from here so served MFU, bench MFU, and the
roofline all divide by the same denominator.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

#: v5e per-chip peaks. The MXU runs f32 inputs at the bf16 MAC rate
#: under jax's default precision, so f32/bf16/int8-weight policies all
#: see the same flops ceiling; int8 activations double the MAC rate.
V5E_PEAK_FLOPS = 197e12
#: v5e HBM2 bandwidth per chip (bytes/s) — the roofline's memory slope.
V5E_PEAK_HBM_BPS = 819e9

POLICY_PEAK_FLOPS = {
    "f32": V5E_PEAK_FLOPS,
    "bf16": V5E_PEAK_FLOPS,
    "int8w": V5E_PEAK_FLOPS,
    "int8": 2 * V5E_PEAK_FLOPS,
}
#: HBM bandwidth is precision-independent (the bytes themselves shrink
#: with narrower dtypes — that is already in the measured byte count).
POLICY_PEAK_BYTES = {
    "f32": V5E_PEAK_HBM_BPS,
    "bf16": V5E_PEAK_HBM_BPS,
    "int8w": V5E_PEAK_HBM_BPS,
    "int8": V5E_PEAK_HBM_BPS,
}


def peak_flops(precision: str | None) -> float:
    return POLICY_PEAK_FLOPS.get(str(precision or "f32"), V5E_PEAK_FLOPS)


def peak_bytes_per_s(precision: str | None) -> float:
    return POLICY_PEAK_BYTES.get(str(precision or "f32"), V5E_PEAK_HBM_BPS)


@dataclass
class RooflineRow:
    """One model's (or op's) position against the machine roofline."""

    flops: float
    bytes: float
    precision: str = "f32"
    batch: int = 1
    #: derived
    intensity: float = 0.0
    knee: float = 0.0
    bound: str = "unknown"
    attainable_calls_per_s: float = 0.0
    attainable_fps: float = 0.0

    def as_dict(self) -> dict:
        return {
            "flops": self.flops,
            "bytes": self.bytes,
            "precision": self.precision,
            "batch": self.batch,
            "intensity": self.intensity,
            "knee": self.knee,
            "bound": self.bound,
            "attainable_calls_per_s": self.attainable_calls_per_s,
            "attainable_fps": self.attainable_fps,
        }


def classify(
    flops: float,
    bytes_accessed: float,
    precision: str = "f32",
    batch: int = 1,
) -> RooflineRow:
    """Roofline position of one launch: arithmetic intensity against
    the machine knee, the binding ceiling, and the attainable call/fps
    rate if ONLY that ceiling bound (the ideal-overlap upper bound an
    actual serving rate is compared to)."""
    flops = max(0.0, float(flops or 0.0))
    bytes_accessed = max(0.0, float(bytes_accessed or 0.0))
    batch = max(1, int(batch or 1))
    pf, pb = peak_flops(precision), peak_bytes_per_s(precision)
    row = RooflineRow(
        flops=flops, bytes=bytes_accessed, precision=str(precision or "f32"),
        batch=batch, knee=pf / pb,
    )
    if flops <= 0 and bytes_accessed <= 0:
        return row
    row.intensity = flops / bytes_accessed if bytes_accessed > 0 else float(
        "inf"
    )
    compute_rate = pf / flops if flops > 0 else float("inf")
    memory_rate = pb / bytes_accessed if bytes_accessed > 0 else float("inf")
    row.bound = "compute" if compute_rate <= memory_rate else "bandwidth"
    row.attainable_calls_per_s = min(compute_rate, memory_rate)
    row.attainable_fps = row.attainable_calls_per_s * batch
    return row


# -- measured cost capture (launcher-build / first-launch time) ---------------


def _cost_dict(cost) -> dict:
    """Normalize jax's cost_analysis return (dict, or list-of-dict on
    some backends) to one flat dict."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return dict(cost or {})


def launcher_name(model) -> str:
    """The python-identifier name the channel gives a model's jitted
    launcher, so the HLO module (``jit_<this>``) names the model in
    profiler traces — opstats' primary op->model attribution key."""
    raw = f"mdl_{model.spec.name}_{model.spec.version}"
    return re.sub(r"[^0-9a-zA-Z_]", "_", raw)


def hlo_module_for(model) -> str:
    """The HLO module name xla emits for the named launcher."""
    return "jit_" + launcher_name(model)


def name_launcher(fn, model):
    """Stamp a launcher callable with the model's launcher name BEFORE
    ``jax.jit`` wraps it — jit takes the module name from the wrapped
    function's ``__name__``."""
    name = launcher_name(model)
    try:
        fn.__name__ = name
        fn.__qualname__ = name
    except (AttributeError, TypeError):
        pass
    return fn


def measure_launch_cost(launcher, *args, batch_rows: int = 1) -> dict:
    """Measured flops/bytes of one launcher call at the given args'
    shapes, via XLA's cost model on the LOWERED module — tracing only,
    no backend compile, so calling this next to the first launch adds
    milliseconds to a path that is about to pay a full compile anyway.

    Returns ``{"flops", "bytes", "batch"}`` (zeros when the cost model
    reports nothing)."""
    lowered = launcher.lower(*args)
    cost = _cost_dict(lowered.cost_analysis())
    return {
        "flops": float(cost.get("flops", 0.0) or 0.0),
        "bytes": float(cost.get("bytes accessed", 0.0) or 0.0),
        "batch": max(1, int(batch_rows or 1)),
    }


def record_launch_cost(model, launcher, *args, batch_rows: int = 1) -> dict:
    """Measure one launcher call and record the result into
    ``model.spec.extra`` (see the module docstring for the keys).
    The previous hand-maintained ``flops_per_call`` seed — if any — is
    preserved as ``analytic_flops_per_call`` and then OVERWRITTEN with
    the measured value, so every downstream flops consumer (the
    DeviceTimeLedger's MFU, the collector's model rows, bench) divides
    by what XLA actually scheduled rather than what a human last
    derived."""
    measured = measure_launch_cost(launcher, *args, batch_rows=batch_rows)
    extra = model.spec.extra
    seed = extra.get("flops_per_call")
    if seed is not None and "analytic_flops_per_call" not in extra:
        extra["analytic_flops_per_call"] = seed
    if measured["flops"] > 0:
        extra["flops_per_call"] = measured["flops"]
    extra["measured_flops_per_call"] = measured["flops"]
    extra["measured_bytes_per_call"] = measured["bytes"]
    extra["measured_batch"] = measured["batch"]
    extra.setdefault("hlo_module", hlo_module_for(model))
    return measured


def model_row(extra: dict, measured_fps: float | None = None) -> dict:
    """Roofline report row from a model's ``spec.extra`` (the shape the
    collector's ``models`` snapshot section and the ``roofline`` CLI
    share). ``measured_fps`` — when known — is reported next to the
    attainable ceiling as ``attained_fraction``."""
    flops = float(extra.get("measured_flops_per_call") or 0.0)
    bytes_ = float(extra.get("measured_bytes_per_call") or 0.0)
    batch = int(extra.get("measured_batch") or 1)
    precision = str(extra.get("precision") or "f32")
    row = classify(flops, bytes_, precision, batch).as_dict()
    analytic = extra.get("analytic_flops_per_call")
    if analytic is not None:
        row["analytic_flops_per_call"] = float(analytic)
    if measured_fps is not None and row["attainable_fps"] > 0:
        row["measured_fps"] = float(measured_fps)
        row["attained_fraction"] = float(measured_fps) / row["attainable_fps"]
    return row
