"""Op-level device attribution from jax.profiler captures.

The ``/profile`` endpoint and ``--profile-trace`` both write a
TensorBoard profile directory whose useful artifact — for a machine —
is the Chrome-trace JSON under ``plugins/profile/<run>/*.trace.json.gz``.
Until this module a human eyeballed it in Perfetto; now it parses into
per-op rows the rest of the observability plane can rank, export, and
diff:

  op name, fusion kind, occurrences, device-time, share of the
  window's total op time, owning model.

Op -> model attribution uses two keys, in order:

  1. ``hlo_module`` — XLA stamps every op event with its module name,
     and the staged channels name each model's launcher so the module
     is ``jit_mdl_<name>_<version>`` (obs/roofline.py
     ``name_launcher``). Exact and unambiguous, survives async
     dispatch and pipelining.
  2. ``TraceAnnotation`` windows — ``StagedChannel.launch`` brackets
     every dispatch in a ``launch:<model>:<version>`` annotation; an op
     event whose midpoint falls inside exactly one model's windows is
     attributed to it. The fallback for launchers that predate naming
     (ragged buckets, host-side custom calls).

Everything here is stdlib (json + gzip): the parser must run inside
the serving process's telemetry thread and in offline CLI use on
machines without TensorBoard.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re

#: annotation prefix StagedChannel.launch emits around every dispatch
LAUNCH_ANNOTATION_PREFIX = "launch:"

#: scope prefix the fused Pallas kernels stamp on their launches
#: (ops/pallas_voxel, ops/pallas_decode use jax.named_scope
#: ``fused:<stage>`` with stage from ops/fused.FUSED_STAGES)
FUSED_SCOPE_PREFIX = "fused:"

_FUSED_SCOPE_RE = re.compile(r"fused:([A-Za-z0-9_]+)")


def fused_stage(name: str, args: dict | None = None) -> str | None:
    """Stage name from a ``fused:<stage>`` scope marker, searched in the
    event name and every string-valued arg. On TPU the jax.named_scope
    rides in the op metadata XLA copies into the trace args (long_name /
    tf_op carry the full scope path); on CPU the metadata is dropped and
    per-stage split falls back to annotation windows (see
    :func:`summarize`)."""
    m = _FUSED_SCOPE_RE.search(name)
    if m:
        return m.group(1)
    for v in (args or {}).values():
        if isinstance(v, str):
            m = _FUSED_SCOPE_RE.search(v)
            if m:
                return m.group(1)
    return None

#: op-name substring -> fusion/kind bucket, first match wins. Coarse on
#: purpose: the question is "what KIND of work dominates", not XLA's
#: full taxonomy.
_KIND_RULES = (
    ("fusion", "fusion"),
    ("custom-call", "custom-call"),
    ("custom_call", "custom-call"),
    ("convolution", "convolution"),
    ("conv", "convolution"),
    ("dot", "dot"),
    ("all-reduce", "collective"),
    ("all-gather", "collective"),
    ("reduce-scatter", "collective"),
    ("collective", "collective"),
    ("scatter", "scatter"),
    ("gather", "gather"),
    ("reduce", "reduce"),
    ("sort", "sort"),
    ("copy", "data-movement"),
    ("transpose", "data-movement"),
    ("reshape", "data-movement"),
    ("broadcast", "data-movement"),
    ("slice", "data-movement"),
    ("concatenate", "data-movement"),
    ("pad", "data-movement"),
    ("infeed", "host-transfer"),
    ("outfeed", "host-transfer"),
)


def op_kind(name: str) -> str:
    low = name.lower()
    for needle, kind in _KIND_RULES:
        if needle in low:
            return kind
    return "other"


def find_trace_file(log_dir: str) -> str | None:
    """Newest ``*.trace.json(.gz)`` under a jax.profiler log dir (the
    ``plugins/profile/<timestamp>/`` layout), or the path itself when
    it already points at a trace file."""
    if os.path.isfile(log_dir):
        return log_dir
    candidates = glob.glob(
        os.path.join(log_dir, "**", "*.trace.json.gz"), recursive=True
    ) + glob.glob(os.path.join(log_dir, "**", "*.trace.json"), recursive=True)
    if not candidates:
        return None
    return max(candidates, key=os.path.getmtime)


def load_trace(path: str) -> dict:
    """Chrome-trace JSON document from a .trace.json(.gz) file."""
    if path.endswith(".gz"):
        with gzip.open(path, "rt") as fh:
            return json.load(fh)
    with open(path) as fh:
        return json.load(fh)


def _annotation_windows(events, prefix: str) -> dict[str, list]:
    """``model -> [(ts, ts_end), ...]`` from launch annotations. The
    annotation name is ``<prefix><model>:<version>``; version is folded
    out — device time is accounted per model name everywhere else."""
    windows: dict[str, list] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        name = e.get("name", "")
        if not name.startswith(prefix):
            continue
        model = name[len(prefix):].rsplit(":", 1)[0]
        ts = float(e.get("ts", 0.0))
        windows.setdefault(model, []).append((ts, ts + float(e.get("dur", 0.0))))
    return windows


def _module_models(hlo_modules: dict | None) -> dict[str, str]:
    """Normalize an ``{hlo_module: model}`` mapping (the collector
    builds one from each spec.extra's recorded ``hlo_module``)."""
    return {str(k): str(v) for k, v in (hlo_modules or {}).items()}


def summarize(
    doc: dict,
    hlo_modules: dict | None = None,
    annotation_prefix: str = LAUNCH_ANNOTATION_PREFIX,
    top_k: int = 0,
) -> dict:
    """Per-op rows from one Chrome-trace document.

    An event is a DEVICE OP when it carries ``args.hlo_op`` or
    ``args.hlo_module`` (XLA stamps both on CPU and TPU op events;
    python/runtime events carry neither). Rows aggregate over
    ``(module, op name)``; ``top_k`` > 0 truncates to the K largest by
    device time (the full totals stay in the summary header either
    way)."""
    events = doc.get("traceEvents", []) or []
    module_of = _module_models(hlo_modules)
    windows = _annotation_windows(events, annotation_prefix)
    stage_windows = _stage_windows(events)

    rows: dict[tuple, dict] = {}
    total_us = 0.0
    for e in events:
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        module = args.get("hlo_module")
        hlo_op = args.get("hlo_op")
        if not module and not hlo_op:
            continue
        name = str(hlo_op or e.get("name", "?"))
        module = str(module or "?")
        stage = fused_stage(str(e.get("name", "")), args) or fused_stage(name)
        dur = float(e.get("dur", 0.0))
        ts = float(e.get("ts", 0.0))
        key = (module, name, stage)
        row = rows.get(key)
        if row is None:
            row = rows[key] = {
                "op": name,
                "module": module,
                "kind": op_kind(name),
                "model": None,
                "stage": stage,
                "occurrences": 0,
                "time_us": 0.0,
                "_mid": [],
            }
        row["occurrences"] += 1
        row["time_us"] += dur
        row["_mid"].append(ts + dur / 2.0)
        total_us += dur

    # attribution pass: module name first, annotation midpoint second;
    # fused-stage split rides the same midpoints when the op metadata
    # carried no scope marker (CPU traces drop it)
    model_us: dict[str, float] = {}
    stage_us: dict[str, float] = {}
    unattributed_us = 0.0
    for row in rows.values():
        model = _attribute_module(row["module"], module_of)
        if model is None:
            model = _attribute_windows(row["_mid"], windows)
        if row["stage"] is None and stage_windows:
            row["stage"] = _attribute_windows(row["_mid"], stage_windows)
        row["model"] = model
        del row["_mid"]
        if model is None:
            unattributed_us += row["time_us"]
        else:
            model_us[model] = model_us.get(model, 0.0) + row["time_us"]
        if row["stage"] is not None:
            stage_us[row["stage"]] = (
                stage_us.get(row["stage"], 0.0) + row["time_us"]
            )

    ordered = sorted(rows.values(), key=lambda r: -r["time_us"])
    for row in ordered:
        row["share"] = row["time_us"] / total_us if total_us > 0 else 0.0
    if top_k and top_k > 0:
        ordered = ordered[:top_k]
    return {
        "total_op_time_us": total_us,
        "op_count": len(rows),
        "ops": ordered,
        "models": model_us,
        "unattributed_us": unattributed_us,
        # additive sub-attribution: stage time is a SPLIT of the same
        # device time already counted under its model, never extra —
        # the >=90% model-attribution bar (perf/profile_roofline.py)
        # is unaffected by fused-kernel accounting
        "stages": stage_us,
        "annotation_windows": {
            m: len(ws) for m, ws in windows.items()
        },
    }


def _stage_windows(events) -> dict[str, list]:
    """``stage -> [(ts, ts_end), ...]`` from ``fused:<stage>`` trace
    annotations (jax.profiler.TraceAnnotation around an eager fused
    launch — perf/profile_fused.py emits them so CPU/interpret traces
    still split per stage). Device-op events are excluded: their own
    scope marker is read directly by :func:`fused_stage`."""
    windows: dict[str, list] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        if args.get("hlo_op") or args.get("hlo_module"):
            continue
        # TraceMe splits "fused:<stage>" at the colon and keeps the full
        # string only in args.long_name — prefer it over the event name
        name = str(args.get("long_name") or e.get("name", ""))
        if not name.startswith(FUSED_SCOPE_PREFIX):
            continue
        stage = name[len(FUSED_SCOPE_PREFIX):]
        ts = float(e.get("ts", 0.0))
        windows.setdefault(stage, []).append(
            (ts, ts + float(e.get("dur", 0.0)))
        )
    return windows


def _attribute_module(module: str, module_of: dict[str, str]) -> str | None:
    """Exact match first; then prefix match — XLA may suffix a module
    name per recompile (``jit_mdl_x_1.2``)."""
    model = module_of.get(module)
    if model is not None:
        return model
    for known, m in module_of.items():
        if module.startswith(known):
            return m
    # the channel's naming convention is self-describing even without
    # a mapping: jit_mdl_<name>_<version>
    if module.startswith("jit_mdl_"):
        stem = module[len("jit_mdl_"):].split(".", 1)[0]
        # strip the trailing _<version> segment
        if "_" in stem:
            return stem.rsplit("_", 1)[0]
    return None


def _attribute_windows(
    midpoints: list, windows: dict[str, list]
) -> str | None:
    """Majority vote of op-occurrence midpoints over the models' launch
    annotation windows; None when no midpoint lands in any window."""
    votes: dict[str, int] = {}
    for mid in midpoints:
        for model, spans in windows.items():
            if any(lo <= mid <= hi for lo, hi in spans):
                votes[model] = votes.get(model, 0) + 1
                break
    if not votes:
        return None
    return max(votes.items(), key=lambda kv: kv[1])[0]


def summarize_profile_dir(
    log_dir: str,
    hlo_modules: dict | None = None,
    top_k: int = 0,
) -> dict:
    """End-to-end: find the capture's trace file, parse, summarize.
    Raises ``FileNotFoundError`` when the directory holds no trace —
    callers on the serving path catch and degrade."""
    path = find_trace_file(log_dir)
    if path is None:
        raise FileNotFoundError(f"no .trace.json(.gz) under {log_dir}")
    summary = summarize(
        load_trace(path), hlo_modules=hlo_modules, top_k=top_k
    )
    summary["trace_file"] = path
    return summary
