"""Request-scoped spans through the overlapped serving pipeline.

A ``RequestTrace`` is a flat, thread-safe list of named
``(t0, t1)`` intervals on the ``time.perf_counter`` clock — one trace
per served request, carried on ``InferRequest.trace`` through the
server, the batcher and the channel. Call sites guard on the attribute
(``tr = request.trace; if tr is not None: ...``), so the un-traced hot
path costs one attribute read per phase and allocates nothing.

Spans deliberately do NOT form a tree: the overlapped pipeline runs a
request's phases on several threads (gRPC handler, batch dispatcher,
executor), and what tail-latency attribution needs is the wall-clock
interval of each phase, not a call stack. Nesting falls out of
interval containment in the Chrome trace view (``stage`` contains
``slot_wait``; the request row contains everything).

``Tracer`` owns the bounded ring buffer of recently finished traces
and the Chrome-trace JSON export (``chrome_trace``) that Perfetto /
``chrome://tracing`` load directly; finished spans also feed the
per-stage Prometheus histogram family through the attached
StageProfiler (stage label ``span_<name>``).
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import json
import threading
import time
import uuid
from typing import Iterator


class TraceContext:
    """W3C-traceparent-style distributed context.

    ``trace_id`` (32 hex chars) names the end-to-end request across
    processes; ``parent_span_id`` (16 hex chars) names the hop that
    issued this RPC (the router attempt, or the originating client);
    ``sampled`` rides the flags byte. The wire form is the traceparent
    string ``00-<trace_id>-<parent_span_id>-<flags>`` carried in the
    kserve request ``parameters`` map — the same map the server already
    reads ``priority`` from, so propagation adds no new proto surface.

    Encode/decode are pure host-side string work (they sit on the
    serving hot path and are rooted in tpulint's HOT_PATH_ROOTS — no
    host syncs may creep in here).
    """

    __slots__ = ("trace_id", "parent_span_id", "sampled")

    #: kserve parameters key the context travels under
    PARAM_KEY = "traceparent"
    _VERSION = "00"

    def __init__(
        self, trace_id: str, parent_span_id: str, sampled: bool = True
    ) -> None:
        self.trace_id = trace_id
        self.parent_span_id = parent_span_id
        self.sampled = bool(sampled)

    @classmethod
    def new(cls, sampled: bool = True) -> "TraceContext":
        """Originate a fresh context (the router's front-door role)."""
        return cls(uuid.uuid4().hex, uuid.uuid4().hex[:16], sampled)

    def child(self) -> "TraceContext":
        """Same trace, fresh parent span id — one per hedge/retry
        attempt, so sibling attempts are distinguishable server-side."""
        return TraceContext(self.trace_id, uuid.uuid4().hex[:16], self.sampled)

    def encode(self) -> str:
        flags = "01" if self.sampled else "00"
        return f"{self._VERSION}-{self.trace_id}-{self.parent_span_id}-{flags}"

    @classmethod
    def decode(cls, value: str) -> "TraceContext | None":
        """Tolerant parse: anything malformed returns None (a foreign
        or corrupt header must never fail the request it rides on)."""
        if not value or not isinstance(value, str):
            return None
        parts = value.split("-")
        if len(parts) != 4 or not parts[1] or not parts[2]:
            return None
        return cls(parts[1], parts[2], sampled=parts[3] != "00")

    def __repr__(self) -> str:
        return f"TraceContext({self.encode()!r})"


class Span:
    """One named wall-clock interval on the perf_counter clock.

    ``attrs`` (optional dict) carries structured tags — the router
    stamps attempt number / endpoint / cancelled on its per-attempt
    spans and the Chrome export surfaces them as event ``args``."""

    __slots__ = ("name", "t0", "t1", "attrs")

    def __init__(
        self, name: str, t0: float, t1: float, attrs: dict | None = None
    ) -> None:
        self.name = name
        self.t0 = t0
        self.t1 = t1
        self.attrs = attrs

    @property
    def duration_s(self) -> float:
        return self.t1 - self.t0

    def __repr__(self) -> str:  # test/debug ergonomics
        return f"Span({self.name!r}, {self.duration_s * 1e3:.3f} ms)"


class RequestTrace:
    """Spans for one request. Append-only, safe from any thread.

    ``begin(name)`` / ``end(name)`` open and close a span across
    threads (the batcher opens ``batch_queue`` on the gRPC handler
    thread and closes it on the executor); ``end`` without a matching
    ``begin`` is a no-op, and a span left open when the trace finishes
    is dropped — observability must never fail the observed path.
    """

    __slots__ = (
        "trace_id",
        "model",
        "request_id",
        "t_start",
        "t_end",
        "status",
        "spans",
        "context",
        "_open",
        "_lock",
    )

    def __init__(
        self,
        trace_id: int,
        model: str = "",
        request_id: str = "",
        context: TraceContext | None = None,
    ) -> None:
        self.trace_id = trace_id
        self.model = model
        self.request_id = request_id
        self.t_start = time.perf_counter()
        self.t_end: float | None = None
        self.status = "ok"
        self.spans: list[Span] = []
        # distributed context (TraceContext): None on purely local
        # traces; set when the server adopts an inbound traceparent or
        # the router originates one. The local int trace_id still keys
        # the ring buffer — the context's hex trace_id keys the FLEET.
        self.context = context
        self._open: dict[str, float] = {}
        self._lock = threading.Lock()

    # -- recording ------------------------------------------------------------

    def add(
        self, name: str, t0: float, t1: float, attrs: dict | None = None
    ) -> None:
        with self._lock:
            self.spans.append(Span(name, t0, t1, attrs))

    @contextlib.contextmanager
    def span(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, t0, time.perf_counter())

    def begin(self, name: str) -> None:
        with self._lock:
            self._open[name] = time.perf_counter()

    def end(self, name: str) -> None:
        t1 = time.perf_counter()
        with self._lock:
            t0 = self._open.pop(name, None)
            if t0 is not None:
                self.spans.append(Span(name, t0, t1))

    # -- reading --------------------------------------------------------------

    def wall_s(self) -> float:
        end = self.t_end if self.t_end is not None else time.perf_counter()
        return end - self.t_start

    def span_coverage(self) -> float:
        """Fraction of [t_start, t_end] covered by the union of spans —
        the acceptance gauge for 'no invisible time in the pipeline'."""
        wall = self.wall_s()
        if wall <= 0:
            return 1.0
        with self._lock:
            ivals = sorted((s.t0, s.t1) for s in self.spans)
        covered, cur0, cur1 = 0.0, None, None
        for t0, t1 in ivals:
            if cur1 is None or t0 > cur1:
                if cur1 is not None:
                    covered += cur1 - cur0
                cur0, cur1 = t0, t1
            else:
                cur1 = max(cur1, t1)
        if cur1 is not None:
            covered += cur1 - cur0
        return min(1.0, covered / wall)

    def summary(self) -> dict:
        with self._lock:
            spans = [
                {
                    "name": s.name,
                    "t0_s": s.t0 - self.t_start,
                    "dur_ms": s.duration_s * 1e3,
                    **({"attrs": s.attrs} if s.attrs else {}),
                }
                for s in sorted(self.spans, key=lambda s: s.t0)
            ]
        out = {
            "trace_id": self.trace_id,
            "model": self.model,
            "request_id": self.request_id,
            "status": self.status,
            "wall_ms": self.wall_s() * 1e3,
            "spans": spans,
        }
        if self.context is not None:
            out["context"] = self.context.encode()
        return out


class MultiTrace:
    """Fan-out proxy for merged device batches.

    The batcher concatenates N requests into one inner-channel call;
    the merged InferRequest carries a MultiTrace over the members'
    traces, so channel-side spans (stage/launch/device_execute/
    readback) land on EVERY member — each request's trace shows the
    shared device work it rode on."""

    __slots__ = ("members",)

    def __init__(self, members) -> None:
        self.members = [m for m in members if m is not None]

    def add(self, name: str, t0: float, t1: float) -> None:
        for m in self.members:
            m.add(name, t0, t1)

    @contextlib.contextmanager
    def span(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            t1 = time.perf_counter()
            for m in self.members:
                m.add(name, t0, t1)

    def begin(self, name: str) -> None:
        for m in self.members:
            m.begin(name)

    def end(self, name: str) -> None:
        for m in self.members:
            m.end(name)


class Tracer:
    """Trace factory + bounded ring buffer of finished request traces.

    ``enabled=False`` makes ``start`` return None, which propagates the
    zero-cost path through every call site. ``profiler`` (a
    StageProfiler) receives each finished span as a ``span_<name>``
    stage sample, which the Prometheus stage-histogram family exports —
    per-stage span histograms under the existing ``stage`` label.

    ``histograms`` (an obs.histogram.HistogramFamily) additionally
    receives per-model SLO-stage samples at finish: each span named in
    ``SLO_STAGES`` lands as (model, stage), and the whole request wall
    lands as (model, "e2e") — the single feed point for the
    ``tpu_serving_latency_seconds`` family, riding the spans the
    pipeline already records instead of new instrumentation.
    """

    def __init__(
        self,
        enabled: bool = True,
        capacity: int = 256,
        profiler=None,
        histograms=None,
    ) -> None:
        self.enabled = bool(enabled) and capacity > 0
        self.capacity = int(capacity)
        self._profiler = profiler
        self._histograms = histograms
        self._ring: collections.deque[RequestTrace] = collections.deque(
            maxlen=max(1, self.capacity)
        )
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._finished = 0

    def start(
        self,
        model: str = "",
        request_id: str = "",
        context: TraceContext | None = None,
    ) -> RequestTrace | None:
        """``context``: inbound distributed context to adopt (the
        server's _issue passes the decoded traceparent; the router
        passes the context it originated)."""
        if not self.enabled:
            return None
        return RequestTrace(
            next(self._ids), model=model, request_id=request_id,
            context=context,
        )

    def finish(self, trace: RequestTrace | None, status: str = "ok") -> None:
        if trace is None:
            return
        trace.t_end = time.perf_counter()
        trace.status = status
        with self._lock:
            self._ring.append(trace)
            self._finished += 1
        if self._profiler is not None:
            for s in list(trace.spans):
                self._profiler.record(f"span_{s.name}", s.duration_s)
        if self._histograms is not None:
            from triton_client_tpu.obs.histogram import SLO_STAGES

            model = trace.model or ""
            for s in list(trace.spans):
                stage = SLO_STAGES.get(s.name)
                if stage is not None:
                    self._histograms.observe(model, stage, s.duration_s)
            self._histograms.observe(
                model, "e2e", trace.t_end - trace.t_start
            )

    def recent(self, n: int = 0) -> list[RequestTrace]:
        """Most recent ``n`` finished traces (0 = everything buffered),
        oldest first."""
        with self._lock:
            traces = list(self._ring)
        return traces[-n:] if n else traces

    def stats(self) -> dict:
        with self._lock:
            return {
                "finished": self._finished,
                "buffered": len(self._ring),
                "capacity": self.capacity,
            }

    def chrome_trace(self, n: int = 0) -> dict:
        return chrome_trace(self.recent(n))


def chrome_trace(traces) -> dict:
    """Chrome-trace ('Trace Event Format') JSON for a list of traces.

    Loadable in Perfetto / chrome://tracing: complete ('X') events with
    microsecond timestamps, one tid (row) per request, the whole
    request as a parent event so the per-phase spans nest visually
    inside it. Timestamps rebase onto the earliest trace start so the
    viewer opens at t=0."""
    traces = [t for t in traces if t is not None]
    if not traces:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    base = min(t.t_start for t in traces)

    def us(t: float) -> float:
        return round((t - base) * 1e6, 3)

    events: list[dict] = [
        {
            "ph": "M",
            "name": "process_name",
            "pid": 1,
            "tid": 0,
            "args": {"name": "tpu_serving"},
        }
    ]
    for tr in traces:
        tid = tr.trace_id
        label = f"req {tr.trace_id} {tr.model}".strip()
        events.append(
            {
                "ph": "M",
                "name": "thread_name",
                "pid": 1,
                "tid": tid,
                "args": {"name": label},
            }
        )
        t_end = tr.t_end if tr.t_end is not None else time.perf_counter()
        req_args = {
            "model": tr.model,
            "request_id": tr.request_id,
            "status": tr.status,
        }
        ctx = getattr(tr, "context", None)
        if ctx is not None:
            req_args["traceparent"] = ctx.encode()
        events.append(
            {
                "ph": "X",
                "name": "request",
                "cat": "request",
                "pid": 1,
                "tid": tid,
                "ts": us(tr.t_start),
                "dur": max(0.0, (t_end - tr.t_start) * 1e6),
                "args": req_args,
            }
        )
        for s in sorted(tr.spans, key=lambda s: s.t0):
            ev = {
                "ph": "X",
                "name": s.name,
                "cat": "span",
                "pid": 1,
                "tid": tid,
                "ts": us(s.t0),
                "dur": max(0.0, s.duration_s * 1e6),
            }
            if s.attrs:
                ev["args"] = dict(s.attrs)
            events.append(ev)
    events.sort(key=lambda e: (e.get("ts", -1.0), e["tid"]))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def dump_chrome_trace(traces, path: str) -> None:
    with open(path, "w") as f:
        json.dump(chrome_trace(traces), f)


# -- cross-process span summaries ---------------------------------------------

#: kserve response parameters key the server's span summary rides under
SUMMARY_PARAM_KEY = "trace_summary"


def encode_span_summary(trace: RequestTrace) -> str:
    """Compact server-side summary for the response ``parameters`` map.

    Times are microseconds RELATIVE to the trace's own t_start (each
    process has its own perf_counter epoch — absolute values would be
    meaningless on the far side): ``{"w": wall_us, "st": status,
    "s": [[name, t0_rel_us, dur_us], ...]}``. Kept deliberately terse:
    this string rides every traced response."""
    t_start = trace.t_start
    with trace._lock:
        spans = [
            [s.name, round((s.t0 - t_start) * 1e6), round(s.duration_s * 1e6)]
            for s in sorted(trace.spans, key=lambda s: s.t0)
        ]
    doc = {
        "w": round(trace.wall_s() * 1e6),
        "st": trace.status,
        "s": spans,
    }
    if trace.context is not None:
        doc["ctx"] = trace.context.encode()
    return json.dumps(doc, separators=(",", ":"))


def decode_span_summary(value: str) -> dict | None:
    """Tolerant inverse of encode_span_summary (None on garbage)."""
    if not value:
        return None
    try:
        doc = json.loads(value)
    except (ValueError, TypeError):
        return None
    if not isinstance(doc, dict) or "s" not in doc or "w" not in doc:
        return None
    return doc


def graft_span_summary(
    trace: RequestTrace,
    summary: dict,
    t_sent: float,
    t_recv: float,
    prefix: str = "srv.",
    attrs: dict | None = None,
) -> None:
    """Place a far-side span summary onto the LOCAL clock.

    The caller observed the RPC as [t_sent, t_recv] on its own
    perf_counter clock; the summary says the server spent ``w``
    microseconds of wall inside that window. The residue is wire +
    router transit — split symmetrically (the same midpoint estimate
    NTP uses for a single round trip), which also yields the clock
    offset the trace-join CLI applies. Server spans land prefixed
    (default ``srv.``) so local and remote phases stay distinguishable
    in one timeline; the wire residue lands as ``wire_send`` /
    ``wire_recv`` spans so the RTT of ROADMAP item 1 is a NAMED span."""
    rtt = max(0.0, t_recv - t_sent)
    server_wall = max(0.0, summary.get("w", 0) / 1e6)
    residue = max(0.0, rtt - server_wall)
    t_server_start = t_sent + residue / 2.0
    if residue > 0:
        trace.add("wire_send", t_sent, t_server_start, attrs)
        trace.add(
            "wire_recv", t_server_start + server_wall, t_recv, attrs
        )
    for row in summary.get("s", ()):
        try:
            name, t0_us, dur_us = row[0], float(row[1]), float(row[2])
        except (IndexError, TypeError, ValueError):
            continue
        t0 = t_server_start + t0_us / 1e6
        trace.add(f"{prefix}{name}", t0, t0 + dur_us / 1e6, attrs)
