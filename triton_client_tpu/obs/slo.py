"""SLO attainment accounting + tail-exemplar sampling.

The tentpole's deadline plane: the server stamps each request's
deadline at admission (``_Servicer._issue``), the batcher and staged
launchers carry it, and THIS module scores the outcome once per
request — on every exit path, success or failure — in the same
``finally``-rooted accounting hook that already feeds the error
counter (tpulint TPL503 enforces that placement).

Three jobs:

  * **attainment counters** — met/missed per (model, priority), read
    through ``RuntimeCollector.snapshot()["slo"]`` and exported as the
    ``tpu_serving_slo_requests_total`` counter family. A request with
    no deadline and no configured budget is not scored (an SLO-less
    server must not report 100% attainment as if it had one).
  * **tail sampler** — a bounded ring of full ``RequestTrace``
    exemplars, retained ONLY for requests that missed their SLO or
    landed at/above the live p99 of their model's e2e histogram. The
    main tracer ring keeps the last N requests regardless; this ring
    answers "show me the slow ones" after millions of fast requests
    have cycled the main ring. Exported at ``/traces?slo_violations=1``.
  * **per-model budgets** — ``slo_ms`` is the default; ``per_model``
    overrides individual models (capacity search probes one model's
    budget without touching its neighbors').
"""

from __future__ import annotations

import collections
import threading
import time

# Don't trust a p99 estimated from a handful of samples: below this
# many e2e observations the tail sampler retains only hard SLO misses.
_MIN_P99_SAMPLES = 100


class SLOTracker:
    """Scores one finished request per ``observe_request`` call."""

    def __init__(
        self,
        slo_ms: float = 0.0,
        per_model: dict[str, float] | None = None,
        tail_capacity: int = 64,
        histograms=None,
    ) -> None:
        """``slo_ms``: default latency budget (0 = no SLO configured —
        requests are scored only when they carry an explicit deadline).
        ``per_model``: model name -> budget ms overrides.
        ``histograms``: the serving ``HistogramFamily``; when present,
        its live (model, e2e) p99 also qualifies traces for the tail
        ring, so the sampler keeps exemplars even on a server whose SLO
        is generous enough to never miss."""
        self._slo_s = max(0.0, float(slo_ms)) / 1e3
        self._per_model_s = {
            str(m): max(0.0, float(v)) / 1e3
            for m, v in (per_model or {}).items()
        }
        self._hist = histograms
        self._lock = threading.Lock()
        # (model, priority) -> [met, missed]
        self._counts: dict[tuple[str, int], list[int]] = {}
        self._tail: collections.deque = collections.deque(
            maxlen=max(1, int(tail_capacity))
        )
        self._tail_retained = 0
        self._deadline_missed = 0

    # -- configuration --------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._slo_s > 0 or bool(self._per_model_s)

    def slo_s(self, model: str) -> float:
        """The latency budget for ``model`` in seconds (0 = none)."""
        return self._per_model_s.get(str(model), self._slo_s)

    def set_budget(self, slo_ms: float, model: str | None = None) -> None:
        """Re-arm the default (or one model's) budget on a live
        tracker — how a calibration pass (perf/profile_slo.py auto-SLO:
        3x the lightly-loaded p50) turns scoring on after the server is
        already taking traffic. Already-scored requests keep their
        original verdicts; only future admissions see the new budget."""
        v = max(0.0, float(slo_ms)) / 1e3
        if model is None:
            self._slo_s = v
        else:
            self._per_model_s[str(model)] = v

    def deadline_for(self, model: str, t0: float) -> float | None:
        """Absolute perf_counter deadline for a request admitted at
        ``t0`` — what ``_Servicer._issue`` stamps onto the
        InferRequest; None when the model has no budget."""
        budget = self.slo_s(model)
        return t0 + budget if budget > 0 else None

    # -- scoring --------------------------------------------------------------

    def observe_request(
        self,
        model: str,
        wall_s: float,
        deadline_s: float | None = None,
        priority: int = 0,
        status: str = "ok",
        trace=None,
        now: float | None = None,
    ) -> None:
        """Score one finished request. ``deadline_s`` is the absolute
        perf_counter deadline stamped at admission (authoritative when
        present — it survives clock-relative drift through the batcher);
        otherwise the model's budget is compared against ``wall_s``.
        Failed requests (``status != "ok"``) count as missed: a served
        error inside budget is not an attained SLO."""
        budget = self.slo_s(model)
        if deadline_s is None and budget <= 0:
            # no SLO anywhere for this request: still feed the tail
            # sampler's p99 criterion, but never the attainment counters
            self._maybe_retain(model, wall_s, missed=False, trace=trace)
            return
        if now is None:
            now = time.perf_counter()
        if deadline_s is not None:
            missed = now > deadline_s
        else:
            missed = wall_s > budget
        if status != "ok":
            missed = True
        key = (str(model), int(priority))
        with self._lock:
            cell = self._counts.get(key)
            if cell is None:
                cell = self._counts[key] = [0, 0]
            cell[1 if missed else 0] += 1
            if missed:
                self._deadline_missed += 1
        self._maybe_retain(model, wall_s, missed=missed, trace=trace)

    def _maybe_retain(self, model, wall_s, missed, trace) -> None:
        if trace is None:
            return
        keep = missed
        if not keep and self._hist is not None:
            try:
                if (
                    self._hist.count(model, "e2e") >= _MIN_P99_SAMPLES
                    and wall_s >= self._hist.quantile(model, "e2e", 0.99)
                ):
                    keep = True
            except Exception:
                keep = False  # observability must never fail the path
        if keep:
            with self._lock:
                self._tail.append(trace)
                self._tail_retained += 1

    # -- reading --------------------------------------------------------------

    def violations(self, n: int = 0) -> list:
        """Most recent ``n`` retained exemplar traces (0 = all
        buffered), oldest first — the ``/traces?slo_violations=1``
        payload."""
        with self._lock:
            traces = list(self._tail)
        return traces[-n:] if n else traces

    def stats(self) -> dict:
        """Numeric-leaved dict for ``RuntimeCollector.snapshot()`` —
        attainment counts keyed ``"model|priority"``, like the error
        counter's ``"model|code"`` keys, so ``delta()`` windows it."""
        with self._lock:
            by_key = {
                f"{m}|{p}": {"met": c[0], "missed": c[1]}
                for (m, p), c in sorted(self._counts.items())
            }
            met = sum(c[0] for c in self._counts.values())
            missed = sum(c[1] for c in self._counts.values())
            return {
                "slo_ms": self._slo_s * 1e3,
                "met": met,
                "missed": missed,
                "requests": by_key,
                "tail_buffered": len(self._tail),
                "tail_retained": self._tail_retained,
            }

    def attainment(self) -> float:
        """Fraction of scored requests that met their SLO (1.0 when
        nothing has been scored yet)."""
        with self._lock:
            met = sum(c[0] for c in self._counts.values())
            total = met + sum(c[1] for c in self._counts.values())
        return met / total if total else 1.0
