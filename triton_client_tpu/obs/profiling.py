"""Per-stage latency profiling + device tracing (SURVEY.md §5 gap).

Lives in ``obs/`` since ISSUE 11 so the repo has ONE timing substrate:
the request-scoped tracer (obs/trace.py) feeds its finished spans into
a StageProfiler from this module, and the drivers/CLIs record their
pipeline stages into the same reservoir. (The ``utils/profiling.py``
deprecation shim has been removed — import from here.)

The reference has NO tracer — only commented-out ``time.time()`` pairs
around the 3D callback (ros_inference3d.py:122,209-210) and print-based
stage timing in the legacy postprocess (tools/utils.py:179-231). This
module is the first-class replacement:

- ``StageProfiler``: thread-safe rolling reservoir of wall-clock
  durations per named stage -> p50/p95/p99/mean/count snapshots.
- ``profiled(profiler, stage)``: context manager / function wrapper.
- ``device_trace``: jax.profiler trace context (XLA + TPU timeline,
  viewable in TensorBoard/Perfetto) for the on-device view host timers
  can't see.
- ``PrometheusStageExporter``: per-stage Histograms on a metrics port —
  the serving-side analogue of Triton's :8002 endpoint the reference
  scrapes (data/prometheus.yml:26-29).
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, Iterator

import numpy as np

_QUANTILES = (50.0, 95.0, 99.0)


class StageProfiler:
    """Rolling per-stage duration reservoir.

    Keeps the most recent ``window`` samples per stage (enough for
    stable tail quantiles at camera rates without unbounded memory over
    long-running serving processes).
    """

    def __init__(self, window: int = 4096) -> None:
        import collections

        self._window = int(window)
        self._lock = threading.Lock()
        # deque(maxlen=...) evicts in O(1); a list's front-deletion would
        # memmove the whole window on every sample in the serving path.
        self._stages: dict[str, "collections.deque[float]"] = {}
        self._deque = collections.deque
        self._counts: dict[str, int] = {}
        self._listeners: list[Callable[[str, float], None]] = []

    def record(self, stage: str, seconds: float) -> None:
        with self._lock:
            buf = self._stages.get(stage)
            if buf is None:
                buf = self._stages[stage] = self._deque(maxlen=self._window)
            buf.append(float(seconds))
            self._counts[stage] = self._counts.get(stage, 0) + 1
            listeners = list(self._listeners)
        for listener in listeners:
            try:
                listener(stage, seconds)
            except Exception:  # noqa: BLE001 — observability must never
                # fail the observed path (e.g. a gRPC request)
                import logging

                logging.getLogger(__name__).warning(
                    "profiler listener failed for stage %r", stage, exc_info=True
                )

    def add_listener(self, fn: Callable[[str, float], None]) -> None:
        """Observe every sample as it lands (Prometheus export hook)."""
        with self._lock:
            self._listeners.append(fn)

    @contextlib.contextmanager
    def stage(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, time.perf_counter() - t0)

    def wrap(self, name: str, fn: Callable) -> Callable:
        def wrapped(*args, **kwargs):
            with self.stage(name):
                return fn(*args, **kwargs)

        return wrapped

    def summary(self) -> dict[str, dict[str, float]]:
        """stage -> {count, mean_ms, p50_ms, p95_ms, p99_ms}."""
        with self._lock:
            stages = {k: np.asarray(v) for k, v in self._stages.items() if v}
            counts = dict(self._counts)
        out = {}
        for name, samples in stages.items():
            ms = samples * 1e3
            row = {"count": float(counts.get(name, len(samples)))}
            row["mean_ms"] = float(ms.mean())
            for q in _QUANTILES:
                row[f"p{int(q)}_ms"] = float(np.percentile(ms, q))
            out[name] = row
        return out

    def report(self) -> str:
        """Human-readable per-stage table (driver end-of-run print)."""
        rows = self.summary()
        if not rows:
            return "(no stage samples)"
        width = max(len(n) for n in rows)
        lines = [
            f"{'stage'.ljust(width)}  count    mean    p50    p95    p99  (ms)"
        ]
        for name, r in sorted(rows.items()):
            lines.append(
                f"{name.ljust(width)}  {int(r['count']):5d}  "
                f"{r['mean_ms']:6.2f} {r['p50_ms']:6.2f} "
                f"{r['p95_ms']:6.2f} {r['p99_ms']:6.2f}"
            )
        return "\n".join(lines)


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """jax.profiler trace window: captures XLA compilation + TPU device
    timeline into ``log_dir`` (open with TensorBoard's profile plugin or
    Perfetto). Complements StageProfiler: host timers see walls, this
    sees what the chip did inside them."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region inside a device trace (jax.profiler.TraceAnnotation)
    — shows host-side spans alongside device ops in the timeline."""
    import jax

    return jax.profiler.TraceAnnotation(name)


# Latency buckets (seconds) tuned for camera-rate serving: 1 ms .. 10 s.
_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
    5.0, 10.0,
)


class PrometheusStageExporter:
    """Per-stage latency Histograms + request counter on a metrics port.

    The serving-side analogue of the Triton metrics endpoint the
    reference scrapes on :8002 (README.md:88-95, data/prometheus.yml).
    Import-gated like the reference's degraded-feature pattern
    (communicator/__init__.py:5-8).

    One histogram FAMILY with a ``stage`` label (round 4; was one
    metric name per stage): rate()/histogram_quantile() drop
    ``__name__``, so name-encoded stages could not be grouped in
    PromQL without recording rules — the label design is also how
    Triton's own nv_inference_* metrics carry the model. The serving
    stage label is ``infer_<model>``, matching the profiler's stage
    naming (runtime/server.py _infer); request traces land as
    ``span_<name>`` stages through obs.Tracer.

    ``registry``: the prometheus CollectorRegistry to export into
    (default the process-global ``prometheus_client.REGISTRY``). A
    second exporter on the same (registry, namespace) reuses the
    already-registered family instead of degrading to a no-op, so
    tests and multi-server processes can each export; pass each server
    its own registry for fully independent series.
    """

    # (registry -> {family name -> Histogram}): a second exporter on
    # the same registry records into the SAME family rather than
    # hitting prometheus's duplicate-registration ValueError and
    # silently recording nothing (the pre-telemetry failure mode).
    _family_cache = None
    _family_cache_lock = threading.Lock()

    def __init__(
        self,
        port: int = 8002,
        namespace: str = "tpu_serving",
        registry=None,
    ) -> None:
        import weakref

        import prometheus_client

        if registry is None:
            registry = prometheus_client.REGISTRY
        self._lock = threading.Lock()
        self._label_sources: dict[str, str] = {}
        self._warned: set[tuple[str, str]] = set()
        name = f"{namespace}_stage_latency_seconds"
        cls = type(self)
        with cls._family_cache_lock:
            if cls._family_cache is None:
                cls._family_cache = weakref.WeakKeyDictionary()
            per_registry = cls._family_cache.setdefault(registry, {})
            family = per_registry.get(name)
            if family is None:
                try:
                    family = prometheus_client.Histogram(
                        name,
                        "wall-clock latency per pipeline/serving stage",
                        labelnames=("stage",),
                        buckets=_BUCKETS,
                        registry=registry,
                    )
                    per_registry[name] = family
                except ValueError:
                    # the name is taken by a collector we did not
                    # create and cannot reuse: export nothing rather
                    # than poison the record path
                    import logging

                    logging.getLogger(__name__).warning(
                        "metric family %s already registered by a "
                        "foreign collector; this exporter records "
                        "nothing", name,
                    )
                    family = None
        self._family = family
        if port:
            prometheus_client.start_http_server(port, registry=registry)

    def observe(self, stage: str, seconds: float) -> None:
        if self._family is None:
            return
        safe = "".join(c if c.isalnum() else "_" for c in stage)
        collision = None
        with self._lock:
            # two distinct stage names sanitizing to one label value
            # ('a.b' and 'a_b') would silently merge their series —
            # warn once per colliding PAIR (the first-seen source is
            # kept so alternating names cannot re-trigger every call)
            first = self._label_sources.setdefault(safe, stage)
            if first != stage and (safe, stage) not in self._warned:
                self._warned.add((safe, stage))
                collision = first
            child = self._family.labels(stage=safe)
        if collision is not None:
            import logging

            logging.getLogger(__name__).warning(
                "stage label %r now receives both %r and %r — series "
                "merged", safe, collision, stage,
            )
        child.observe(seconds)

    def attach(self, profiler: StageProfiler) -> "PrometheusStageExporter":
        profiler.add_listener(self.observe)
        return self
