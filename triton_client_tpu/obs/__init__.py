"""Request-scoped serving telemetry (the observability subsystem).

The reference's operator story is Triton's ``nv_inference_*`` counters
scraped into Grafana (README.md:88-109). Our serving plane does far
more than a request counter can describe — the overlapped dispatch
path (channel/tpu_channel.py) decomposes a request's wall latency into
queue wait, batch formation, H2D staging, device execute and deferred
readback — so this package makes that decomposition first-class:

- ``trace``     — per-request spans (trace-id, monotonic clock,
  ~zero-cost when disabled), a bounded ring buffer of recent request
  traces, and Chrome-trace/Perfetto JSON export.
- ``collector`` — bridges the in-process ``stats()`` dicts of
  TPUChannel and BatchingChannel, HBM ``memory_stats()`` and jit
  compile events into Prometheus gauges/counters, with a ``snapshot()``
  API so perf scripts and production read identical numbers.
- ``http``      — one HTTP endpoint on the metrics port serving
  ``/metrics`` (Prometheus exposition), ``/traces`` (Chrome trace
  JSON) and ``/snapshot`` (raw collector stats).
"""

from triton_client_tpu.obs.trace import (
    MultiTrace,
    RequestTrace,
    Span,
    Tracer,
    chrome_trace,
)
from triton_client_tpu.obs.collector import (
    METRIC_TYPES,
    CompileEvents,
    RuntimeCollector,
)
from triton_client_tpu.obs.histogram import (
    DEFAULT_BUCKETS,
    SLO_STAGES,
    HistogramFamily,
    LatencyHistogram,
    quantile_from_snapshot,
)
from triton_client_tpu.obs.http import TelemetryServer
from triton_client_tpu.obs.slo import SLOTracker

__all__ = [
    "DEFAULT_BUCKETS",
    "METRIC_TYPES",
    "SLO_STAGES",
    "CompileEvents",
    "HistogramFamily",
    "LatencyHistogram",
    "MultiTrace",
    "RequestTrace",
    "RuntimeCollector",
    "SLOTracker",
    "Span",
    "TelemetryServer",
    "Tracer",
    "chrome_trace",
    "quantile_from_snapshot",
]
