"""Continuous op-level sampling: short profiler windows on a budget.

One `/profile` capture answers "what ran during THAT second"; serving
regressions ask "what runs all day". The ContinuousSampler takes a
short jax.profiler capture window every ``interval_s`` seconds, parses
it with obs/opstats.py, and feeds the top-K op device-time rows into
the RuntimeCollector — so ``tpu_serving_op_device_seconds{model,op}``
is a standing Prometheus series instead of a one-off curl.

Overhead is bounded structurally: the duty cycle
``window_s / interval_s`` is clamped to :data:`MAX_DUTY_CYCLE` (<1% of
wall time inside a capture) at construction, and the sampler runs
through the SAME process-global capture guard as ``/profile`` —
jax.profiler keeps one global trace, so an operator capture and the
sampler must never overlap. When the guard is busy the sampler skips
the tick and counts it (``skipped_busy``), exactly the 409 a second
``/profile`` caller gets.

The capture directory is deleted after parsing: at one capture every
30s a serving process would otherwise leak ~3 GB of trace files a day.
"""

from __future__ import annotations

import logging
import shutil
import tempfile
import threading
import time

log = logging.getLogger(__name__)

#: hard ceiling on window_s / interval_s — the <1% throughput budget
MAX_DUTY_CYCLE = 0.01


class ContinuousSampler:
    """Background profiler sampling loop.

    ``sink``: anything answering ``record_op_sample(rows, window_s)``
    (the RuntimeCollector). ``hlo_modules``: zero-arg callable
    returning the live ``{hlo_module: model}`` mapping (read per tick —
    models register/evict at runtime). ``lock``: the shared capture
    guard (TelemetryServer.profile_lock); a private lock is made when
    the telemetry endpoint is absent.

    The thread only starts on :meth:`start`; tests drive
    :meth:`sample_once` directly for determinism.
    """

    def __init__(
        self,
        sink=None,
        interval_s: float = 30.0,
        window_s: float = 0.2,
        top_k: int = 10,
        lock: threading.Lock | None = None,
        hlo_modules=None,
    ) -> None:
        self.interval_s = max(1.0, float(interval_s))
        # clamp the window so the duty cycle can never exceed budget,
        # whatever knob combination the caller passed
        self.window_s = min(
            max(0.01, float(window_s)), self.interval_s * MAX_DUTY_CYCLE
        )
        self.top_k = max(1, int(top_k))
        self._sink = sink
        self._lock = lock if lock is not None else threading.Lock()
        self._hlo_modules = hlo_modules
        self._stats_lock = threading.Lock()
        self._captures = 0
        self._skipped_busy = 0
        self._failures = 0
        self._capture_seconds = 0.0
        self._started = time.perf_counter()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @property
    def duty_cycle(self) -> float:
        """Configured capture share of wall time (<= MAX_DUTY_CYCLE)."""
        return self.window_s / self.interval_s

    # -- one sample (the unit tests drive this directly) ----------------------

    def sample_once(self) -> dict | None:
        """Take one capture window now. Returns the opstats summary, or
        None when the capture guard was busy / jax is unavailable /
        the capture failed (each outcome counted in stats())."""
        try:
            import jax
        except ImportError:
            with self._stats_lock:
                self._failures += 1
            return None
        if not self._lock.acquire(blocking=False):
            # an operator /profile (or a concurrent tick) owns the
            # process-global trace: skip, never queue — a late capture
            # is worthless and a queued one doubles the duty cycle
            with self._stats_lock:
                self._skipped_busy += 1
            return None
        log_dir = None
        t0 = time.perf_counter()
        try:
            log_dir = tempfile.mkdtemp(prefix="tpu_serving_sample_")
            jax.profiler.start_trace(log_dir)
            try:
                time.sleep(self.window_s)
            finally:
                jax.profiler.stop_trace()
            from triton_client_tpu.obs import opstats

            modules = {}
            if self._hlo_modules is not None:
                try:
                    modules = self._hlo_modules() or {}
                except Exception:
                    modules = {}
            summary = opstats.summarize_profile_dir(
                log_dir, hlo_modules=modules, top_k=self.top_k
            )
            with self._stats_lock:
                self._captures += 1
                self._capture_seconds += time.perf_counter() - t0
            if self._sink is not None:
                try:
                    self._sink.record_op_sample(
                        summary["ops"], self.window_s
                    )
                except Exception:
                    log.exception("op-sample sink failed")
            return summary
        except Exception:
            log.exception("continuous profiler sample failed")
            with self._stats_lock:
                self._failures += 1
                self._capture_seconds += time.perf_counter() - t0
            return None
        finally:
            self._lock.release()
            if log_dir is not None:
                shutil.rmtree(log_dir, ignore_errors=True)

    # -- background loop ------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="op-sampler", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        # first tick waits a full interval: a server's first seconds
        # are compile storms nobody wants in the standing sample
        while not self._stop.wait(self.interval_s):
            self.sample_once()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.window_s + 5.0)
            self._thread = None

    # -- introspection --------------------------------------------------------

    def stats(self) -> dict:
        with self._stats_lock:
            elapsed = max(time.perf_counter() - self._started, 1e-9)
            return {
                "interval_s": self.interval_s,
                "window_s": self.window_s,
                "duty_cycle": self.duty_cycle,
                "captures": self._captures,
                "skipped_busy": self._skipped_busy,
                "failures": self._failures,
                "capture_seconds": self._capture_seconds,
                "measured_duty_cycle": self._capture_seconds / elapsed,
            }
